"""Scalability benchmark suite.

Mirrors the reference's release scalability benchmarks
(release/benchmarks/{many_actors,many_pgs,many_tasks}.py and
release/nightly_tests/object_store — published numbers in
release/release_logs/2.0.0/{benchmarks,scalability}/) scaled to a
single-host run: the shapes are the same (actor churn, PG churn, task
fan-out across real agent processes, object broadcast, cross-node
bandwidth). Counts: 2,000 actors (reference: 10k multi-node), 10k tasks,
1,000 PGs, 1 GiB broadcast over 4 agents. Baselines below are the
reference's published rates, so ratios compare like-for-like where a
direct counterpart exists. Every row is the median of ``trials`` runs
with min/max recorded (single-trial rows made regressions
unfalsifiable), and head peak RSS is reported the way the reference's
many_actors records ``_peak_memory``.
"""

from __future__ import annotations

import time
from typing import Dict

# reference numbers (BASELINE.md scalability table)
SCALE_BASELINE = {
    "many_actors_per_s": 510.0,        # 10k actors, multi-node AWS
    "many_pgs_per_s": 16.9,            # 1k PGs, multi-node AWS
    "many_tasks_per_s": 27.6,          # 10k long tasks (scheduling rate)
    "broadcast_gbps": 0.65,            # 1 GiB to 50 nodes in 76.7s ~= 0.65 GB/s aggregate
    "cross_node_gbps": None,           # no direct reference row (p2p plane)
}


def _median_row(rates) -> Dict[str, float]:
    rates = sorted(rates)
    return {"median": rates[len(rates) // 2], "min": rates[0],
            "max": rates[-1], "trials": len(rates)}


def run_scale_curve(node_counts=(1, 2, 4, 8), per_node_cpus=2,
                    n_tasks=2000, n_actors=32, trials=3):
    """Throughput-vs-node-count curve over VIRTUAL in-process nodes.

    Each point boots a fresh runtime with ``rmt.init(num_nodes=n)`` (n
    node managers inside one head process, workers as real subprocesses)
    and measures task and actor-churn throughput. The curve watches the
    CONTROL plane: with the sharded directory, agent-local leaf
    scheduling and batched done replies, tasks/s must climb as nodes are
    added instead of plateauing at the head's single core. Tasks are
    submitted WITHOUT a scheduling strategy so they stay leaf-eligible
    and ride the per-node lease pools; actors use SPREAD so 0-CPU probes
    don't all pack onto node 0 and serialize on one fork path.

    Returns {nodes, many_tasks_per_s: {node_count: rate}, many_actors_per_s,
    tasks_scaling_1_to_4, actors_scaling_1_to_4, stats} with per-point
    median/min/max rows under ``stats`` (dict keys are strings so the
    structure survives a JSON round trip unchanged)."""
    import ray_memory_management_tpu as rmt

    import resource

    curve_nodes = list(node_counts)
    tasks_pts: Dict[str, float] = {}
    actors_pts: Dict[str, float] = {}
    rss_pts: Dict[str, float] = {}
    dir_p99_pts: Dict[str, float] = {}
    stats = {"many_tasks_per_s": {}, "many_actors_per_s": {}}
    for n in curve_nodes:
        rt = rmt.init(num_cpus=per_node_cpus, num_nodes=n,
                      object_store_memory=1 << 30)
        try:
            @rmt.remote(max_retries=0)
            def noop():
                return b"ok"

            @rmt.remote(num_cpus=0)
            class Probe:
                def ready(self):
                    return b"ok"

            # warm untimed: boot every node's workers and the fork path
            # once so the timed bursts measure steady state, not zygote
            # preload (same rationale as run_scale_suite's warm bursts)
            rmt.get([noop.remote() for _ in range(4 * n * per_node_cpus)],
                    timeout=300)
            warm = [Probe.options(scheduling_strategy="SPREAD").remote()
                    for _ in range(2 * n)]
            rmt.get([w.ready.remote() for w in warm], timeout=300)
            for w in warm:
                rmt.kill(w)
            time.sleep(0.5)

            rates = []
            for _ in range(trials):
                t0 = time.perf_counter()
                refs = [noop.remote() for _ in range(n_tasks)]
                rmt.get(refs, timeout=600)
                rates.append(n_tasks / (time.perf_counter() - t0))
                del refs
            stats["many_tasks_per_s"][str(n)] = _median_row(rates)
            tasks_pts[str(n)] = stats["many_tasks_per_s"][str(n)]["median"]

            def _workers_alive() -> int:
                return sum(len(nm.workers) for nm in rt.nodes.values())

            floor = _workers_alive()
            rates = []
            for _ in range(trials):
                t0 = time.perf_counter()
                actors = [Probe.options(
                    scheduling_strategy="SPREAD").remote()
                    for _ in range(n_actors)]
                rmt.get([a.ready.remote() for a in actors], timeout=600)
                rates.append(n_actors / (time.perf_counter() - t0))
                for a in actors:
                    rmt.kill(a)
                del actors
                # bounded drain so kill/reap cleanup doesn't bleed CPU
                # into the next timed burst
                deadline = time.monotonic() + 30.0
                while (_workers_alive() > floor
                       and time.monotonic() < deadline):
                    time.sleep(0.2)
                time.sleep(0.3)
            stats["many_actors_per_s"][str(n)] = _median_row(rates)
            actors_pts[str(n)] = stats["many_actors_per_s"][str(n)]["median"]

            # per-point head memory + directory-op tail: the control
            # plane's two scaling liabilities alongside its throughput
            # (pod_bench carries the same pair out to 256 sim nodes)
            rss_pts[str(n)] = round(
                resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024.0,
                1)
            import os as _os

            nid = next(iter(rt.nodes))
            durs = []
            for i in range(1000):
                oid = b"scalecurve" + i.to_bytes(4, "big") + _os.urandom(4)
                t0 = time.perf_counter()
                rt.gcs.add_object_location(oid, nid, size=64)
                rt.gcs.locate_objects([oid])
                rt.gcs.remove_object_location(oid, nid)
                durs.append((time.perf_counter() - t0) * 1e6)
            durs.sort()
            dir_p99_pts[str(n)] = round(durs[(len(durs) * 99) // 100], 1)
        finally:
            rmt.shutdown()

    out = {
        "nodes": curve_nodes,
        "many_tasks_per_s": {k: round(v, 1) for k, v in tasks_pts.items()},
        "many_actors_per_s": {k: round(v, 1) for k, v in actors_pts.items()},
        "head_peak_rss_mb": rss_pts,
        "dir_op_p99_us": dir_p99_pts,
        "stats": {m: {k: {kk: round(vv, 2) for kk, vv in row.items()}
                      for k, row in pts.items()}
                  for m, pts in stats.items()},
    }
    t1, t4 = tasks_pts.get("1"), tasks_pts.get("4")
    out["tasks_scaling_1_to_4"] = round(t4 / t1, 3) if t1 and t4 else None
    a1, a4 = actors_pts.get("1"), actors_pts.get("4")
    out["actors_scaling_1_to_4"] = round(a4 / a1, 3) if a1 and a4 else None
    return out


def run_scale_suite(n_actors: int = 2000, n_tasks: int = 10_000,
                    n_pgs: int = 1000, broadcast_mb: int = 1024,
                    n_agents: int = 4, trials: int = 3):
    """Run against a fresh runtime with ``n_agents`` real agent processes.
    Returns ({metric: median}, {metric: {median,min,max,trials}})."""
    import numpy as np

    import ray_memory_management_tpu as rmt
    from ray_memory_management_tpu.core.placement_group import (
        placement_group, remove_placement_group,
    )
    from ray_memory_management_tpu.core.scheduling_strategies import (
        NodeAffinitySchedulingStrategy,
    )

    results: Dict[str, float] = {}
    stats: Dict[str, Dict[str, float]] = {}
    rt = rmt.init(num_cpus=8, object_store_memory=3 << 30)
    try:
        agent_ids = [rt.add_remote_node_process(num_cpus=4)
                     for _ in range(n_agents)]

        # -- many actors: create + first call round-trip ---------------------
        @rmt.remote(num_cpus=0)
        class Probe:
            def ready(self):
                return b"ok"

        # warm every node's fork server and worker path once: the burst
        # measures steady-state creation, not one-time zygote preload
        warm = [Probe.options(
            scheduling_strategy=NodeAffinitySchedulingStrategy(
                node_id=nid, soft=False)).remote()
            for nid in agent_ids] + [Probe.remote()]
        rmt.get([w.ready.remote() for w in warm], timeout=300)
        for w in warm:
            rmt.kill(w)
        # ...then one untimed mini-burst: the first burst after boot also
        # pays one-time OS costs (page-cache faulting the worker import
        # tree for fork COW) that a 5-actor warm does not amortize —
        # measured 34/s -> ~100/s between the first and second bursts
        warm = [Probe.remote() for _ in range(64)]
        rmt.get([w.ready.remote() for w in warm], timeout=600)
        for w in warm:
            rmt.kill(w)
        time.sleep(1.0)

        from ray_memory_management_tpu.core import zygote

        z = zygote.peek_global()  # observer: never starts a fork server
        fork0 = (z.spawn_count, z.spawn_seconds) if z else (0, 0.0)
        boot0 = (sum(nm.boot_count for nm in rt.nodes.values()),
                 sum(nm.boot_seconds for nm in rt.nodes.values()))
        def _workers_alive() -> int:
            return sum(len(nm.workers) for nm in rt.nodes.values())

        def _wait_drain(floor: int, budget_s: float = 45.0) -> None:
            """Block until killed workers are reaped (bounded): kill/EOF
            cleanup otherwise bleeds CPU into the NEXT timed burst and
            the trial measures teardown, not creation."""
            deadline = time.monotonic() + budget_s
            while _workers_alive() > floor and time.monotonic() < deadline:
                time.sleep(0.25)
            time.sleep(0.5)  # straggling reaps/frees

        def _child_cpu_ms() -> float:
            """Mean on-CPU time of the live actor workers (schedstat,
            ns resolution — utime ticks are too coarse at ~5ms each)."""
            total, n = 0.0, 0
            for nm in rt.nodes.values():
                for h in nm.workers.values():
                    pid = getattr(h.proc, "pid", None)
                    if h.actor_id is not None and pid:
                        try:
                            with open(f"/proc/{pid}/schedstat") as f:
                                total += int(f.read().split()[0]) / 1e6
                            n += 1
                        except (OSError, ValueError, IndexError):
                            pass
            return total / n if n else 0.0

        floor = _workers_alive()
        rates = []
        child_cpu = 0.0
        for i in range(trials):
            t0 = time.perf_counter()
            actors = [Probe.remote() for _ in range(n_actors)]
            rmt.get([a.ready.remote() for a in actors], timeout=900)
            rates.append(n_actors / (time.perf_counter() - t0))
            if i == 0:
                child_cpu = _child_cpu_ms()  # before the kills below
            for a in actors:
                rmt.kill(a)
            del actors
            _wait_drain(floor)
        stats["many_actors_per_s"] = _median_row(rates)
        results["many_actors_per_s"] = stats["many_actors_per_s"]["median"]
        # per-phase decomposition (VERDICT r4 #4): fork = amortized zygote
        # batch round trip; boot = spawn-call -> worker registered;
        # child_cpu = each worker's own on-CPU boot+create+first-call
        # cost (the dominant term: COW write faults + thread spawns of a
        # forked CPython — per_actor_ms converges to the SUM of the
        # per-process costs on a single-core host)
        if zygote.peek_global() is not z:
            z = None  # zygote replaced mid-burst: counters reset, skip
        n_forks = (z.spawn_count - fork0[0]) if z else 0
        n_boots = sum(nm.boot_count for nm in rt.nodes.values()) - boot0[0]
        per_actor_ms = 1000.0 / stats["many_actors_per_s"]["median"]
        fork_ms = ((z.spawn_seconds - fork0[1]) / n_forks * 1000
                   if z and n_forks else None)
        boot_ms = ((sum(nm.boot_seconds for nm in rt.nodes.values())
                    - boot0[1]) / n_boots * 1000 if n_boots else None)
        stats["many_actors_phases"] = {
            "per_actor_ms": round(per_actor_ms, 2),
            "fork_ms": round(fork_ms, 2) if fork_ms else None,
            "boot_to_ready_ms": round(boot_ms, 2) if boot_ms else None,
            "child_cpu_ms": round(child_cpu, 2),
            "create_call_ms": round(
                per_actor_ms - (fork_ms or 0), 2),
        }

        # head peak RSS sampled HERE — after the actor churn, before the
        # broadcast section allocates its 1 GiB payload in this process
        # (sampling later would just measure the benchmark's own blob).
        # The reference records _peak_memory at 10k actors the same way.
        import resource

        results["head_peak_rss_mb"] = round(
            resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024, 1)

        # -- many tasks across real agent nodes ------------------------------
        @rmt.remote(max_retries=0)
        def noop():
            return b"ok"

        rates = []
        for _ in range(trials):
            t0 = time.perf_counter()
            refs = [noop.options(scheduling_strategy="SPREAD").remote()
                    for _ in range(n_tasks)]
            rmt.get(refs, timeout=900)
            rates.append(n_tasks / (time.perf_counter() - t0))
            del refs
        stats["many_tasks_per_s"] = _median_row(rates)
        results["many_tasks_per_s"] = stats["many_tasks_per_s"]["median"]

        # -- many placement groups -------------------------------------------
        rates = []
        for _ in range(trials):
            t0 = time.perf_counter()
            for _ in range(n_pgs):
                pg = placement_group([{"CPU": 0.01}], strategy="PACK")
                pg.wait(10)
                remove_placement_group(pg)
            rates.append(n_pgs / (time.perf_counter() - t0))
        stats["many_pgs_per_s"] = _median_row(rates)
        results["many_pgs_per_s"] = stats["many_pgs_per_s"]["median"]

        # -- broadcast one object to every agent node ------------------------
        @rmt.remote(max_retries=0)
        def touch(arr):
            return int(arr[0])

        # one UNTIMED warmup trial first, reported separately: the first
        # pass pays one-time costs (cold page faults on fresh shm
        # segments, worker arg-path priming) that polluted medians with
        # 1.41-vs-5.99 GB/s swings across runs. The timed trials measure
        # steady state; warmup_gbps records what cold-start actually cost.
        rates = []
        warmup_gbps = None
        for i in range(trials + 1):
            blob = np.ones(broadcast_mb << 18, np.float32)  # broadcast_mb MB
            ref = rmt.put(blob)
            t0 = time.perf_counter()
            outs = [touch.options(
                scheduling_strategy=NodeAffinitySchedulingStrategy(
                    node_id=nid, soft=False)).remote(ref)
                for nid in agent_ids]
            assert rmt.get(outs, timeout=900) == [1] * n_agents
            dt = time.perf_counter() - t0
            rate = (broadcast_mb / 1024) * n_agents / dt
            if i == 0:
                warmup_gbps = rate
            else:
                rates.append(rate)
            del ref, blob
            time.sleep(0.5)  # let frees land so trials don't stack copies
        stats["broadcast_gbps"] = _median_row(rates)
        stats["broadcast_gbps"]["warmup_gbps"] = round(warmup_gbps, 3)
        results["broadcast_gbps"] = stats["broadcast_gbps"]["median"]

        # -- cross-node (agent->agent) p2p bandwidth -------------------------
        if n_agents >= 2:
            @rmt.remote(max_retries=0)
            def produce(mb):
                import numpy as _np

                return _np.ones(mb << 18, _np.float32)

            rates = []
            warmup_gbps = None
            for i in range(trials + 1):  # trial 0 = untimed-in-median warmup
                src = agent_ids[i % n_agents]
                dst = agent_ids[(i + 1) % n_agents]
                pref = produce.options(
                    scheduling_strategy=NodeAffinitySchedulingStrategy(
                        node_id=src, soft=False)).remote(broadcast_mb)
                rmt.wait([pref], timeout=900)
                t0 = time.perf_counter()
                out = touch.options(
                    scheduling_strategy=NodeAffinitySchedulingStrategy(
                        node_id=dst, soft=False)).remote(pref)
                assert rmt.get(out, timeout=900) == 1
                rate = (broadcast_mb / 1024) / (time.perf_counter() - t0)
                if i == 0:
                    warmup_gbps = rate
                else:
                    rates.append(rate)
                del pref
            stats["cross_node_gbps"] = _median_row(rates)
            stats["cross_node_gbps"]["warmup_gbps"] = round(warmup_gbps, 3)
            results["cross_node_gbps"] = stats["cross_node_gbps"]["median"]

    finally:
        rmt.shutdown()
    return results, stats


def vs_scale_baseline(results: Dict[str, float]) -> Dict[str, float]:
    out = {}
    for k, v in results.items():
        base = SCALE_BASELINE.get(k)
        if base:
            out[k] = v / base
    return out
