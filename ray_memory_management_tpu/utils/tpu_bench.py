"""TPU compute benchmarks: train-step MFU, flash-attention kernel, and
collective bus-bandwidth.

Measures the north-star rows of BASELINE.md ("match A100 DDP/NCCL") that the
reference never publishes (its release tests assert completion, not
throughput — release/release_logs/): the numbers must be measured, so this
module measures them on whatever TPU is attached.

Methodology note: on tunneled/remote TPU runtimes, ``block_until_ready`` can
return before the computation finishes and per-dispatch round-trips run
multiple milliseconds, so every timed region (a) runs its whole loop inside
ONE jitted dispatch via ``lax.scan``/``fori_loop``, and (b) ends with a tiny
device→host readback, which is the only reliable completion barrier.
"""

from __future__ import annotations

import dataclasses
import functools
import time
from typing import Dict, Optional

import numpy as np

# bf16 peak FLOPs/s per chip by device kind (public spec sheets)
PEAK_BF16: Dict[str, float] = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5": 459e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
}


def peak_flops(device) -> float:
    kind = getattr(device, "device_kind", "")
    for name, peak in PEAK_BF16.items():
        if kind.startswith(name):
            return peak
    return 197e12  # conservative default: v5e-class


def on_tpu() -> bool:
    import jax

    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


def _readback(x) -> float:
    """Force completion: pull one scalar to the host."""
    import jax

    leaf = jax.tree_util.tree_leaves(x)[0]
    return float(np.asarray(leaf).ravel()[0])


def train_step_mfu(preset: str = "gpt2-small", batch_size: int = 8,
                   seq_len: int = 1024, steps: int = 8,
                   remat: bool = False,
                   bf16_params: bool = False) -> Dict[str, float]:
    """Single-chip TransformerLM train step: tokens/s and model FLOPs
    utilisation. Full fwd+bwd+AdamW, ``steps`` steps inside one dispatch.

    Tuned for the chip: params/opt-state DONATED (buffers reused in
    place), layer scan fully unrolled (drops the scan-carry
    dynamic-update-slice traffic — worth ~8% step time at gpt2-small),
    flash attention. ``bf16_params`` stores params and Adam moments in
    bf16 (with bf16 grads) — what lets a ~1B-param model + optimizer fit
    a single 16 GB chip; ``remat`` checkpoints each block for long-S
    activation memory."""
    import jax
    import jax.numpy as jnp
    import optax
    from jax import lax

    from ..models import gpt

    over = {"attention": "flash", "max_seq": seq_len, "remat": remat,
            "scan_unroll": gpt.PRESETS[preset].n_layers}
    if bf16_params:
        over["param_dtype"] = jnp.bfloat16
    cfg = dataclasses.replace(gpt.PRESETS[preset], **over)
    key = jax.random.PRNGKey(0)
    params = gpt.init_params(key, cfg)
    if bf16_params:
        opt = optax.adamw(3e-4, mu_dtype=jnp.bfloat16)
    else:
        opt = optax.adamw(3e-4)
    opt_state = opt.init(params)
    tokens = jax.random.randint(key, (batch_size, seq_len), 0,
                                cfg.vocab_size)
    batch = {"tokens": tokens, "targets": jnp.roll(tokens, -1, axis=1)}

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def run(params, opt_state, batch):
        def step(carry, _):
            p, s = carry
            loss, grads = jax.value_and_grad(
                lambda p_: gpt.loss_fn(p_, batch, cfg))(p)
            updates, s = opt.update(grads, s, p)
            p = optax.apply_updates(p, updates)
            return (p, s), loss

        (p, s), losses = lax.scan(step, (params, opt_state), None,
                                  length=steps)
        return p, s, losses

    params, opt_state, losses = run(params, opt_state, batch)  # compile
    _readback(losses)
    n_params = gpt.count_params(params)
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        params, opt_state, losses = run(params, opt_state, batch)
        final_loss = _readback(losses[-1:])
        best = min(best, time.perf_counter() - t0)
    dt = best

    tokens_per_s = batch_size * seq_len * steps / dt
    # PaLM-appendix accounting: 6N per token (fwd+bwd matmuls) plus causal
    # attention 6*L*S*d_model per token (12*L*S*d non-causal, halved)
    flops_per_token = 6 * n_params + 6 * cfg.n_layers * seq_len * cfg.d_model
    mfu = tokens_per_s * flops_per_token / peak_flops(jax.devices()[0])
    return {
        "tokens_per_s": tokens_per_s,
        "mfu": mfu,
        "n_params": n_params,
        "loss": final_loss,
        "step_ms": dt / steps * 1e3,
    }


def flash_attention_bench(seq_lens=(1024, 4096, 8192), bh: int = 4,
                          head_dim: int = 128,
                          iters: int = 8) -> Dict[int, Dict[str, float]]:
    """Flash kernel vs jnp reference, fwd+bwd, per sequence length.
    Returns {S: {flash_ms, ref_ms, speedup}}."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from ..ops.flash_attention import flash_attention, reference_attention

    out: Dict[int, Dict[str, float]] = {}
    for S in seq_lens:
        kq, kk, kv = jax.random.split(jax.random.PRNGKey(0), 3)
        q = jax.random.normal(kq, (bh, S, head_dim), jnp.bfloat16)
        k = jax.random.normal(kk, (bh, S, head_dim), jnp.bfloat16)
        v = jax.random.normal(kv, (bh, S, head_dim), jnp.bfloat16)

        def timed(attn_fn, n):
            def loss(q_, k_, v_):
                return jnp.sum(attn_fn(q_, k_, v_).astype(jnp.float32) ** 2)

            grad = jax.grad(loss, argnums=(0, 1, 2))

            @jax.jit
            def run(q, k, v):
                def body(i, carry):
                    q_, acc = carry
                    dq, dk, dv = grad(q_, k, v)
                    # data-dependence across iterations so nothing is hoisted
                    return (q_ + 1e-6 * dq.astype(q_.dtype),
                            acc + jnp.sum(dv.astype(jnp.float32)))

                return lax.fori_loop(0, n, body, (q, jnp.float32(0.0)))

            _readback(run(q, k, v)[1])  # compile + warm
            t0 = time.perf_counter()
            _readback(run(q, k, v)[1])
            return (time.perf_counter() - t0) / n * 1e3

        n_ref = max(2, iters // 4) if S >= 8192 else iters
        flash_ms = timed(
            lambda q_, k_, v_: flash_attention(q_, k_, v_, use_pallas="on"),
            iters)
        ref_ms = timed(
            lambda q_, k_, v_: reference_attention(q_, k_, v_), n_ref)
        out[S] = {"flash_ms": flash_ms, "ref_ms": ref_ms,
                  "speedup": ref_ms / flash_ms}
    return out


def llm_serving_bench(preset: str = "gpt2-small", n_requests: int = 32,
                      prompt_len: int = 128, max_new_tokens: int = 64,
                      max_batch_size: int = 8) -> Dict[str, float]:
    """Decode goodput (REQUESTED tokens/s) through the FULL serve stack
    on the chip: handle -> router -> replica (num_tpus=1 chip lease) ->
    batching engine -> the KV-cached decode programs (serve/llm.py).
    Runs BOTH batching modes over the same Poisson arrival schedule of a
    MIXED workload (budgets alternate max_new_tokens and a quarter of
    it) — "continuous" (decode-step join/leave, per-request budgets
    honored, the default) vs the legacy "barrier" (whole-batch: every
    request pays the full deployment budget and new arrivals park behind
    the longest running batch) — and reports the speedup."""
    import os
    import threading

    import numpy as np

    prev_worker_platform = os.environ.get("RMT_WORKER_JAX_PLATFORMS")
    os.environ["RMT_WORKER_JAX_PLATFORMS"] = "tpu"
    try:
        import ray_memory_management_tpu as rmt
        from ray_memory_management_tpu import serve
        from ray_memory_management_tpu.serve.llm import llm_deployment

        rmt.init(num_cpus=4, num_tpus=1)
        try:
            out: Dict[str, float] = {}
            prompt = list(range(2, 2 + prompt_len))
            # Poisson arrivals at ~2x the barrier's drain rate so queueing
            # pressure is real; same arrival schedule for both modes
            rng = np.random.default_rng(0)
            gaps = rng.exponential(0.05, n_requests)  # drawn ONCE: both
            # mixed budgets: half the requests want a quarter the tokens
            budgets = [max_new_tokens if i % 2 == 0 else
                       max(1, max_new_tokens // 4)
                       for i in range(n_requests)]
            requested = sum(budgets)
            for mode in ("continuous", "barrier"):    # modes see the same
                # arrival schedule, so the ratio measures the batching
                # mode, not arrival-pattern noise
                serve.start(http_port=None)
                handle = serve.run(llm_deployment(
                    preset, ray_actor_options={"num_tpus": 1},
                    max_new_tokens=max_new_tokens,
                    max_batch_size=max_batch_size,
                    batch_wait_timeout_s=0.02,
                    batching=mode))
                # warm: compiles the decode programs on the chip
                warm = rmt.get(handle.remote({"tokens": prompt}),
                               timeout=900)
                assert len(warm["tokens"]) == max_new_tokens

                results: list = []

                def one(budget):
                    r = rmt.get(handle.remote(
                        {"tokens": prompt, "max_new_tokens": budget}),
                        timeout=900)
                    results.append(len(r["tokens"]))

                t0 = time.perf_counter()
                threads = []
                for i in range(n_requests):
                    th = threading.Thread(target=one, args=(budgets[i],))
                    th.start()
                    threads.append(th)
                    time.sleep(float(gaps[i]))
                for th in threads:
                    th.join()
                dt = time.perf_counter() - t0
                assert len(results) == n_requests
                # goodput: tokens the CLIENTS asked for per second
                # (barrier mode over-generates for short requests; those
                # surplus tokens are waste, not throughput)
                key = ("decode_tokens_per_s" if mode == "continuous"
                       else "decode_tokens_per_s_barrier")
                out[key] = requested / dt
                if mode == "continuous":
                    out["requests_per_s"] = n_requests / dt
                    try:
                        stats = rmt.get(handle.stats.remote(), timeout=60)
                        out["decode_steps"] = stats["batches"]
                    except Exception:
                        pass
                serve.shutdown()
            if out.get("decode_tokens_per_s_barrier"):
                out["continuous_vs_barrier"] = (
                    out["decode_tokens_per_s"]
                    / out["decode_tokens_per_s_barrier"])
            return out
        finally:
            try:
                serve.shutdown()
            except Exception:
                pass
            rmt.shutdown()
    finally:
        if prev_worker_platform is None:
            os.environ.pop("RMT_WORKER_JAX_PLATFORMS", None)
        else:
            os.environ["RMT_WORKER_JAX_PLATFORMS"] = prev_worker_platform


def rl_learner_bench(n_workers: int = 2, iters: int = 4,
                     train_batch: int = 4096, fragment: int = 512,
                     num_sgd_iter: int = 6,
                     minibatch: int = 512) -> Dict[str, float]:
    """RL throughput with the learner ON THE CHIP: PPO through the full
    stack — CPU rollout actors sample CartPole fragments in worker
    processes, the driver-side learner runs donated-state minibatch SGD
    on the TPU (make_ppo_update donate=True: params/opt-state update in
    place in HBM). The north-star row BASELINE.md names ("RLlib
    PPO/IMPALA with TPU learner — env steps/s"); the reference's analog
    keeps learner threads off the rollout path
    (rllib/execution/multi_gpu_learner_thread.py).

    Reports overall env_steps_per_s (sample+learn, the headline),
    learner-only learner_env_steps_per_s, and learner_ms per jit'd
    minibatch update."""
    import ray_memory_management_tpu as rmt
    from ray_memory_management_tpu.rllib.ppo import PPOConfig

    rmt.init(num_cpus=max(2, n_workers))
    try:
        algo = (PPOConfig()
                .environment("CartPole",
                             env_config={"max_episode_steps": 200})
                .rollouts(num_rollout_workers=n_workers,
                          rollout_fragment_length=fragment)
                .training(train_batch_size=train_batch, lr=3e-4,
                          num_sgd_iter=num_sgd_iter,
                          sgd_minibatch_size=minibatch,
                          donate_learner_state=True)
                .debugging(seed=0)
                .build())
        try:
            algo.train()  # warm: compiles the update, forks the workers
            steps = 0
            sample_s = learn_s = 0.0
            updates = 0
            t0 = time.perf_counter()
            for _ in range(iters):
                r = algo.train()
                steps += r["num_env_steps_sampled"]
                sample_s += r["sample_time_s"]
                learn_s += r["learn_time_s"]
                updates += num_sgd_iter * max(
                    1, r["num_env_steps_sampled"] // minibatch)
            dt = time.perf_counter() - t0
            return {
                "env_steps_per_s": steps / dt,
                "learner_env_steps_per_s": steps / max(learn_s, 1e-9),
                "learner_ms": learn_s / max(updates, 1) * 1e3,
                "sample_s": sample_s, "learn_s": learn_s,
                "algo": "ppo", "n_workers": n_workers,
                # episode_reward_mean is None when no episode completed
                # in the window — keep the persisted row JSON-numeric
                "final_reward": r.get("episode_reward_mean") or 0.0,
            }
        finally:
            algo.stop()
    finally:
        rmt.shutdown()


def allreduce_busbw(size_mb: int = 64,
                    iters: int = 8) -> Optional[Dict[str, float]]:
    """Bus bandwidth of a psum allreduce over all local TPU devices.
    Returns None when fewer than 2 devices are attached (a single chip has
    no interconnect to measure)."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    devs = jax.devices()
    n = len(devs)
    if n < 2:
        return None
    mesh = Mesh(np.array(devs), ("x",))
    elems = size_mb * (1 << 20) // 4
    x = jnp.ones((n, elems), jnp.float32)
    x = jax.device_put(x, NamedSharding(mesh, P("x", None)))

    @jax.jit
    def run(x):
        def body(i, y):
            from .jax_compat import shard_map

            f = shard_map(lambda a: lax.psum(a, "x"), mesh=mesh,
                          in_specs=P("x", None), out_specs=P("x", None))
            return f(y) / n  # keep magnitudes bounded

        return lax.fori_loop(0, iters, body, x)

    _readback(run(x))
    t0 = time.perf_counter()
    _readback(run(x))
    dt = (time.perf_counter() - t0) / iters
    bytes_moved = size_mb * (1 << 20)
    # ring-allreduce bus bytes: 2*(n-1)/n per byte of payload
    busbw = bytes_moved * 2 * (n - 1) / n / dt
    return {"busbw_gbps": busbw / 1e9, "world": n,
            "alg_bw_gbps": bytes_moved / dt / 1e9}
