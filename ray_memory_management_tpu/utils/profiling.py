"""TPU profiling: xprof traces + device-memory profiles via jax.profiler.

The reference's profiling story is (a) per-worker ProfileEvents to GCS
rendered by ``ray timeline`` (src/ray/core_worker/profiling.h:30; covered
here by utils/timeline.py) and (b) torch-profiler integration inside Train
(train/torch/train_loop_utils.py:232 TorchWorkerProfiler). On TPU the
equivalent of (b) is xprof: ``jax.profiler`` captures XLA device traces
(HLO timing, MXU utilization, HBM traffic) viewable in TensorBoard or
Perfetto. This module is the thin, dependency-gated bridge:

  - ``xprof_trace(logdir)``     capture a device trace for the enclosed code
                                (jax.profiler.trace), and record the span in
                                the runtime timeline so host-side task spans
                                and device traces line up;
  - ``annotate(name)``          a TraceAnnotation visible in xprof AND a
                                timeline span — one annotation, both views;
  - ``start_server(port)``      live-capture endpoint (connect TensorBoard's
                                profile tab to localhost:<port>);
  - ``save_device_memory_profile(path)``  HBM allocation snapshot (pprof
                                format) — the OOM-debugging tool.

All entry points degrade to no-ops with a warning when jax is unavailable
(CPU-only driver processes), so library code can call them unconditionally.
"""

from __future__ import annotations

import contextlib
import time
from typing import Optional

from . import timeline


def _profiler():
    try:
        import jax

        return jax.profiler
    except Exception:
        return None


@contextlib.contextmanager
def xprof_trace(logdir: str, create_perfetto_trace: bool = False):
    """Capture an xprof/TensorBoard device trace of the enclosed block into
    ``logdir`` (the TorchWorkerProfiler analog for XLA)."""
    prof = _profiler()
    start = time.time()
    if prof is None:
        yield
        return
    try:
        with prof.trace(logdir,
                        create_perfetto_trace=create_perfetto_trace):
            yield
    finally:
        timeline.record_event("xprof_trace", "profiler", start, time.time(),
                              extra={"logdir": logdir})


@contextlib.contextmanager
def annotate(name: str):
    """Named region visible in BOTH the xprof device trace (TraceAnnotation)
    and the runtime chrome timeline."""
    prof = _profiler()
    start = time.time()
    ctx = prof.TraceAnnotation(name) if prof is not None \
        else contextlib.nullcontext()
    try:
        with ctx:
            yield
    finally:
        timeline.record_event(name, "annotation", start, time.time())


_server = None


def start_server(port: int = 9012) -> bool:
    """Start the live profiler server (TensorBoard profile tab target).
    Returns False when jax is unavailable."""
    global _server
    prof = _profiler()
    if prof is None:
        return False
    if _server is None:
        _server = prof.start_server(port)
    return True


def stop_server() -> None:
    global _server
    prof = _profiler()
    if prof is not None and _server is not None:
        prof.stop_server()
        _server = None


def save_device_memory_profile(path: str) -> Optional[str]:
    """Dump the current device (HBM) allocation profile in pprof format
    (``jax.profiler.save_device_memory_profile``); None if unavailable."""
    prof = _profiler()
    if prof is None:
        return None
    prof.save_device_memory_profile(path)
    return path
