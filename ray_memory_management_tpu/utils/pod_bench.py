"""Pod-scale control-plane benchmark.

Drives 64-256 node memberships and ~10^6 live directory rows through
the GENUINE head code paths using the simulated agent plane
(:mod:`sim_agent`): every sim node speaks the real wire protocol over
the real authenticated channels, so the head's scheduler, lease-credit
accounting, delta-heartbeat ingress, and memory-bounded directory are
measured exactly as a real pod would exercise them — minus worker
processes and the p2p transfer plane, which is what lets one host
sustain 256 memberships.

Two phases:

* **membership curve** — for each node count: register N sim agents,
  burst leaf tasks through the lease plane (tasks/s), microbench the
  directory (add/locate/remove p50/p99 us), and sample head RSS.
* **row flood** (largest point only) — sim agents assert synthetic
  rows via pong deltas until the directory holds ``rows_target`` live
  rows against a small hot cap backed by a sqlite blob surface.  The
  headline claims are (a) head RSS stays bounded (hot cap + cold
  index, NOT ~1KB/row), and (b) ingress is O(changes): churn ships
  delta pongs whose size tracks the churn rate, not the row count.
"""

from __future__ import annotations

import os
import sys
import tempfile
import time
from typing import Dict, List

from .sim_agent import close_sim_agents, spawn_sim_agents


def _note(msg: str) -> None:
    # rmtcheck: disable=log-discipline — bench progress, stderr like
    # bench.py's own suite chatter
    print(f"    pod: {msg}", file=sys.stderr, flush=True)


def _rss_mb() -> float:
    """Current RSS of the head process (MB) — /proc is authoritative and
    cheap; ru_maxrss is a high-water mark that never comes back down
    across the curve's points."""
    try:
        with open("/proc/self/statm") as f:
            pages = int(f.read().split()[1])
        return pages * (os.sysconf("SC_PAGE_SIZE") / (1024.0 * 1024.0))
    except (OSError, ValueError, IndexError):
        import resource
        return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024.0


def _pcts(durs_us: List[float]) -> Dict[str, float]:
    durs_us = sorted(durs_us)
    n = len(durs_us)
    return {"p50": durs_us[n // 2], "p99": durs_us[min(n - 1, (n * 99) // 100)]}


def _dir_microbench(gcs, node_id: bytes, n_ops: int = 2000) -> Dict[str, float]:
    """Directory-op latency under whatever concurrent pong-delta load the
    sim plane is applying: timed add -> locate -> remove over fresh oids."""
    oids = [b"podbench" + i.to_bytes(6, "big") + os.urandom(6)
            for i in range(n_ops)]
    add_us: List[float] = []
    loc_us: List[float] = []
    for oid in oids:
        t0 = time.perf_counter()
        gcs.add_object_location(oid, node_id, size=64)
        add_us.append((time.perf_counter() - t0) * 1e6)
    for oid in oids:
        t0 = time.perf_counter()
        gcs.locate_objects([oid])
        loc_us.append((time.perf_counter() - t0) * 1e6)
    for oid in oids:
        gcs.remove_object_location(oid, node_id)
    both = add_us + loc_us
    out = _pcts(both)
    out["locate_p99"] = _pcts(loc_us)["p99"]
    return out


def run_pod_curve(node_counts=(8, 64, 128, 256), tasks_per_point=1500,
                  rows_target=1_000_000, hot_max_rows=200_000,
                  rows_per_agent_chunk=1000):
    """Returns the ``pod_curve`` suite dict (see module docstring)."""
    import ray_memory_management_tpu as rmt
    from ..config import Config
    from ..core import metrics_defs as mdefs

    counts = list(node_counts)
    tasks_pts: Dict[str, float] = {}
    dir_p50: Dict[str, float] = {}
    dir_p99: Dict[str, float] = {}
    rss_pts: Dict[str, float] = {}
    rows_detail: Dict[str, float] = {}
    tmp = tempfile.mkdtemp(prefix="rmt-podbench-")
    for n in counts:
        # every curve point runs the SAME config (in-memory tables, no
        # WAL) so tasks/s compares membership size and nothing else
        t_pt = time.perf_counter()
        rt = rmt.init(num_cpus=2, object_store_memory=1 << 28)
        agents = []
        try:
            agents = spawn_sim_agents(rt, n, num_cpus=2)
            _note(f"{n}n registered in "
                  f"{time.perf_counter() - t_pt:.1f}s")

            @rmt.remote(max_retries=0)
            def noop():
                return b"ok"

            # warm: one wave boots the lease plane + fn_blob caches
            rmt.get([noop.remote() for _ in range(2 * n)], timeout=300)
            t0 = time.perf_counter()
            rmt.get([noop.remote() for _ in range(tasks_per_point)],
                    timeout=600)
            tasks_pts[str(n)] = tasks_per_point / (time.perf_counter() - t0)

            mb = _dir_microbench(rt.gcs, agents[0].node_id)
            dir_p50[str(n)] = mb["p50"]
            dir_p99[str(n)] = mb["p99"]
            rss_pts[str(n)] = _rss_mb()
            _note(f"{n}n tasks {tasks_pts[str(n)]:.0f}/s, point done in "
                  f"{time.perf_counter() - t_pt:.1f}s")
        finally:
            close_sim_agents(agents)
            rmt.shutdown()
            _note(f"{n}n torn down at {time.perf_counter() - t_pt:.1f}s")
    if rows_target > 0:
        # row flood in a dedicated runtime at the largest membership:
        # small hot cap + sqlite blob surface so cold batches leave RAM
        cfg = Config(
            gcs_storage_path=os.path.join(tmp, "pod-rows.db"),
            gcs_directory_hot_max_rows=hot_max_rows,
        )
        t_fl = time.perf_counter()
        rt = rmt.init(num_cpus=2, object_store_memory=1 << 28, _config=cfg)
        agents = []
        try:
            agents = spawn_sim_agents(rt, counts[-1], num_cpus=2)
            _note(f"flood fleet up in {time.perf_counter() - t_fl:.1f}s")
            rows_detail = _row_flood(rt, agents, rows_target,
                                     rows_per_agent_chunk, mdefs)
            _note(f"flood converged {rows_detail['total']:.0f} rows at "
                  f"{time.perf_counter() - t_fl:.1f}s")
            # directory-op latency with the table at full row count and
            # the hot cap engaged (faults on the locate path)
            rows_detail["dir_p99_us_at_rows"] = \
                _dir_microbench(rt.gcs, agents[0].node_id)["p99"]
        finally:
            close_sim_agents(agents)
            rmt.shutdown()
    first, lastc = str(counts[0]), str(counts[-1])
    return {
        "nodes": counts,
        "tasks_per_s": tasks_pts,
        "dir_p50_us": dir_p50,
        "dir_p99_us": dir_p99,
        "head_rss_mb": rss_pts,
        "tasks_scaling_first_to_last":
            tasks_pts[lastc] / tasks_pts[first] if tasks_pts.get(first)
            else 0.0,
        "rows": rows_detail,
    }


def _row_flood(rt, agents, rows_target, chunk, mdefs) -> Dict[str, float]:
    """Assert rows_target synthetic rows across the sim fleet via pong
    deltas, then churn to show steady-state ingress is O(changes)."""
    per_agent = rows_target // len(agents) + 1
    added = 0
    while added < per_agent:
        step = min(chunk, per_agent - added)
        for a in agents:
            a.add_rows(step)
        added += step
        # pace the flood to the heartbeat so pong frames stay reasonable
        time.sleep(0.25)
    deadline = time.monotonic() + 180
    stats = rt.gcs.directory_stats()
    while time.monotonic() < deadline:
        stats = rt.gcs.directory_stats()
        if stats["hot"] + stats["cold"] >= rows_target:
            break
        time.sleep(0.5)
    rss_at_rows = _rss_mb()
    # steady-state churn: 1% of rows replaced; the delta plane must ship
    # ~2% of rows per cycle, NOT full state
    shipped_before = sum(a.rows_shipped for a in agents)
    for a in agents:
        a.churn_rows(max(1, a.row_count() // 100))
    time.sleep(3 * 0.5 + 0.5)  # a few heartbeat cycles
    shipped_churn = sum(a.rows_shipped for a in agents) - shipped_before
    out = {
        "target": float(rows_target),
        "total": float(stats["hot"] + stats["cold"]),
        "hot": float(stats["hot"]),
        "cold": float(stats["cold"]),
        "rss_mb_at_rows": rss_at_rows,
        "faults": float(mdefs.gcs_directory_faults().get()),
        "spills": float(mdefs.gcs_directory_spills().get()),
        "resyncs": float(mdefs.heartbeat_resyncs().get()),
        "full_pongs": float(sum(a.pongs_full for a in agents)),
        "delta_pongs": float(sum(a.pongs_delta for a in agents)),
        "churn_rows_shipped": float(shipped_churn),
    }
    return out
