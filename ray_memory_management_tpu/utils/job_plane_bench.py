"""Job-plane bench: multi-tenant isolation overhead, sweep latency,
driver churn.

Three measurements, matching the multi-tenant job plane's acceptance
criteria:

  - **Isolation overhead** — tasks/s of one batch submitted by the root
    job alone (single-ledger fast path: no admission, no fair ordering)
    vs the same batch split across 4 quota'd jobs (per-job attribution,
    byte/slot admission, stride fair ordering in ``_pump``). The gap is
    the whole cost of multi-tenancy on the submit hot path.
  - **Sweep latency vs object count** — a client job puts K objects and
    dies; how long does :meth:`Runtime.sweep_job` take to cancel, free,
    and retire everything, and does the directory really end at zero
    rows for the job? (K = 100 and 1000 — the sweep walks only tagged
    rows, so it should scale with the JOB's footprint, not the
    cluster's.)
  - **Driver churn soak** — N driver threads cycle register → submit →
    (get results + clean sweep | abrupt mid-flight sweep, the SIGKILL
    analog) for several rounds. Reports aggregate completed tasks/s and
    the leak probes: directory rows still tagged to any dead job and
    device-tier bytes pinned above the pre-churn baseline (both must be
    zero).

Run via ``bench.py`` (the ``jobs`` headline block) or directly:
``python -m ray_memory_management_tpu.utils.job_plane_bench``.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List


def _noop_fn():
    import ray_memory_management_tpu as rmt

    @rmt.remote
    def _bench_noop(i):
        return i

    return _bench_noop


def _submit_as(rt, fn, job, i) -> List[bytes]:
    """Submit one task attributed to ``job`` (None = root), the way the
    cluster server stamps thin-client payloads."""
    from .. import api as _api

    payload = dict(fn._template())
    enc_args, enc_kwargs = _api._encode_call((i,), {})
    payload["args"] = enc_args
    payload["kwargs"] = enc_kwargs
    if job is not None:
        payload["job_id"] = job
    return rt.submit_task(payload)


def _drain(rt, rids, timeout: float = 120.0) -> int:
    done = 0
    for rid in rids:
        try:
            rt.get_objects([rid], timeout=timeout)
            done += 1
        except Exception:  # noqa: BLE001 — swept jobs fail their tasks
            pass
    return done


def _isolation_suite(rt, n_tasks: int) -> Dict:
    fn = _noop_fn()
    # warm: pool spin-up and fn-blob shipping are not the measurement
    _drain(rt, [r for i in range(8) for r in _submit_as(rt, fn, None, i)])

    t0 = time.perf_counter()
    rids = [r for i in range(n_tasks) for r in _submit_as(rt, fn, None, i)]
    _drain(rt, rids)
    single_s = time.perf_counter() - t0

    jobs = [os.urandom(16) for _ in range(4)]
    for j in jobs:
        rt.register_client_job(j, {"type": "bench"},
                               quota={"priority": 1})
    t0 = time.perf_counter()
    rids = [r for i in range(n_tasks)
            for r in _submit_as(rt, fn, jobs[i % 4], i)]
    done = _drain(rt, rids)
    multi_s = time.perf_counter() - t0
    for j in jobs:
        rt.sweep_job(j, trigger="disconnect")

    single_rate = n_tasks / single_s if single_s > 0 else 0.0
    multi_rate = done / multi_s if multi_s > 0 else 0.0
    overhead = ((single_rate / multi_rate - 1.0) * 100.0
                if multi_rate > 0 else float("inf"))
    return {
        "single_job_tasks_per_s": round(single_rate, 1),
        "multi_job_tasks_per_s": round(multi_rate, 1),
        "isolation_overhead_pct": round(overhead, 1),
    }


def _sweep_suite(rt, counts=(100, 1000)) -> Dict:
    out: Dict = {"sweep_leaked_rows": 0}
    for k in counts:
        job = os.urandom(16)
        rt.register_client_job(job, {"type": "bench"})
        for i in range(k):
            rt.put_object(b"x" * 256, job_id=job)
        t0 = time.perf_counter()
        ok = rt.sweep_job(job, trigger="disconnect")
        out[f"sweep_ms_{k}"] = round(
            (time.perf_counter() - t0) * 1e3, 2)
        leaked = rt.gcs.count_job_rows(job)
        out["sweep_leaked_rows"] += leaked if ok else leaked or 1
    return out


def _churn_suite(rt, drivers: int = 4, rounds: int = 3,
                 tasks_per_round: int = 20) -> Dict:
    fn = _noop_fn()
    _drain(rt, [r for i in range(4) for r in _submit_as(rt, fn, None, i)])
    baseline_dev = rt.device_store.total_bytes()
    dead_jobs: List[bytes] = []
    dead_lock = threading.Lock()
    completed = [0] * drivers
    kills = [0] * drivers

    def driver(ix: int) -> None:
        for rnd in range(rounds):
            job = os.urandom(16)
            rt.register_client_job(job, {"type": "bench-churn"},
                                   quota={"priority": 1 + ix % 2})
            rids = [r for i in range(tasks_per_round)
                    for r in _submit_as(rt, fn, job, i)]
            rt.put_object(b"y" * 1024, job_id=job)
            if (ix + rnd) % 3 == 2:
                # the SIGKILL analog: no goodbye, tasks still in flight
                # — the sweep must cancel and reclaim them all
                rt.sweep_job(job, trigger="watchdog")
                kills[ix] += 1
            else:
                completed[ix] += _drain(rt, rids)
                rt.sweep_job(job, trigger="disconnect")
            with dead_lock:
                dead_jobs.append(job)

    threads = [threading.Thread(target=driver, args=(i,),
                                name=f"bench-driver-{i}")
               for i in range(drivers)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0

    leaked_rows = sum(rt.gcs.count_job_rows(j) for j in dead_jobs)
    live = rt.job_usage()
    ghost_ledgers = sum(1 for j in dead_jobs if j.hex() in live)
    leaked_dev = max(0, rt.device_store.total_bytes() - baseline_dev)
    return {
        "churn_tasks_per_s": round(sum(completed) / wall, 1)
        if wall > 0 else 0.0,
        "churn_jobs": len(dead_jobs),
        "churn_kills": sum(kills),
        "churn_leaked_rows": leaked_rows + ghost_ledgers,
        "churn_leaked_device_bytes": leaked_dev,
    }


def run_job_plane_suite(mini: bool = False) -> Dict:
    import ray_memory_management_tpu as rmt
    from .. import _worker_context

    owns = _worker_context.get_runtime() is None
    if owns:
        rmt.init(num_cpus=4)
    rt = _worker_context.get_runtime()
    try:
        out: Dict = {"mini": bool(mini)}
        out.update(_isolation_suite(rt, n_tasks=40 if mini else 160))
        out.update(_sweep_suite(rt, counts=(100,) if mini
                                else (100, 1000)))
        if mini:
            out.setdefault("sweep_ms_1000", out.get("sweep_ms_100", 0.0))
        out.update(_churn_suite(
            rt, drivers=4, rounds=2 if mini else 3,
            tasks_per_round=8 if mini else 20))
        return out
    finally:
        if owns:
            rmt.shutdown()


if __name__ == "__main__":
    import json

    print(json.dumps(run_job_plane_suite(mini=True), indent=1))
