"""Device mesh construction: the parallelism substrate.

Replaces the reference's process-group choreography (§2.3-2.4 of SURVEY.md)
with jax meshes: a named-axis mesh is the single object every strategy (DP /
FSDP / TP / SP / EP / PP) hangs off. On TPU hardware,
``mesh_utils.create_device_mesh`` lays axes onto the ICI torus so the
innermost axes get the fastest links; on CPU test meshes we reshape directly.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional

import numpy as np

import jax
from jax.sharding import Mesh

# canonical axis order: outer (slow/DCN-ish) to inner (fast ICI); tp innermost
AXIS_ORDER = ("pp", "dp", "fsdp", "sp", "ep", "tp")


def make_mesh(axes: Dict[str, int],
              devices: Optional[List] = None) -> Mesh:
    """Build a mesh with the given {axis: size}. Axes are laid out in
    AXIS_ORDER (unknown names go last in given order)."""
    names = sorted(
        axes.keys(),
        key=lambda n: AXIS_ORDER.index(n) if n in AXIS_ORDER else 99,
    )
    shape = tuple(axes[n] for n in names)
    n_dev = math.prod(shape)
    if devices is None:
        devices = jax.devices()
    if n_dev > len(devices):
        raise ValueError(
            f"mesh {axes} needs {n_dev} devices, have {len(devices)}"
        )
    devices = devices[:n_dev]
    if devices[0].platform == "tpu":
        from jax.experimental import mesh_utils

        arr = mesh_utils.create_device_mesh(shape, devices=devices)
    else:
        arr = np.array(devices).reshape(shape)
    return Mesh(arr, names)


def cpu_mesh(axes: Dict[str, int]) -> Mesh:
    """Test mesh over the forced-host-device CPU backend."""
    return make_mesh(axes, devices=jax.devices("cpu"))


def local_tpu_mesh(axes: Optional[Dict[str, int]] = None) -> Mesh:
    """Mesh over this host's TPU chips (the host-process model: one process
    owns 4-8 chips)."""
    devices = jax.devices("tpu") if any(
        d.platform == "tpu" for d in jax.devices()) else jax.devices()
    if axes is None:
        axes = {"dp": len(devices)}
    return make_mesh(axes, devices=devices)
