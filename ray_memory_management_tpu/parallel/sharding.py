"""Sharding presets: DP / FSDP / TP(+combinations) as PartitionSpec rules.

This module is the TPU replacement for the reference's parallelism wiring
(SURVEY.md §2.4: DDP via torch process groups, FSDP via user code, TP absent):
strategies are *sharding rules over a named mesh*, applied with pjit/jit so
XLA inserts the collectives (psum for DP grads, all-gather/reduce-scatter for
FSDP, all-reduce pairs for Megatron TP) on ICI.

Rules are keyed by the TransformerLM parameter names (models/gpt.py); unknown
trees fall back to dimension-based heuristics so other models (ResNet) work
too.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# Megatron-style TP rules for TransformerLM stacked params [L, in, out]:
# column-parallel (shard output dim), row-parallel (shard input dim).
_TP_RULES = {
    "wq": P(None, None, "tp"),
    "wk": P(None, None, "tp"),
    "wv": P(None, None, "tp"),
    "wo": P(None, "tp", None),
    "w1": P(None, None, "tp"),
    "w3": P(None, None, "tp"),
    "w2": P(None, "tp", None),
    "ln1": P(None, None),
    "ln2": P(None, None),
    "tok_embed": P("tp", None),   # vocab-parallel embedding
    "lm_head": P(None, "tp"),
    "final_ln": P(None),
}


def _maybe_add_fsdp(spec: P, shape, fsdp_size: int) -> P:
    """Layer FSDP onto a TP spec: shard the largest still-unsharded,
    divisible dimension along the fsdp axis (ZeRO-3-style parameter
    sharding; XLA all-gathers just-in-time and reduce-scatters grads)."""
    dims = list(spec) + [None] * (len(shape) - len(spec))
    candidates = sorted(
        range(len(shape)), key=lambda i: -int(np.prod(shape[i:i + 1]))
    )
    for i in candidates:
        if dims[i] is None and shape[i] % fsdp_size == 0 and shape[i] > 1:
            dims[i] = "fsdp"
            return P(*dims)
    return P(*dims)


def param_pspecs(params: Dict[str, Any], mesh: Mesh,
                 strategy: str = "dp") -> Dict[str, Any]:
    """PartitionSpec pytree for a parameter pytree.

    strategy: "dp" (replicated params), "fsdp", "tp", "ep", and
    combinations ("fsdp+tp", "dp+tp", "ep+tp", ...). Mesh must carry the
    matching axis names.
    """
    use_tp = "tp" in strategy and "tp" in mesh.shape
    use_fsdp = "fsdp" in strategy and "fsdp" in mesh.shape
    use_ep = "ep" in strategy and "ep" in mesh.shape
    fsdp_size = mesh.shape.get("fsdp", 1)

    # MoE expert weights ([L, E, ...], ops/moe.py): the expert dim shards
    # over ep (when enabled); tp (if also on) stays Megatron-style WITHIN
    # each expert (col-parallel w1/w3 output dim, row-parallel w2 input
    # dim). These rules apply whenever the 4-D expert shape is seen — a
    # tp-only strategy must NOT fall through to the 3-D dense rules, which
    # would shard the expert dim as if it were a feature dim.
    ep_ax = "ep" if use_ep else None
    tp_ax = "tp" if use_tp else None
    _MOE_RULES = {
        "w1": P(None, ep_ax, None, tp_ax),
        "w3": P(None, ep_ax, None, tp_ax),
        "w2": P(None, ep_ax, tp_ax, None),
        "router": P(None, None, None),  # [L, D, E]: tiny, replicated
    }

    def spec_for(path: str, leaf) -> P:
        shape = leaf.shape
        name = path.split("/")[-1]
        spec = P(*([None] * len(shape)))
        if name in _MOE_RULES and len(_MOE_RULES[name]) == len(shape):
            spec = _MOE_RULES[name]
        elif use_tp:
            if name in _TP_RULES:
                spec = _TP_RULES[name]
                if len(spec) < len(shape):  # non-stacked variant
                    spec = P(*list(spec)[-len(shape):])
                elif len(spec) > len(shape):
                    spec = P(*list(spec)[-len(shape):])
        if use_fsdp:
            spec = _maybe_add_fsdp(spec, shape, fsdp_size)
        return spec

    def walk(tree, path=""):
        if isinstance(tree, dict):
            return {k: walk(v, f"{path}/{k}") for k, v in tree.items()}
        return spec_for(path, tree)

    return walk(params)


def batch_pspec(mesh: Mesh) -> P:
    """Shard the batch dimension over every data-ish axis present."""
    axes = tuple(a for a in ("dp", "fsdp") if a in mesh.shape)
    return P(axes if axes else None)


def shard_pytree(tree, mesh: Mesh, specs, copy: bool = False) -> Any:
    """Place a pytree onto the mesh per its specs (used at init; jit
    propagates from there).

    NOTE: device_put may alias the input's buffers when a shard already
    lives on the right device, so a later DONATING train step can delete the
    caller's original tree too. Pass ``copy=True`` if you intend to reuse
    the unsharded tree afterwards (e.g. sharding the same init across
    several meshes in tests)."""
    import numpy as np  # local: forces a host-side copy when requested

    def put(x, s):
        if copy:
            x = np.array(x)
        return jax.device_put(x, NamedSharding(mesh, s))

    return jax.tree.map(
        put, tree, specs, is_leaf=lambda x: not isinstance(x, dict),
    )


def make_train_step(loss_fn, optimizer, mesh: Mesh,
                    donate: bool = True):
    """Build the jitted train step. Params/opt-state shardings propagate from
    their placement (shard_pytree at init); the batch is constrained inside so
    XLA partitions the whole step and inserts grad psums automatically."""
    bspec = batch_pspec(mesh)

    def step(params, opt_state, batch):
        batch = jax.lax.with_sharding_constraint(
            batch, NamedSharding(mesh, bspec)
        )
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = jax.tree.map(lambda p, u: p + u, params, updates)
        return params, opt_state, loss

    donate_argnums = (0, 1) if donate else ()
    return jax.jit(step, donate_argnums=donate_argnums)
