"""Pipeline parallelism: a GPipe-style microbatch schedule over mesh stages.

Net-new versus the reference, which has no pipeline-parallel library — it only
offers the building blocks (actors + ``collective.send/recv``,
util/collective/collective.py:531,594, and static task graphs via ray.dag,
python/ray/dag/dag_node.py:23). SURVEY.md §2.4 maps PP as composable-but-
absent; VERDICT r1 item 8 asks for the real thing. Here it is TPU-idiomatic:

  - one SPMD program over a mesh with a ``pp`` axis (no actor choreography,
    no point-to-point sends): every device runs the same ``shard_map``-ped
    schedule, holding its stage's slice of the LAYER-STACKED parameters
    (models/gpt.py keeps weights as [L, ...] pytrees, so "stage s owns
    layers [s*L/S, (s+1)*L/S)" is just a sharding of the leading dim);
  - activations flow between stages with ``lax.ppermute`` — XLA lowers it
    to a collective-permute that rides neighbor ICI links, exactly the
    transfer pattern the TPU torus is built for;
  - the schedule is the classic GPipe fill/flush loop: M microbatches over
    S stages in M + S - 1 steps, expressed as a ``lax.scan`` (static trip
    count, jit-compatible);
  - the whole schedule is DIFFERENTIABLE: jax autodiff through
    scan+ppermute yields the reverse schedule (transpose of a ppermute is
    the reverse ppermute), so ``jax.grad`` of a pipelined loss just works,
    with weight grads landing sharded over ``pp`` like the weights.

Composes with data parallelism by adding a ``dp`` axis to the mesh: batch
shards over dp, each dp-row runs its own pipeline, and XLA inserts the grad
psum across dp (see test_parallel.py).
"""

from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..utils.jax_compat import shard_map


def stage_pspec(n_dims: int, axis: str = "pp") -> P:
    """Spec sharding a layer-stacked parameter's leading dim over stages."""
    return P(axis, *([None] * (n_dims - 1)))


def stacked_param_pspecs(params: Any, axis: str = "pp") -> Any:
    """PartitionSpec pytree placing every layer-stacked leaf on its stage."""
    return jax.tree.map(lambda p: stage_pspec(p.ndim, axis), params)


def pipeline_blocks(
    block_fn: Callable[[Any, Any], Any],
    stacked_params: Any,
    x: jnp.ndarray,
    mesh: Mesh,
    axis: str = "pp",
    n_microbatches: int = 0,
    batch_axes: tuple = (),
    with_aux: bool = False,
):
    """Run ``x`` through L stacked layers pipelined over the ``axis`` stages.

    block_fn(x_mb, layer) applies ONE layer (a pytree slice of
    ``stacked_params`` at leading index l) to a microbatch activation.
    stacked_params: pytree with leading dim L (L % n_stages == 0), sharded
    over ``axis``. x: [B, ...] activations (replicated over ``axis``;
    optionally sharded over ``batch_axes`` — e.g. ("dp",) — in which case B
    here is the per-shard batch). Returns [B, ...] like a plain layer scan.

    with_aux: block_fn returns (h, aux_scalar) per layer — e.g. the MoE
    load-balancing loss — and pipeline_blocks returns (out, mean_aux).
    Aux from bubble steps (fill/flush garbage microbatches) is masked out.

    Schedule: step t of M+S-1 —
      stage 0 consumes microbatch min(t, M-1); stage s consumes what stage
      s-1 produced at t-1 (delivered by ppermute); stage S-1's outputs for
      t >= S-1 are microbatch t-(S-1)'s result. Bubble fraction is the GPipe
      (S-1)/(M+S-1).
    """
    S = mesh.shape[axis]
    if n_microbatches <= 0:
        n_microbatches = S
    M = n_microbatches
    B = x.shape[0]
    # the schedule slices the PER-SHARD batch into microbatches: validate
    # against the shard size, not the global batch
    shards = 1
    for a in batch_axes:
        shards *= mesh.shape[a]
    if B % shards != 0:
        raise ValueError(
            f"batch {B} not divisible over batch_axes {batch_axes} "
            f"({shards} shards)")
    if (B // shards) % M != 0:
        raise ValueError(
            f"per-shard batch {B // shards} (batch {B} over {shards} "
            f"{batch_axes} shards) not divisible by {M} microbatches")

    bspec = P(batch_axes if batch_axes else None)
    param_specs = stacked_param_pspecs(stacked_params, axis)
    out_specs = (bspec, P()) if with_aux else bspec

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(param_specs, bspec),
        out_specs=out_specs,
    )
    def run(params_local, x_local):
        stage = lax.axis_index(axis)
        b = x_local.shape[0]
        mbs = x_local.reshape(M, b // M, *x_local.shape[1:])

        def stage_apply(h):
            def body(h, layer):
                if with_aux:
                    h, aux = block_fn(h, layer)
                    return h, aux
                return block_fn(h, layer), jnp.float32(0.0)

            h, layer_aux = lax.scan(body, h, params_local)
            return h, jnp.sum(layer_aux)

        def step(carry, t):
            state, outputs, aux_sum = carry
            # stage 0 injects microbatch t (clamped during the flush tail)
            x_t = lax.dynamic_index_in_dim(
                mbs, jnp.clip(t, 0, M - 1), 0, keepdims=False)
            h_in = jnp.where(stage == 0, x_t, state)
            y, aux = stage_apply(h_in)
            # this stage processes microbatch t-stage; only those steps
            # carry real data (fill/flush steps see garbage activations)
            mb = t - stage
            real = (mb >= 0) & (mb < M)
            aux_sum = aux_sum + jnp.where(real, aux, 0.0)
            # the last stage emits microbatch t-(S-1) during the drain
            out_t = t - (S - 1)
            valid = (out_t >= 0) & (stage == S - 1)
            safe_t = jnp.clip(out_t, 0, M - 1)
            prev = lax.dynamic_index_in_dim(outputs, safe_t, 0,
                                            keepdims=False)
            outputs = lax.dynamic_update_index_in_dim(
                outputs, jnp.where(valid, y, prev), safe_t, 0)
            # hand this stage's activation to the next stage over ICI
            state = lax.ppermute(
                y, axis, [(i, (i + 1) % S) for i in range(S)])
            return (state, outputs, aux_sum), None

        state0 = jnp.zeros_like(mbs[0])
        outputs0 = jnp.zeros_like(mbs)
        (_, outputs, aux_sum), _ = lax.scan(
            step, (state0, outputs0, jnp.float32(0.0)),
            jnp.arange(M + S - 1))
        # results live on the last stage only; psum broadcasts them so the
        # caller sees a pp-replicated activation (zeros elsewhere)
        outputs = jnp.where(stage == S - 1, outputs, 0)
        outputs = lax.psum(outputs, axis)
        out = outputs.reshape(b, *x_local.shape[1:])
        if with_aux:
            # sum over stages (each stage saw its own layers), mean over
            # the M microbatches, the L/S layers per stage, and any batch
            # shards (each dp shard routed different tokens)
            total_aux = lax.psum(aux_sum, axis)
            for a in batch_axes:
                total_aux = lax.pmean(total_aux, a)
            L = jax.tree.leaves(params_local)[0].shape[0] * S
            return out, total_aux / (M * L)
        return out

    return run(stacked_params, x)


# ---------------------------------------------------------------- LM wiring
def pipeline_forward(params, tokens, cfg, mesh: Mesh, axis: str = "pp",
                     n_microbatches: int = 0, batch_axes: tuple = ()):
    """TransformerLM forward with the block stack pipelined over ``axis``.

    Embedding and head are small next to the block stack; they run
    replicated over pp (sharded over ``batch_axes`` if given), while the
    [L, ...] layer stack streams microbatches through the stages.
    Returns (logits, aux) — aux is the MoE load-balancing loss (0.0 for
    dense configs).
    """
    from ..models import gpt

    x = params["tok_embed"][tokens].astype(cfg.dtype)

    def block(h, layer):
        h, _, moe_aux = gpt.apply_block_with_aux(h, layer, cfg)
        return h, moe_aux

    x, aux = pipeline_blocks(block, params["layers"], x, mesh, axis=axis,
                             n_microbatches=n_microbatches,
                             batch_axes=batch_axes, with_aux=True)
    x = gpt._rmsnorm(x, params["final_ln"])
    logits = lax.dot_general(
        x, params["lm_head"].astype(cfg.dtype),
        (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    return logits, aux


def pipeline_loss_fn(params, batch, cfg, mesh: Mesh, axis: str = "pp",
                     n_microbatches: int = 0, batch_axes: tuple = ()):
    """Drop-in for models.gpt.loss_fn with a pipelined block stack
    (including the weighted MoE aux for expert configs)."""
    logits, aux = pipeline_forward(params, batch["tokens"], cfg, mesh,
                                   axis, n_microbatches, batch_axes)
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    take = jnp.take_along_axis(logits, batch["targets"][..., None],
                               axis=-1)[..., 0]
    loss = jnp.mean(lse - take)
    if cfg.n_experts > 0:
        loss = loss + cfg.moe_aux_weight * aux
    return loss
