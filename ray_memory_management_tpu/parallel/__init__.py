"""Parallelism strategy library: meshes + sharding presets (DP/FSDP/TP/SP/PP)."""

from .mesh import cpu_mesh, local_tpu_mesh, make_mesh  # noqa: F401
from .pipeline import (  # noqa: F401
    pipeline_blocks,
    pipeline_forward,
    pipeline_loss_fn,
    stacked_param_pspecs,
)
from .sharding import (  # noqa: F401
    batch_pspec,
    make_train_step,
    param_pspecs,
    shard_pytree,
)
