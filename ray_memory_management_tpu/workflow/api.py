"""Workflow API: durable DAG execution with resume.

The reference's workflow library (python/ray/workflow/ —
``WorkflowExecutor`` at workflow_executor.py:32, DAG/state rebuild in
workflow_state_from_{dag,storage}.py, event listeners in
event_listener.py). Surface:

    @workflow.step
    def fetch(url): ...

    dag = process.step(fetch.step(url))
    result = workflow.run(dag, workflow_id="etl-1")
    # crash mid-run → workflow.resume("etl-1") re-executes ONLY the
    # steps whose results never committed to storage.

Each step runs as a cluster task; committed results are pickled into
workflow storage keyed by a deterministic step id, so resume is
idempotent across drivers.
"""

from __future__ import annotations

import hashlib
import time
from typing import Any, Callable, Dict, List, Optional

from .. import api
from .. import serialization as ser
from .storage import WorkflowStorage, list_workflows

class WorkflowCancelledError(RuntimeError):
    """Raised inside a running workflow when cancel() flipped its
    status (the reference's WorkflowCancellationError)."""


RUNNING = "RUNNING"
SUCCESS = "SUCCESS"
FAILED = "FAILED"
CANCELED = "CANCELED"


class StepNode:
    """One node of a workflow DAG (the reference's DAGNode bound to a
    step function)."""

    def __init__(self, fn: Callable, args: tuple, kwargs: dict,
                 options: dict):
        self.fn = fn
        self.args = args
        self.kwargs = kwargs
        self.options = dict(options)
        self.name = options.get("name") or getattr(
            fn, "__name__", "step")

    def step_id(self, cache: Dict[int, str]) -> str:
        """Deterministic content-derived id: step name + the ids of
        upstream steps + a digest of the literal args. Re-running the
        same DAG yields the same ids, which is what makes storage lookups
        on resume hit. Positional and keyword slots hash with distinct
        markers so ``f.step(('k', 1))`` and ``f.step(k=1)`` never
        collide."""
        if id(self) in cache:
            return cache[id(self)]
        h = hashlib.sha256(self.name.encode())

        def hash_value(v):
            if isinstance(v, StepNode):
                h.update(b"\x02" + v.step_id(cache).encode())
            else:
                try:
                    h.update(ser.dumps(v))
                except Exception:
                    h.update(repr(v).encode())

        for a in self.args:
            h.update(b"\x00arg")
            hash_value(a)
        for k, v in sorted(self.kwargs.items()):
            h.update(b"\x01kw:" + k.encode())
            hash_value(v)
        sid = f"{self.name}-{h.hexdigest()[:16]}"
        cache[id(self)] = sid
        return sid


class WorkflowStepFunction:
    """``@workflow.step`` wrapper: ``.step(*args)`` builds a DAG node;
    ``.options(...)`` sets per-step retry/naming."""

    def __init__(self, fn: Callable, **options):
        self.fn = fn
        self._options = options

    def options(self, *, name: Optional[str] = None,
                max_retries: Optional[int] = None,
                catch_exceptions: Optional[bool] = None,
                num_cpus: Optional[float] = None,
                num_tpus: Optional[float] = None) -> "WorkflowStepFunction":
        merged = dict(self._options)
        for k, v in (("name", name), ("max_retries", max_retries),
                     ("catch_exceptions", catch_exceptions),
                     ("num_cpus", num_cpus), ("num_tpus", num_tpus)):
            if v is not None:
                merged[k] = v
        return WorkflowStepFunction(self.fn, **merged)

    def step(self, *args, **kwargs) -> StepNode:
        return StepNode(self.fn, args, kwargs, self._options)

    def __call__(self, *args, **kwargs):
        return self.fn(*args, **kwargs)


def step(fn: Optional[Callable] = None, **options):
    """``@workflow.step`` / ``@workflow.step(max_retries=3)``."""
    if fn is not None:
        return WorkflowStepFunction(fn)
    return lambda f: WorkflowStepFunction(f, **options)


# -------------------------------------------------------------- execution
class _Executor:
    """Depth-first DAG executor with storage commit per step
    (workflow_executor.py:32; recovery = skip committed steps)."""

    def __init__(self, store: WorkflowStorage):
        self.store = store
        self.cache: Dict[int, str] = {}
        self._memo: Dict[str, Any] = {}

    def execute(self, node: Any) -> Any:
        if not isinstance(node, StepNode):
            return node
        sid = node.step_id(self.cache)
        if sid in self._memo:
            return self._memo[sid]
        if self.store.has_step_result(sid):
            result = self.store.load_step_result(sid)
            self._memo[sid] = result
            return result
        # cancellation is checked at step boundaries — AFTER the memo /
        # committed lookups (cached hits cost no status read) and again
        # after argument resolution below: cancel() from another
        # thread/process flips the stored status and the next dispatch
        # aborts; committed steps stay committed for a later resume
        if self.store.get_status() == CANCELED:
            raise WorkflowCancelledError(self.store.workflow_id)
        args = [self.execute(a) for a in node.args]
        kwargs = {k: self.execute(v) for k, v in node.kwargs.items()}
        # re-check AFTER argument resolution: a cancel landing while a
        # child step ran must stop the parent from dispatching
        if self.store.get_status() == CANCELED:
            raise WorkflowCancelledError(self.store.workflow_id)
        t0 = time.time()
        opts = {
            "num_cpus": node.options.get("num_cpus", 1),
            "max_retries": node.options.get("max_retries", 3),
            "retry_exceptions": True,
        }
        if node.options.get("num_tpus"):
            opts["num_tpus"] = node.options["num_tpus"]
        remote_fn = api.remote(node.fn).options(**opts)
        attempts = 1
        try:
            result = api.get(remote_fn.remote(*args, **kwargs))
            if node.options.get("catch_exceptions"):
                result = (result, None)
        except Exception as e:
            if node.options.get("catch_exceptions"):
                result = (None, e)
            else:
                raise
        # a nested StepNode return value means "continue with this DAG"
        # (the reference's workflow continuation)
        if isinstance(result, StepNode):
            result = self.execute(result)
        self.store.save_step_result(sid, result, meta={
            "name": node.name, "attempts": attempts,
            "wall_s": time.time() - t0,
        })
        self._memo[sid] = result
        return result


def run(dag: StepNode, *, workflow_id: Optional[str] = None) -> Any:
    """Execute a workflow DAG durably; returns the root step's result."""
    if workflow_id is None:
        workflow_id = f"workflow-{int(time.time() * 1000):x}"
    store = WorkflowStorage(workflow_id)
    ex = _Executor(store)
    store.set_status(RUNNING)
    store.set_output_step(dag.step_id(ex.cache))
    try:
        result = ex.execute(dag)
    except WorkflowCancelledError:
        raise  # status is already CANCELED; do not overwrite with FAILED
    except BaseException:
        # a cancel racing the failure keeps CANCELED (atomic transition)
        store.transition_status(FAILED, expect={RUNNING})
        raise
    # cancel-wins: if cancel() landed while the FINAL step ran (no later
    # boundary existed to observe it), the caller still gets the
    # cancellation they asked for — the committed results make a rerun
    # complete instantly. A failed transition for any OTHER reason (a
    # concurrent driver of the same workflow id finished first and wrote
    # a terminal status) is a success: the result is committed.
    if not store.transition_status(SUCCESS, expect={RUNNING}):
        if store.get_status() == CANCELED:
            raise WorkflowCancelledError(workflow_id)
    return result


def run_async(dag: StepNode, *, workflow_id: Optional[str] = None):
    """Run in a background thread; returns a concurrent Future."""
    from concurrent.futures import ThreadPoolExecutor

    ex = ThreadPoolExecutor(1, thread_name_prefix="workflow")
    fut = ex.submit(run, dag, workflow_id=workflow_id)
    ex.shutdown(wait=False)
    return fut


def resume(workflow_id: str) -> Any:
    """Re-run a workflow from storage: committed steps load, missing
    steps (and only those) execute (workflow_state_from_storage.py)."""
    store = WorkflowStorage(workflow_id)
    status = store.get_status()
    if status is None:
        raise ValueError(f"no workflow {workflow_id!r} in storage")
    if status == SUCCESS:
        return get_output(workflow_id)
    raise ValueError(
        "resume() needs the original DAG in this runtime; call "
        "run(dag, workflow_id=...) again — committed steps are skipped"
    )


def rerun(dag: StepNode, *, workflow_id: str) -> Any:
    """Explicit resume-with-DAG: identical to run(); committed steps are
    loaded from storage instead of re-executing."""
    return run(dag, workflow_id=workflow_id)


def cancel(workflow_id: str) -> None:
    """Request cancellation: the run aborts at its next step boundary
    (in-flight steps finish; committed steps stay committed, so a later
    ``run(dag, workflow_id=...)`` resumes past them). Only a RUNNING
    workflow can be canceled: terminal statuses stay put (a late cancel
    must not relabel a completed run), and an unknown id raises without
    leaving a phantom directory behind."""
    if workflow_id not in list_workflows():
        raise ValueError(f"no workflow {workflow_id!r} in storage")
    # atomic RUNNING->CANCELED: a cancel racing the run's completion
    # write must never relabel a finished workflow
    WorkflowStorage(workflow_id).transition_status(
        CANCELED, expect={RUNNING})


def get_status(workflow_id: str) -> Optional[str]:
    return WorkflowStorage(workflow_id).get_status()


def get_output(workflow_id: str) -> Any:
    store = WorkflowStorage(workflow_id)
    sid = store.get_output_step()
    if sid is None or not store.has_step_result(sid):
        raise ValueError(f"workflow {workflow_id!r} has no output yet")
    return store.load_step_result(sid)


def list_all() -> List[tuple]:
    return [(wid, get_status(wid)) for wid in list_workflows()]


def delete(workflow_id: str) -> None:
    import shutil

    shutil.rmtree(WorkflowStorage(workflow_id).root, ignore_errors=True)


# ---------------------------------------------------------------- events
class EventListener:
    """Event-listener contract (reference event_listener.py): subclass
    and implement poll_for_event; use with ``wait_for_event``."""

    async def poll_for_event(self, *args, **kwargs) -> Any:
        raise NotImplementedError


def wait_for_event(listener_cls, *args, poll_interval_s: float = 0.1,
                   timeout_s: float = 3600.0, **kwargs) -> StepNode:
    """A DAG node that resolves when the listener's event fires. The
    committed event value is durable: a resumed workflow does not
    re-wait."""

    def _wait():
        import asyncio

        listener = listener_cls()
        return asyncio.new_event_loop().run_until_complete(
            asyncio.wait_for(
                listener.poll_for_event(*args, **kwargs), timeout_s))

    # the listener args live in the closure, invisible to step_id — fold
    # their digest into the step name so distinct waits get distinct ids
    arg_digest = hashlib.sha256(
        repr((args, sorted(kwargs.items()))).encode()).hexdigest()[:8]
    _wait.__name__ = (
        f"wait_for_event_{listener_cls.__name__}_{arg_digest}")
    return WorkflowStepFunction(_wait).step()


def sleep(duration_s: float) -> StepNode:
    """Durable sleep step (workflow.sleep in the reference)."""

    def _sleep():
        time.sleep(duration_s)
        return duration_s

    _sleep.__name__ = f"sleep_{duration_s}"
    return WorkflowStepFunction(_sleep).step()
