"""Workflow library: durable DAG execution on storage.

The reference's ``ray.workflow`` (python/ray/workflow/ — executor,
storage-backed state, resume, event listeners).
"""

from .api import (  # noqa: F401
    CANCELED,
    FAILED,
    RUNNING,
    SUCCESS,
    EventListener,
    StepNode,
    WorkflowStepFunction,
    cancel,
    WorkflowCancelledError,
    delete,
    get_output,
    get_status,
    list_all,
    rerun,
    resume,
    run,
    run_async,
    sleep,
    step,
    wait_for_event,
)
from .storage import get_storage, set_storage  # noqa: F401
