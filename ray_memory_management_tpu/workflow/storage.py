"""Durable workflow storage.

The reference persists workflow DAG state to pluggable storage and
rebuilds execution state from it on resume
(python/ray/workflow/workflow_state_from_storage.py,
workflow_storage.py). Here: a filesystem layout, one directory per
workflow, one pickle per completed step — the FileSystemStorage tier of
the reference's storage stack (S3/GCS layers mount the same interface
over a remote path).

Layout::

    <base>/<workflow_id>/
        status            # RUNNING | SUCCESS | FAILED | CANCELED
        output            # step_id of the DAG root
        steps/<step_id>/
            result.pkl    # present iff the step committed
            meta.json     # name, attempt count, wall time
"""

from __future__ import annotations

import json
import os
import pickle
import tempfile
from typing import Any, List, Optional

_DEFAULT_BASE = os.path.join(tempfile.gettempdir(), "rmt_workflows")
_base_dir = os.environ.get("RMT_WORKFLOW_STORAGE", _DEFAULT_BASE)


def set_storage(path: str) -> None:
    global _base_dir
    _base_dir = path


def get_storage() -> str:
    return _base_dir


class WorkflowStorage:
    def __init__(self, workflow_id: str, base: Optional[str] = None):
        self.workflow_id = workflow_id
        self.root = os.path.join(base or _base_dir, workflow_id)
        os.makedirs(os.path.join(self.root, "steps"), exist_ok=True)

    # -- workflow level ------------------------------------------------------
    def set_status(self, status: str) -> None:
        self._atomic_write(os.path.join(self.root, "status"),
                           status.encode())

    def get_status(self) -> Optional[str]:
        try:
            with open(os.path.join(self.root, "status")) as f:
                return f.read().strip()
        except FileNotFoundError:
            return None

    def transition_status(self, to: str, expect) -> bool:
        """Atomically move status to ``to`` iff the current status is in
        ``expect`` (an fcntl lock serializes racing writers — e.g. a
        cancel() racing the run's own completion write). Returns whether
        the transition happened."""
        import fcntl

        lock_path = os.path.join(self.root, ".status.lock")
        with open(lock_path, "w") as lf:
            fcntl.flock(lf, fcntl.LOCK_EX)
            if self.get_status() not in expect:
                return False
            self._atomic_write(os.path.join(self.root, "status"),
                               to.encode())
            return True

    def set_output_step(self, step_id: str) -> None:
        self._atomic_write(os.path.join(self.root, "output"),
                           step_id.encode())

    def get_output_step(self) -> Optional[str]:
        try:
            with open(os.path.join(self.root, "output")) as f:
                return f.read().strip()
        except FileNotFoundError:
            return None

    # -- step level ----------------------------------------------------------
    def _step_dir(self, step_id: str) -> str:
        return os.path.join(self.root, "steps", step_id)

    def has_step_result(self, step_id: str) -> bool:
        return os.path.exists(
            os.path.join(self._step_dir(step_id), "result.pkl"))

    def save_step_result(self, step_id: str, result: Any,
                         meta: Optional[dict] = None) -> None:
        d = self._step_dir(step_id)
        os.makedirs(d, exist_ok=True)
        if meta is not None:
            self._atomic_write(os.path.join(d, "meta.json"),
                               json.dumps(meta).encode())
        # result.pkl lands last and atomically: its presence IS the commit
        self._atomic_write(os.path.join(d, "result.pkl"),
                           pickle.dumps(result))

    def load_step_result(self, step_id: str) -> Any:
        with open(os.path.join(self._step_dir(step_id), "result.pkl"),
                  "rb") as f:
            return pickle.load(f)

    def list_steps(self) -> List[str]:
        steps_dir = os.path.join(self.root, "steps")
        return sorted(os.listdir(steps_dir)) if os.path.isdir(steps_dir) \
            else []

    def _atomic_write(self, path: str, data: bytes) -> None:
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)


def list_workflows(base: Optional[str] = None) -> List[str]:
    root = base or _base_dir
    return sorted(os.listdir(root)) if os.path.isdir(root) else []
