"""Tune-equivalent: hyperparameter search over trial actors.

Reference surface: python/ray/tune (Tuner tune/tuner.py:32, TrialRunner
tune/execution/trial_runner.py:236, Trainable tune/trainable/trainable.py:65,
search spaces tune/search/sample.py, schedulers tune/schedulers/).
"""

from .search import (
    BasicVariantGenerator,
    RandomSearch,
    TPESearch,
    Searcher,
    choice,
    grid_search,
    loguniform,
    quniform,
    randint,
    sample_from,
    uniform,
)
from .schedulers import (
    ASHAScheduler,
    FIFOScheduler,
    MedianStoppingRule,
    PopulationBasedTraining,
    TrialScheduler,
)
from .syncer import Syncer
from .trainable import FunctionTrainable, Trainable, wrap_function
from .tuner import ResultGrid, TrialResult, TuneConfig, Tuner

__all__ = [
    "Tuner", "TuneConfig", "ResultGrid", "TrialResult",
    "Trainable", "FunctionTrainable", "wrap_function",
    "TrialScheduler", "FIFOScheduler", "ASHAScheduler",
    "MedianStoppingRule",
    "PopulationBasedTraining",
    "Searcher", "RandomSearch", "TPESearch", "BasicVariantGenerator",
    "uniform", "quniform", "loguniform", "randint", "choice",
    "grid_search", "sample_from",
]
