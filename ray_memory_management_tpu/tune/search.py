"""Search spaces and search algorithms.

Mirrors the reference's tune search layer (python/ray/tune/search/):
sample-space primitives (tune/search/sample.py — uniform/loguniform/choice/
randint/grid_search), `BasicVariantGenerator` (tune/search/basic_variant.py)
which crosses grid axes and samples stochastic axes, and the `Searcher`
suggest/on_trial_complete contract (tune/search/searcher.py) used by advanced
algorithms. This build keeps the same surface but is dependency-free.
"""

from __future__ import annotations

import itertools
import random
from typing import Any, Callable, Dict, List, Optional


class Domain:
    """A sampleable hyperparameter domain."""

    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError


class Uniform(Domain):
    def __init__(self, low: float, high: float):
        self.low, self.high = low, high

    def sample(self, rng):
        return rng.uniform(self.low, self.high)


class QUniform(Domain):
    def __init__(self, low: float, high: float, q: float):
        self.low, self.high, self.q = low, high, q

    def sample(self, rng):
        v = rng.uniform(self.low, self.high)
        return round(round(v / self.q) * self.q, 10)


class LogUniform(Domain):
    def __init__(self, low: float, high: float):
        import math

        self.log_low, self.log_high = math.log(low), math.log(high)

    def sample(self, rng):
        import math

        return math.exp(rng.uniform(self.log_low, self.log_high))


class RandInt(Domain):
    def __init__(self, low: int, high: int):
        self.low, self.high = low, high

    def sample(self, rng):
        return rng.randrange(self.low, self.high)


class Choice(Domain):
    def __init__(self, categories):
        self.categories = list(categories)

    def sample(self, rng):
        return rng.choice(self.categories)


class Function(Domain):
    def __init__(self, fn: Callable):
        self.fn = fn

    def sample(self, rng):
        return self.fn(None)


class GridSearch:
    """Marker for exhaustive axes (tune/search/sample.py grid_search)."""

    def __init__(self, values):
        self.values = list(values)


def uniform(low: float, high: float) -> Uniform:
    return Uniform(low, high)


def quniform(low: float, high: float, q: float) -> QUniform:
    return QUniform(low, high, q)


def loguniform(low: float, high: float) -> LogUniform:
    return LogUniform(low, high)


def randint(low: int, high: int) -> RandInt:
    return RandInt(low, high)


def choice(categories) -> Choice:
    return Choice(categories)


def sample_from(fn: Callable) -> Function:
    return Function(fn)


def grid_search(values) -> GridSearch:
    return GridSearch(values)


def _split_space(space: Dict[str, Any]):
    """Partition a (possibly nested) param space into grid axes and the
    sampled/constant remainder. Returns (grid_paths, template) where
    grid_paths is [(key_path, values)]."""
    grid: List = []

    def walk(node, path):
        if isinstance(node, GridSearch):
            grid.append((path, node.values))
            return None
        if isinstance(node, dict):
            return {k: walk(v, path + (k,)) for k, v in node.items()}
        return node

    template = walk(space, ())
    return grid, template


def _materialize(node, rng: random.Random):
    if isinstance(node, Domain):
        return node.sample(rng)
    if isinstance(node, dict):
        return {k: _materialize(v, rng) for k, v in node.items()}
    return node


def _set_path(cfg: dict, path, value):
    cur = cfg
    for key in path[:-1]:
        cur = cur.setdefault(key, {})
    cur[path[-1]] = value


class BasicVariantGenerator:
    """Cross-product of grid axes x ``num_samples`` random draws
    (tune/search/basic_variant.py semantics: num_samples multiplies the
    grid)."""

    def __init__(self, space: Dict[str, Any], num_samples: int = 1,
                 seed: Optional[int] = None):
        self.space = space
        self.num_samples = num_samples
        self.rng = random.Random(seed)

    def variants(self) -> List[Dict[str, Any]]:
        grid_axes, _ = _split_space(self.space)
        out: List[Dict[str, Any]] = []
        grid_combos: List[List] = (
            [list(combo) for combo in
             itertools.product(*[vals for _, vals in grid_axes])]
            if grid_axes else [[]]
        )
        for _ in range(self.num_samples):
            for combo in grid_combos:
                _, template = _split_space(self.space)
                cfg = _materialize(template, self.rng)
                if not isinstance(cfg, dict):
                    cfg = {}
                for (path, _vals), value in zip(grid_axes, combo):
                    _set_path(cfg, path, value)
                out.append(cfg)
        return out


class Searcher:
    """suggest/on_trial_complete contract (tune/search/searcher.py)."""

    def __init__(self, metric: str = "loss", mode: str = "min"):
        self.metric = metric
        self.mode = mode

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        raise NotImplementedError

    def on_trial_result(self, trial_id: str, result: Dict[str, Any]) -> None:
        pass

    def on_trial_complete(self, trial_id: str,
                          result: Optional[Dict[str, Any]] = None,
                          error: bool = False) -> None:
        pass


class RandomSearch(Searcher):
    """Pure random sampling searcher over a Domain space."""

    def __init__(self, space: Dict[str, Any], metric: str = "loss",
                 mode: str = "min", seed: Optional[int] = None):
        super().__init__(metric, mode)
        self.space = space
        self.rng = random.Random(seed)

    def suggest(self, trial_id: str) -> Dict[str, Any]:
        _, template = _split_space(self.space)
        return _materialize(template, self.rng)


class TPESearch(Searcher):
    """Tree-structured Parzen Estimator (Bergstra et al. 2011) — the
    algorithm behind the reference's hyperopt integration
    (tune/search/hyperopt/hyperopt_search.py), implemented in-repo.

    After ``n_initial_points`` random trials, completed observations
    split into a good fraction (best ``gamma`` quantile by the metric)
    and the rest; for each dimension, candidates are drawn from a kernel
    density over the GOOD values and ranked by the density ratio
    l(x)/g(x) (hyperopt's factorized per-dimension form). Numeric
    domains (uniform / loguniform / quniform / randint) get Gaussian
    kernels (log-space for loguniform); Choice domains get smoothed
    category frequencies. Other domains fall back to random sampling.

    Model-based search needs results fed back: the Tuner runs searcher
    trials in waves and calls on_trial_complete between waves."""

    def __init__(self, space: Dict[str, Any], metric: str = "loss",
                 mode: str = "min", seed: Optional[int] = None,
                 n_initial_points: int = 10, gamma: float = 0.25,
                 n_candidates: int = 24):
        super().__init__(metric, mode)
        grid_axes, _ = _split_space(space)
        if grid_axes:
            raise ValueError(
                "TPESearch does not support grid_search axes (they would "
                "silently materialize as None); use plain Domains, or "
                "keep grid axes on the BasicVariantGenerator path")
        self.space = space
        self.rng = random.Random(seed)
        self.n_initial_points = n_initial_points
        self.gamma = gamma
        self.n_candidates = n_candidates
        self._suggested: Dict[str, Dict[str, Any]] = {}
        self._obs: List = []  # (score, flat_config)

    # -- observation feed -----------------------------------------------------
    def on_trial_complete(self, trial_id, result=None,
                          error: bool = False) -> None:
        cfg = self._suggested.pop(trial_id, None)
        if error or cfg is None or not result \
                or self.metric not in result:
            return
        score = float(result[self.metric])
        if self.mode == "max":
            score = -score
        self._obs.append((score, cfg))

    # -- suggestion -----------------------------------------------------------
    def suggest(self, trial_id: str) -> Dict[str, Any]:
        _, template = _split_space(self.space)
        if len(self._obs) < self.n_initial_points \
                or not isinstance(template, dict):
            cfg = _materialize(template, self.rng)
        else:
            ranked = sorted(self._obs, key=lambda t: t[0])
            n_good = max(1, int(len(ranked) * self.gamma))
            good = [c for _, c in ranked[:n_good]]
            bad = [c for _, c in ranked[n_good:]] or good
            cfg = {k: self._suggest_dim(k, v, good, bad)
                   for k, v in template.items()}
        self._suggested[trial_id] = cfg
        return cfg

    def _suggest_dim(self, key, domain, good, bad):
        import math

        if isinstance(domain, Choice):
            # l(x): smoothed category counts among good observations
            weights = []
            for cat in domain.categories:
                g = sum(1 for c in good if c.get(key) == cat) + 1.0
                b = sum(1 for c in bad if c.get(key) == cat) + 1.0
                weights.append(g / b)
            return self.rng.choices(domain.categories, weights)[0]
        if isinstance(domain, (Uniform, QUniform, RandInt, LogUniform)):
            log = isinstance(domain, LogUniform)
            if log:
                lo, hi = domain.log_low, domain.log_high
            elif isinstance(domain, RandInt):
                # randrange semantics: high is EXCLUSIVE — the largest
                # valid integer is high - 1, and a clamped candidate must
                # never round outside the declared domain
                lo, hi = domain.low, domain.high - 1
            else:
                lo, hi = domain.low, domain.high

            def val(c):
                v = float(c.get(key))
                return math.log(v) if log else v

            gvals = [val(c) for c in good if c.get(key) is not None]
            bvals = [val(c) for c in bad if c.get(key) is not None]
            if not gvals:
                return domain.sample(self.rng)
            # bandwidth follows the empirical spread of the GOOD set
            # (self-tightening as the search concentrates), floored at a
            # small fraction of the range so the kernel never collapses
            if len(gvals) > 1:
                mean = sum(gvals) / len(gvals)
                spread = (sum((v - mean) ** 2 for v in gvals)
                          / len(gvals)) ** 0.5
            else:
                spread = (hi - lo) / 4.0
            sigma = max(spread, (hi - lo) * 1e-3, 1e-12)

            # both densities carry a uniform prior component (weight 1):
            # in unexplored regions the ratio tends to 1, so exploration
            # survives even when the good set has collapsed into a narrow
            # (possibly wrong) cluster — the standard TPE prior smoothing
            prior = 1.0 / max(hi - lo, 1e-12)
            norm = 1.0 / (sigma * math.sqrt(2 * math.pi))

            def density(x, centers):
                k = sum(math.exp(-0.5 * ((x - m) / sigma) ** 2)
                        for m in centers) * norm
                return (k + prior) / (len(centers) + 1)

            best_x, best_ratio = None, -1.0
            for i in range(self.n_candidates):
                if i % 4 == 3:  # a quarter of candidates probe uniformly
                    x = self.rng.uniform(lo, hi)
                else:
                    x = min(max(self.rng.gauss(self.rng.choice(gvals),
                                               sigma), lo), hi)
                ratio = density(x, gvals) / (density(x, bvals) + 1e-300)
                if ratio > best_ratio:
                    best_x, best_ratio = x, ratio
            x = math.exp(best_x) if log else best_x
            if isinstance(domain, QUniform):
                # mirror QUniform.sample exactly (incl. the float-noise
                # rounding) so model-phase values compare equal to
                # random-phase ones
                x = round(round(x / domain.q) * domain.q, 10)
            if isinstance(domain, RandInt):
                x = int(round(x))
            return x
        return _materialize(domain, self.rng)  # nested/unsupported: random
