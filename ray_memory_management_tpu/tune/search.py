"""Search spaces and search algorithms.

Mirrors the reference's tune search layer (python/ray/tune/search/):
sample-space primitives (tune/search/sample.py — uniform/loguniform/choice/
randint/grid_search), `BasicVariantGenerator` (tune/search/basic_variant.py)
which crosses grid axes and samples stochastic axes, and the `Searcher`
suggest/on_trial_complete contract (tune/search/searcher.py) used by advanced
algorithms. This build keeps the same surface but is dependency-free.
"""

from __future__ import annotations

import itertools
import random
from typing import Any, Callable, Dict, List, Optional


class Domain:
    """A sampleable hyperparameter domain."""

    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError


class Uniform(Domain):
    def __init__(self, low: float, high: float):
        self.low, self.high = low, high

    def sample(self, rng):
        return rng.uniform(self.low, self.high)


class QUniform(Domain):
    def __init__(self, low: float, high: float, q: float):
        self.low, self.high, self.q = low, high, q

    def sample(self, rng):
        v = rng.uniform(self.low, self.high)
        return round(round(v / self.q) * self.q, 10)


class LogUniform(Domain):
    def __init__(self, low: float, high: float):
        import math

        self.log_low, self.log_high = math.log(low), math.log(high)

    def sample(self, rng):
        import math

        return math.exp(rng.uniform(self.log_low, self.log_high))


class RandInt(Domain):
    def __init__(self, low: int, high: int):
        self.low, self.high = low, high

    def sample(self, rng):
        return rng.randrange(self.low, self.high)


class Choice(Domain):
    def __init__(self, categories):
        self.categories = list(categories)

    def sample(self, rng):
        return rng.choice(self.categories)


class Function(Domain):
    def __init__(self, fn: Callable):
        self.fn = fn

    def sample(self, rng):
        return self.fn(None)


class GridSearch:
    """Marker for exhaustive axes (tune/search/sample.py grid_search)."""

    def __init__(self, values):
        self.values = list(values)


def uniform(low: float, high: float) -> Uniform:
    return Uniform(low, high)


def quniform(low: float, high: float, q: float) -> QUniform:
    return QUniform(low, high, q)


def loguniform(low: float, high: float) -> LogUniform:
    return LogUniform(low, high)


def randint(low: int, high: int) -> RandInt:
    return RandInt(low, high)


def choice(categories) -> Choice:
    return Choice(categories)


def sample_from(fn: Callable) -> Function:
    return Function(fn)


def grid_search(values) -> GridSearch:
    return GridSearch(values)


def _split_space(space: Dict[str, Any]):
    """Partition a (possibly nested) param space into grid axes and the
    sampled/constant remainder. Returns (grid_paths, template) where
    grid_paths is [(key_path, values)]."""
    grid: List = []

    def walk(node, path):
        if isinstance(node, GridSearch):
            grid.append((path, node.values))
            return None
        if isinstance(node, dict):
            return {k: walk(v, path + (k,)) for k, v in node.items()}
        return node

    template = walk(space, ())
    return grid, template


def _materialize(node, rng: random.Random):
    if isinstance(node, Domain):
        return node.sample(rng)
    if isinstance(node, dict):
        return {k: _materialize(v, rng) for k, v in node.items()}
    return node


def _set_path(cfg: dict, path, value):
    cur = cfg
    for key in path[:-1]:
        cur = cur.setdefault(key, {})
    cur[path[-1]] = value


class BasicVariantGenerator:
    """Cross-product of grid axes x ``num_samples`` random draws
    (tune/search/basic_variant.py semantics: num_samples multiplies the
    grid)."""

    def __init__(self, space: Dict[str, Any], num_samples: int = 1,
                 seed: Optional[int] = None):
        self.space = space
        self.num_samples = num_samples
        self.rng = random.Random(seed)

    def variants(self) -> List[Dict[str, Any]]:
        grid_axes, _ = _split_space(self.space)
        out: List[Dict[str, Any]] = []
        grid_combos: List[List] = (
            [list(combo) for combo in
             itertools.product(*[vals for _, vals in grid_axes])]
            if grid_axes else [[]]
        )
        for _ in range(self.num_samples):
            for combo in grid_combos:
                _, template = _split_space(self.space)
                cfg = _materialize(template, self.rng)
                if not isinstance(cfg, dict):
                    cfg = {}
                for (path, _vals), value in zip(grid_axes, combo):
                    _set_path(cfg, path, value)
                out.append(cfg)
        return out


class Searcher:
    """suggest/on_trial_complete contract (tune/search/searcher.py)."""

    def __init__(self, metric: str = "loss", mode: str = "min"):
        self.metric = metric
        self.mode = mode

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        raise NotImplementedError

    def on_trial_result(self, trial_id: str, result: Dict[str, Any]) -> None:
        pass

    def on_trial_complete(self, trial_id: str,
                          result: Optional[Dict[str, Any]] = None,
                          error: bool = False) -> None:
        pass


class RandomSearch(Searcher):
    """Pure random sampling searcher over a Domain space."""

    def __init__(self, space: Dict[str, Any], metric: str = "loss",
                 mode: str = "min", seed: Optional[int] = None):
        super().__init__(metric, mode)
        self.space = space
        self.rng = random.Random(seed)

    def suggest(self, trial_id: str) -> Dict[str, Any]:
        _, template = _split_space(self.space)
        return _materialize(template, self.rng)
