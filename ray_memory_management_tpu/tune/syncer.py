"""Checkpoint sync to cloud storage — the reference's ``tune/syncer.py``.

The reference syncs each trial's checkpoint directory to a cloud
``upload_dir`` (``Syncer``/``SyncConfig``, tune/syncer.py:99) so an
experiment survives the loss of the head node's disk. Here trial
checkpoints are opaque blobs (the Trainable save() contract), so the
syncer is blob-level: every checkpoint uploads through the same
URI-scheme registry the spill tier uses (core/external_storage.py —
s3:// and gs:// built in, ``register_storage_scheme`` for anything
else), under a deterministic key layout:

    <upload_dir>/<hex(experiment/trial/checkpoint)>    (checkpoint blob)
    <upload_dir>/<hex(experiment/trial/.meta)>         (latest-pointer)

The latest-pointer makes recovery independent of local state: a fresh
process (or another host) constructs ``Syncer(upload_dir)`` and calls
``download(trial_id)`` with no manifest on disk. Both built-in storage
backends return URLs of the form ``<base>/<hex(object_id)>``, which is
what makes the deterministic layout possible; a custom scheme's storage
just has to keep ``spill(oid, ...)`` / ``restore(oid, url)``
deterministic in ``oid`` the same way.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional

from ..core.external_storage import ExternalStorage, storage_for_uri


class Syncer:
    """Blob-level checkpoint sync for one experiment."""

    def __init__(self, upload_dir: str, experiment: str):
        self.upload_dir = upload_dir.rstrip("/")
        self.experiment = experiment
        self.storage: ExternalStorage = storage_for_uri(upload_dir)

    # -- key layout -----------------------------------------------------------
    def _oid(self, trial_id: str, what: str) -> bytes:
        return f"{self.experiment}/{trial_id}/{what}".encode()

    def _url_for(self, oid: bytes) -> str:
        return f"{self.upload_dir}/{oid.hex()}"

    # -- upload ---------------------------------------------------------------
    def upload(self, trial_id: str, blob: bytes,
               iteration: Optional[int] = None) -> str:
        """Upload one checkpoint blob and advance the trial's
        latest-pointer; returns the checkpoint URL."""
        oid = self._oid(trial_id, "checkpoint")
        url = self.storage.spill(oid, memoryview(blob))
        meta = {"url": url, "iteration": iteration,
                "size": len(blob)}
        self.storage.spill(self._oid(trial_id, ".meta"),
                           memoryview(json.dumps(meta).encode()))
        return url

    # -- download -------------------------------------------------------------
    def meta(self, trial_id: str) -> Optional[Dict]:
        oid = self._oid(trial_id, ".meta")
        try:
            raw = self.storage.restore(oid, self._url_for(oid))
        except Exception:  # noqa: BLE001 — nothing uploaded yet
            return None
        return json.loads(bytes(raw))

    def download(self, trial_id: str) -> Optional[bytes]:
        """The trial's latest checkpoint blob, or None if never synced.
        Needs no local state — a fresh process recovers from the
        deterministic key layout alone."""
        m = self.meta(trial_id)
        if m is None:
            return None
        oid = self._oid(trial_id, "checkpoint")
        try:
            return bytes(self.storage.restore(oid, m["url"]))
        except Exception:  # noqa: BLE001
            return None

    def delete(self, trial_id: str) -> None:
        for what in ("checkpoint", ".meta"):
            oid = self._oid(trial_id, what)
            try:
                self.storage.delete(self._url_for(oid))
            except Exception:  # noqa: BLE001
                pass

    def trials_synced(self, trial_ids: List[str]) -> List[str]:
        return [t for t in trial_ids if self.meta(t) is not None]
