"""Trainable: the step/save/restore contract every trial actor implements.

Mirrors the reference's tune/trainable/trainable.py:65 (train:308,
save:436, restore:599) and the function-trainable wrapper
(tune/trainable/function_trainable.py): a function ``fn(config)`` that calls
``session.report(...)`` is adapted to the step-wise class contract by running
it on a background thread and treating each report as one training iteration.
"""

from __future__ import annotations

import os
import pickle
import queue
import shutil
import tempfile
import threading
import time
from typing import Any, Callable, Dict, Optional

RESULT_DONE = "done"
TRAINING_ITERATION = "training_iteration"


class Trainable:
    """Subclass contract: override setup/step/save_checkpoint/load_checkpoint.

    ``train()``/``save()``/``restore()``/``reset_config()``/``stop()`` are the
    driver-callable surface (invoked as actor methods by the trial runner).
    """

    def __init__(self, config: Optional[Dict[str, Any]] = None,
                 trial_info: Optional[Dict[str, Any]] = None):
        self.config = dict(config or {})
        self.trial_info = dict(trial_info or {})
        self.iteration = 0
        self._start_time = time.time()
        self.setup(self.config)

    # -- user overrides -------------------------------------------------------
    def setup(self, config: Dict[str, Any]) -> None:
        pass

    def step(self) -> Dict[str, Any]:
        raise NotImplementedError

    def save_checkpoint(self, checkpoint_dir: str) -> None:
        pass

    def load_checkpoint(self, checkpoint_dir: str) -> None:
        pass

    def cleanup(self) -> None:
        pass

    def reset_config(self, new_config: Dict[str, Any]) -> bool:
        """Return True if the trainable can hot-swap configs (PBT exploit
        without an actor restart — trainable.py reset semantics)."""
        return False

    # -- driver-callable surface ----------------------------------------------
    def train(self) -> Dict[str, Any]:
        result = self.step() or {}
        self.iteration += 1
        result.setdefault(TRAINING_ITERATION, self.iteration)
        result.setdefault("time_total_s", time.time() - self._start_time)
        result.setdefault(RESULT_DONE, False)
        result.setdefault("trial_id", self.trial_info.get("id", ""))
        return result

    def save(self) -> bytes:
        tmp = tempfile.mkdtemp(prefix="rmt_tune_ckpt_")
        try:
            self.save_checkpoint(tmp)
            files = {}
            for root, _dirs, names in os.walk(tmp):
                for name in names:
                    full = os.path.join(root, name)
                    files[os.path.relpath(full, tmp)] = open(full, "rb").read()
            return pickle.dumps({"files": files, "iteration": self.iteration})
        finally:
            shutil.rmtree(tmp, ignore_errors=True)

    def restore(self, blob: bytes) -> None:
        state = pickle.loads(blob)
        tmp = tempfile.mkdtemp(prefix="rmt_tune_ckpt_")
        try:
            for rel, data in state["files"].items():
                full = os.path.join(tmp, rel)
                os.makedirs(os.path.dirname(full), exist_ok=True)
                with open(full, "wb") as f:
                    f.write(data)
            self.load_checkpoint(tmp)
            self.iteration = state["iteration"]
        finally:
            shutil.rmtree(tmp, ignore_errors=True)

    def reset(self, new_config: Dict[str, Any]) -> bool:
        ok = self.reset_config(new_config)
        if ok:
            self.config = dict(new_config)
        return ok

    def stop(self) -> None:
        self.cleanup()


class FunctionTrainable(Trainable):
    """Adapts ``fn(config)`` + session.report to the step contract
    (function_trainable.py analog: fn runs on a thread; train() blocks until
    the next report or function exit)."""

    _fn: Optional[Callable] = None  # bound by wrap_function subclassing

    def setup(self, config: Dict[str, Any]) -> None:
        from ..train import session as session_mod

        self._session = session_mod.init_session(
            world_rank=0, world_size=1, checkpoint=None,
            trial_info=self.trial_info,
        )
        # The fn thread starts lazily on the first step() so a restore()
        # issued right after actor creation lands its checkpoint in the
        # session before user code runs (the reference resolves the same
        # race by passing the checkpoint into the session at start).
        self._thread: Optional[threading.Thread] = None

    def _run(self, config):
        s = self._session
        try:
            type(self)._fn(config)
        except BaseException as e:  # surfaced by train()
            s.error = e
        finally:
            s.finished.set()

    def step(self) -> Dict[str, Any]:
        s = self._session
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, args=(self.config,), daemon=True)
            self._thread.start()
        while True:
            try:
                item = s.queue.get(timeout=0.1)
                metrics = dict(item["metrics"])
                ckpt = item.get("checkpoint")
                if ckpt is not None:
                    self._latest_fn_ckpt = ckpt.to_bytes()
                return metrics
            except queue.Empty:
                if s.finished.is_set() and s.queue.empty():
                    if s.error is not None:
                        raise s.error
                    return {RESULT_DONE: True}

    def save_checkpoint(self, checkpoint_dir: str) -> None:
        blob = getattr(self, "_latest_fn_ckpt", None)
        if blob is not None:
            with open(os.path.join(checkpoint_dir, "fn_ckpt.bin"), "wb") as f:
                f.write(blob)

    def load_checkpoint(self, checkpoint_dir: str) -> None:
        path = os.path.join(checkpoint_dir, "fn_ckpt.bin")
        if os.path.exists(path):
            from ..train.checkpoint import Checkpoint

            blob = open(path, "rb").read()
            self._latest_fn_ckpt = blob
            self._session.loaded_checkpoint = Checkpoint.from_bytes(blob)


def wrap_function(fn: Callable) -> type:
    """Build a FunctionTrainable subclass bound to ``fn``."""

    class _Wrapped(FunctionTrainable):
        _fn = staticmethod(fn)

    _Wrapped.__name__ = getattr(fn, "__name__", "fn") + "_trainable"
    return _Wrapped
