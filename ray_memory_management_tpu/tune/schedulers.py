"""Trial schedulers: FIFO, ASHA (async successive halving), PBT.

Mirrors the reference's tune/schedulers/ — the TrialScheduler
CONTINUE/PAUSE/STOP decision contract (trial_scheduler.py), ASHA rung logic
(async_hyperband.py: rungs at ``grace_period * reduction_factor**k``, cutoff
at the top ``1/reduction_factor`` quantile of completed rung results), and
PopulationBasedTraining exploit/explore (pbt.py: bottom-quantile trials clone
the state of top-quantile trials and perturb hyperparameters by 1.2x/0.8x or
a resample).
"""

from __future__ import annotations

import random
from typing import Any, Callable, Dict, List, Optional

CONTINUE = "CONTINUE"
STOP = "STOP"


class TrialScheduler:
    def __init__(self, metric: str = "loss", mode: str = "min"):
        assert mode in ("min", "max")
        self.metric = metric
        self.mode = mode

    def _score(self, result: Dict[str, Any]) -> Optional[float]:
        v = result.get(self.metric)
        if v is None:
            return None
        return -float(v) if self.mode == "min" else float(v)

    def on_trial_result(self, runner, trial, result: Dict[str, Any]) -> str:
        return CONTINUE

    def on_trial_complete(self, runner, trial,
                          result: Optional[Dict[str, Any]]) -> None:
        pass


class FIFOScheduler(TrialScheduler):
    """Run every trial to completion (tune default)."""


class ASHAScheduler(TrialScheduler):
    """Async successive halving (tune/schedulers/async_hyperband.py)."""

    def __init__(self, metric: str = "loss", mode: str = "min",
                 max_t: int = 100, grace_period: int = 1,
                 reduction_factor: int = 4, time_attr: str =
                 "training_iteration"):
        super().__init__(metric, mode)
        self.max_t = max_t
        self.grace_period = grace_period
        self.rf = reduction_factor
        self.time_attr = time_attr
        # rung milestones: grace, grace*rf, grace*rf^2, ... < max_t
        self.milestones: List[int] = []
        t = grace_period
        while t < max_t:
            self.milestones.append(t)
            t *= reduction_factor
        # recorded scores per rung
        self.rungs: Dict[int, List[float]] = {m: [] for m in self.milestones}

    def on_trial_result(self, runner, trial, result: Dict[str, Any]) -> str:
        t = result.get(self.time_attr, 0)
        score = self._score(result)
        if score is None:
            return CONTINUE
        if t >= self.max_t:
            return STOP
        decision = CONTINUE
        for m in self.milestones:
            if t == m:
                rung = self.rungs[m]
                rung.append(score)
                k = max(1, len(rung) // self.rf)
                cutoff = sorted(rung, reverse=True)[k - 1]
                if score < cutoff:
                    decision = STOP
        return decision


class MedianStoppingRule(TrialScheduler):
    """Stop a trial whose running-average score falls below the median
    of all trials' running averages at the same point in training
    (tune/schedulers/median_stopping_rule.py — the Google Vizier rule).
    Gentler than ASHA: no rungs, every trial gets ``grace_period`` and
    the cut tracks the cohort continuously."""

    def __init__(self, metric: str = "loss", mode: str = "min",
                 grace_period: int = 5, min_samples_required: int = 3,
                 time_attr: str = "training_iteration"):
        super().__init__(metric, mode)
        self.grace_period = grace_period
        self.min_samples = min_samples_required
        self.time_attr = time_attr
        # per-trial score history (in canonical higher-is-better space)
        self._history: Dict[str, List[float]] = {}

    def _running_mean(self, tid: str, upto: int) -> float:
        # truncate at the decision step: a finished trial's converged tail
        # must not raise the bar on a younger trial being judged at t
        h = self._history[tid][:upto]
        return sum(h) / len(h)

    def on_trial_result(self, runner, trial, result: Dict[str, Any]) -> str:
        import statistics

        t = result.get(self.time_attr, 0)
        score = self._score(result)
        if score is None:
            return CONTINUE
        self._history.setdefault(trial.id, []).append(score)
        if t < self.grace_period:
            return CONTINUE
        n_own = len(self._history[trial.id])
        means = [self._running_mean(tid, n_own)
                 for tid in self._history if tid != trial.id]
        if len(means) < self.min_samples:
            return CONTINUE  # not enough cohort evidence to cut anyone
        if self._running_mean(trial.id, n_own) < statistics.median(means):
            return STOP
        return CONTINUE


class PopulationBasedTraining(TrialScheduler):
    """PBT (tune/schedulers/pbt.py): every ``perturbation_interval``
    iterations, trials in the bottom quantile clone a top-quantile trial's
    checkpoint and run with perturbed hyperparameters. The runner performs the
    actual exploit via the ``exploit`` callback it passes in."""

    def __init__(self, metric: str = "loss", mode: str = "min",
                 perturbation_interval: int = 5,
                 hyperparam_mutations: Optional[Dict[str, Any]] = None,
                 quantile_fraction: float = 0.25,
                 resample_probability: float = 0.25,
                 seed: Optional[int] = None,
                 time_attr: str = "training_iteration"):
        super().__init__(metric, mode)
        self.interval = perturbation_interval
        self.mutations = hyperparam_mutations or {}
        self.quantile = quantile_fraction
        self.resample_p = resample_probability
        self.time_attr = time_attr
        self.rng = random.Random(seed)
        self.last_scores: Dict[str, float] = {}
        self.last_perturb: Dict[str, int] = {}

    def explore(self, config: Dict[str, Any]) -> Dict[str, Any]:
        """Perturb mutated keys: 1.2x / 0.8x, or resample (pbt.py:explore)."""
        from .search import Domain

        new = dict(config)
        for key, spec in self.mutations.items():
            if key not in new:
                continue
            if self.rng.random() < self.resample_p:
                if isinstance(spec, Domain):
                    new[key] = spec.sample(self.rng)
                elif isinstance(spec, list):
                    new[key] = self.rng.choice(spec)
                elif callable(spec):
                    new[key] = spec()
            elif isinstance(new[key], (int, float)):
                factor = 1.2 if self.rng.random() > 0.5 else 0.8
                new[key] = type(new[key])(new[key] * factor)
            elif isinstance(spec, list):
                new[key] = self.rng.choice(spec)
        return new

    def on_trial_result(self, runner, trial, result: Dict[str, Any]) -> str:
        t = result.get(self.time_attr, 0)
        score = self._score(result)
        if score is not None:
            self.last_scores[trial.id] = score
        if t - self.last_perturb.get(trial.id, 0) < self.interval:
            return CONTINUE
        self.last_perturb[trial.id] = t
        scores = sorted(self.last_scores.values())
        n = len(scores)
        if n < 2 or score is None:
            return CONTINUE
        k = max(1, int(n * self.quantile))
        lower_cut = scores[k - 1]
        upper_cut = scores[n - k]
        if score <= lower_cut:
            # exploit: pick a random top-quantile trial to clone
            top = [tid for tid, s in self.last_scores.items()
                   if s >= upper_cut and tid != trial.id]
            if top:
                runner.request_exploit(trial, self.rng.choice(top),
                                       self.explore(trial.config))
        return CONTINUE
