"""Tuner + TrialRunner: the experiment event loop.

Mirrors the reference's tune execution layer — `Tuner.fit`
(tune/tuner.py:32,212) → `tune.run` (tune/tune.py:129) → `TrialRunner.step`
(tune/execution/trial_runner.py:236,864) with trials placed as actors by
`RayTrialExecutor` (tune/execution/ray_trial_executor.py). Each trial is one
actor implementing the Trainable step/save/restore contract; the runner polls
outstanding ``train()`` calls with ``wait``, feeds results to the scheduler,
and applies CONTINUE/STOP plus PBT exploit requests.

TPU note: a trial's bundle may include TPU chips; concurrent trials then
time-share the host's chips the way Tune trials share GPUs — the scheduler's
resource accounting (not CUDA_VISIBLE_DEVICES masking) keeps them apart.
"""

from __future__ import annotations

import dataclasses
import time
import uuid
from typing import Any, Callable, Dict, List, Optional, Tuple, Type

from .. import api
from ..exceptions import RmtError
from .schedulers import CONTINUE, STOP, FIFOScheduler, TrialScheduler
from .search import BasicVariantGenerator, Searcher
from .trainable import RESULT_DONE, Trainable, wrap_function

PENDING = "PENDING"
RUNNING = "RUNNING"
TERMINATED = "TERMINATED"
ERROR = "ERROR"


@dataclasses.dataclass
class TuneConfig:
    metric: str = "loss"
    mode: str = "min"
    num_samples: int = 1
    max_concurrent_trials: Optional[int] = None
    scheduler: Optional[TrialScheduler] = None
    search_alg: Optional[Searcher] = None
    seed: Optional[int] = None
    max_iterations: Optional[int] = None


@dataclasses.dataclass
class TrialResult:
    trial_id: str
    config: Dict[str, Any]
    metrics: Dict[str, Any]
    metrics_history: List[Dict[str, Any]]
    checkpoint_blob: Optional[bytes] = None
    error: Optional[str] = None

    @property
    def metrics_dataframe(self):
        return self.metrics_history


class ResultGrid:
    def __init__(self, results: List[TrialResult], metric: str, mode: str):
        self._results = results
        self.metric = metric
        self.mode = mode

    def __len__(self):
        return len(self._results)

    def __iter__(self):
        return iter(self._results)

    def __getitem__(self, i):
        return self._results[i]

    @property
    def errors(self) -> List[str]:
        return [r.error for r in self._results if r.error]

    def get_best_result(self, metric: Optional[str] = None,
                        mode: Optional[str] = None) -> TrialResult:
        metric = metric or self.metric
        mode = mode or self.mode
        scored = [r for r in self._results
                  if r.error is None and metric in r.metrics]
        if not scored:
            raise RmtError("no successful trial reported "
                           f"metric {metric!r}")
        key = (lambda r: r.metrics[metric])
        return (min if mode == "min" else max)(scored, key=key)

    def get_dataframe(self) -> List[Dict[str, Any]]:
        return [dict(r.metrics, trial_id=r.trial_id) for r in self._results]


class _TrialActorImpl:
    """Generic trial actor hosting one Trainable instance. ``kind`` is
    "class" (blob is a Trainable subclass) or "fn" (blob is a plain function
    wrapped into a FunctionTrainable here, so only the user fn crosses the
    wire)."""

    def __init__(self, kind: str, blob: bytes, config: dict,
                 trial_info: dict):
        import cloudpickle

        obj = cloudpickle.loads(blob)
        cls = obj if kind == "class" else wrap_function(obj)
        self.trainable: Trainable = cls(config, trial_info)

    def train(self) -> dict:
        return self.trainable.train()

    def save(self) -> bytes:
        return self.trainable.save()

    def restore(self, blob: bytes) -> bool:
        self.trainable.restore(blob)
        return True

    def reset(self, config: dict) -> bool:
        return self.trainable.reset(config)

    def stop(self) -> bool:
        self.trainable.stop()
        return True


class Trial:
    def __init__(self, config: Dict[str, Any], trial_num: int,
                 experiment: str):
        self.id = f"{experiment}_{trial_num:05d}_{uuid.uuid4().hex[:6]}"
        self.config = config
        self.status = PENDING
        self.actor = None
        self.pending_ref = None
        self.last_result: Dict[str, Any] = {}
        self.history: List[Dict[str, Any]] = []
        self.checkpoint_blob: Optional[bytes] = None
        self.error: Optional[str] = None
        # queued exploit: (donor checkpoint blob, new config) applied
        # between train() rounds
        self.exploit: Optional[Tuple[str, Dict[str, Any]]] = None


class TrialRunner:
    def __init__(self, trainable: Tuple[str, Any],
                 trials: List[Trial], tune_config: TuneConfig,
                 resources_per_trial: Dict[str, float],
                 syncer=None):
        from .. import serialization as ser

        self.kind, payload = trainable
        self.blob = ser.dumps_function(payload)
        self.trials = trials
        self.cfg = tune_config
        self.resources = resources_per_trial
        self.syncer = syncer  # tune/syncer.py analog: cloud checkpoints
        self.scheduler = tune_config.scheduler or FIFOScheduler(
            tune_config.metric, tune_config.mode)
        cluster_cpus = int(api.cluster_resources().get("CPU", 1))
        per_trial_cpus = max(1, int(resources_per_trial.get("CPU", 1)))
        self.max_concurrent = tune_config.max_concurrent_trials or max(
            1, cluster_cpus // per_trial_cpus)
        self._exploits: List[Tuple[Trial, str, Dict[str, Any]]] = []

    # -- scheduler callback ---------------------------------------------------
    def request_exploit(self, trial: Trial, donor_trial_id: str,
                        new_config: Dict[str, Any]) -> None:
        self._exploits.append((trial, donor_trial_id, new_config))

    # -- lifecycle ------------------------------------------------------------
    def _start_trial(self, trial: Trial) -> None:
        cls = api.remote(_TrialActorImpl)
        trial.actor = cls.options(
            num_cpus=self.resources.get("CPU", 1),
            num_tpus=self.resources.get("TPU", 0),
        ).remote(self.kind, self.blob, trial.config,
                 {"id": trial.id, "name": trial.id})
        trial.status = RUNNING
        trial.pending_ref = trial.actor.train.remote()

    def _stop_trial(self, trial: Trial, status: str,
                    error: Optional[str] = None) -> None:
        trial.status = status
        trial.error = error
        if trial.actor is not None:
            try:
                if status == TERMINATED:
                    trial.checkpoint_blob = api.get(
                        trial.actor.save.remote(), timeout=60)
                    api.get(trial.actor.stop.remote(), timeout=60)
            except Exception:
                pass
            try:
                api.kill(trial.actor)
            except Exception:
                pass
            trial.actor = None
        if self.syncer is not None and trial.checkpoint_blob:
            # durability, not correctness: a failed upload must not fail
            # the trial — but it must be LOUD (the experiment thinks its
            # checkpoints survive the head's disk)
            try:
                self.syncer.upload(
                    trial.id, trial.checkpoint_blob,
                    iteration=trial.last_result.get("training_iteration"))
            except Exception as e:  # noqa: BLE001
                from ..utils import events

                events.emit(
                    "TUNE_SYNC_FAILED",
                    f"checkpoint upload for trial {trial.id} failed: "
                    f"{e!r}", severity=events.WARNING, source="tune")
        trial.pending_ref = None
        self.scheduler.on_trial_complete(self, trial, trial.last_result)
        if self.cfg.search_alg is not None:
            self.cfg.search_alg.on_trial_complete(
                trial.id, trial.last_result, error=status == ERROR)

    def _apply_exploits(self) -> None:
        by_id = {t.id: t for t in self.trials}
        while self._exploits:
            trial, donor_id, new_config = self._exploits.pop()
            donor = by_id.get(donor_id)
            if donor is None or trial.actor is None:
                continue
            blob = None
            if donor.actor is not None:
                try:
                    blob = api.get(donor.actor.save.remote(), timeout=120)
                except Exception:
                    pass
            if blob is None:
                # donor already terminated — exploit its final checkpoint
                blob = donor.checkpoint_blob
            if blob is None:
                continue
            trial.exploit = None
            try:
                # hot path: in-place reset if the trainable supports it,
                # else replace the actor (pbt.py restarts the same way)
                ok = api.get(trial.actor.reset.remote(new_config),
                             timeout=120)
                if not ok:
                    api.kill(trial.actor)
                    cls = api.remote(_TrialActorImpl)
                    trial.actor = cls.options(
                        num_cpus=self.resources.get("CPU", 1),
                        num_tpus=self.resources.get("TPU", 0),
                    ).remote(self.kind, self.blob, new_config,
                             {"id": trial.id, "name": trial.id})
                api.get(trial.actor.restore.remote(blob), timeout=120)
                trial.config = new_config
                trial.pending_ref = trial.actor.train.remote()
            except Exception as e:
                self._stop_trial(trial, ERROR, f"exploit failed: {e}")

    # -- main loop ------------------------------------------------------------
    def run(self) -> None:
        pending = [t for t in self.trials]
        while True:
            running = [t for t in self.trials if t.status == RUNNING]
            while pending and len(running) < self.max_concurrent:
                trial = pending.pop(0)
                try:
                    self._start_trial(trial)
                    running.append(trial)
                except Exception as e:
                    trial.status = ERROR
                    trial.error = str(e)
            if not running and not pending:
                break
            ref_to_trial = {t.pending_ref: t for t in running
                            if t.pending_ref is not None}
            if not ref_to_trial:
                time.sleep(0.05)
                continue
            # block until at least one result, then sweep up everything
            # that is already done so concurrent trials advance in lockstep
            # (the reference processes one event per step() but its executor
            # keeps per-trial futures running; here fairness needs the sweep)
            refs = list(ref_to_trial.keys())
            ready, _ = api.wait(refs, num_returns=1, timeout=1.0)
            if ready:
                ready, _ = api.wait(refs, num_returns=len(refs), timeout=0)
            for ref in ready:
                trial = ref_to_trial[ref]
                try:
                    result = api.get(ref)
                except Exception as e:
                    self._stop_trial(trial, ERROR, str(e))
                    continue
                # a bare done-sentinel (function trainable exhausted) carries
                # no user metrics — don't let it clobber the last real result
                sentinel = result.get(RESULT_DONE, False) and not (
                    set(result) - {RESULT_DONE, "training_iteration",
                                   "time_total_s", "trial_id"})
                if not sentinel:
                    trial.last_result = result
                    trial.history.append(result)
                    if self.cfg.search_alg is not None:
                        self.cfg.search_alg.on_trial_result(trial.id, result)
                done = result.get(RESULT_DONE, False)
                max_it = self.cfg.max_iterations
                if max_it is not None and \
                        result.get("training_iteration", 0) >= max_it:
                    done = True
                decision = self.scheduler.on_trial_result(
                    self, trial, result)
                if done or decision == STOP:
                    self._stop_trial(trial, TERMINATED)
                else:
                    trial.pending_ref = trial.actor.train.remote()
            self._apply_exploits()


class Tuner:
    """tune/tuner.py:32 analog.

    ``trainable`` may be a Trainable subclass, a plain function
    ``fn(config)`` using train.session.report, or a trainer object with
    ``.fit()`` (JaxTrainer — mirroring how the reference runs trainers under
    Tune, base_trainer.py:354).
    """

    def __init__(self, trainable, *, param_space: Optional[dict] = None,
                 tune_config: Optional[TuneConfig] = None,
                 resources_per_trial: Optional[Dict[str, float]] = None,
                 name: Optional[str] = None,
                 upload_dir: Optional[str] = None):
        self.trainable = trainable
        self.param_space = param_space or {}
        self.cfg = tune_config or TuneConfig()
        self.resources = resources_per_trial or {"CPU": 1}
        self.name = name or f"tune_{int(time.time())}"
        # cloud checkpoint sync (tune/syncer.py's upload_dir): every
        # completed trial's checkpoint blob uploads through the external-
        # storage registry (s3:// gs:// file:// or a registered scheme)
        self.syncer = None
        if upload_dir:
            from .syncer import Syncer

            self.syncer = Syncer(upload_dir, self.name)

    def _trainable_payload(self) -> Tuple[str, Any]:
        t = self.trainable
        if isinstance(t, type) and issubclass(t, Trainable):
            return ("class", t)
        if callable(t) and not hasattr(t, "fit"):
            return ("fn", t)
        if hasattr(t, "fit"):
            trainer = t

            def run_trainer(config):
                from ..train import session

                merged = dict(trainer.config or {})
                merged.update(config)
                trainer.config = merged
                result = trainer.fit()
                if result.error is not None:
                    raise result.error
                session.report(result.metrics or {"_fit": "ok"})

            return ("fn", run_trainer)
        raise TypeError(f"unsupported trainable: {t!r}")

    def _generate_trials(self) -> List[Trial]:
        configs = BasicVariantGenerator(
            self.param_space, self.cfg.num_samples,
            seed=self.cfg.seed).variants()
        return [Trial(c, i, self.name) for i, c in enumerate(configs)]

    def _fit_with_searcher(self) -> List[Trial]:
        """Model-based search needs results fed back between suggestions
        (the suggest/on_trial_complete loop, tune/search/searcher.py):
        trials run in waves of ``max_concurrent_trials`` (default 1 wave
        of everything for a stateless searcher would starve the model, so
        the default wave is 1), with every completion reported to the
        searcher before the next wave is suggested."""
        import dataclasses

        alg = self.cfg.search_alg
        # wave size trades model freshness for throughput: 1 gives the
        # searcher feedback after every trial, large waves parallelize.
        # Default 4 keeps feedback-free searchers from running strictly
        # serially while a model-based searcher still observes often.
        wave = self.cfg.max_concurrent_trials or 4
        # the runner must NOT also report to the searcher (it would use
        # its own trial ids, double-counting every completion); this loop
        # is the single feedback path, keyed by the suggest() ids
        runner_cfg = dataclasses.replace(self.cfg, search_alg=None)
        payload = self._trainable_payload()  # pickle the trainable once
        trials: List[Trial] = []
        i = 0
        while i < self.cfg.num_samples:
            batch = []
            for j in range(min(wave, self.cfg.num_samples - i)):
                cfg = alg.suggest(f"t{i + j}")
                if cfg is None:
                    break  # searcher exhausted
                batch.append(Trial(cfg, i + j, self.name))
            if not batch:
                break
            runner = TrialRunner(payload, batch, runner_cfg,
                                 self.resources, syncer=self.syncer)
            runner.run()
            for j, t in enumerate(batch):
                alg.on_trial_complete(f"t{i + j}", t.last_result,
                                      error=t.error is not None)
            trials.extend(batch)
            i += len(batch)
        return trials

    def fit(self) -> ResultGrid:
        if self.cfg.search_alg is not None:
            trials = self._fit_with_searcher()
        else:
            trials = self._generate_trials()
            runner = TrialRunner(self._trainable_payload(), trials,
                                 self.cfg, self.resources,
                                 syncer=self.syncer)
            runner.run()
        results = [
            TrialResult(
                trial_id=t.id, config=t.config, metrics=t.last_result,
                metrics_history=t.history, checkpoint_blob=t.checkpoint_blob,
                error=t.error,
            )
            for t in trials
        ]
        return ResultGrid(results, self.cfg.metric, self.cfg.mode)
