"""ray_memory_management_tpu: a TPU-native distributed runtime with the
capability surface of the reference (tasks, actors, objects, placement groups,
collectives, Train/Tune/Data/Serve-style libraries), re-architected for
JAX/XLA/Pallas — see SURVEY.md for the blueprint."""

__version__ = "0.1.0"

# RMT_LOCK_CHECK=1 patches threading.Lock/RLock with the lock-order
# recorder BEFORE any runtime lock exists (api/init below creates them)
from .analysis import lockwatch as _lockwatch  # noqa: E402

_lockwatch.maybe_install_from_env()

from .api import (  # noqa: F401
    init, shutdown, is_initialized, remote, get, put, wait, kill, cancel,
    get_actor, method, ObjectRef, nodes, cluster_resources,
    available_resources, timeline, cpp_function, cpp_functions,
)
from .exceptions import (  # noqa: F401
    RmtError, TaskError, ActorError, ActorDiedError, WorkerCrashedError,
    ObjectLostError, ObjectStoreFullError, GetTimeoutError,
)
