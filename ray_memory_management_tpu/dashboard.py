"""Dashboard: HTTP observability endpoint over the live cluster.

The reference's dashboard (dashboard/head.py:62 aiohttp head + per-node
agents + React UI) reduced to its data surface: a stdlib HTTP server in
the driver process exposing the state API as JSON, cluster resources,
jobs, and Prometheus metrics, plus a minimal HTML overview. Runs
in-process because cluster state lives in the driver runtime.

Routes::

    /                       HTML overview
    /api/cluster            resources total/available
    /api/nodes|actors|tasks|objects|workers|placement_groups
                            (tasks/objects take ?job_id= to narrow to
                            one tenant's rows)
    /api/jobs               job-submission table
    /api/drivers            GCS job table (driver + client jobs) with
                            live quota-ledger usage per job
    /api/events             structured cluster events
    /api/task_summary       task-state counts + per-stage latency p50/95/99
    /api/timeline           Chrome traceEvents JSON (load in Perfetto);
                            filters: ?task_id=&trace_id=&cat=&limit=
    /api/trace?trace_id=    span tree + critical-path attribution
    /api/logs               structured log records + dropped count;
                            filters: ?task_id=&trace_id=&node_id=
                            &level=&since=&limit=&job_id=
                            (400 on bad params)
    /api/profile            folded stack samples + dropped count;
                            filters: ?task_id=&trace_id=&node_id=
                            &since=&limit=&fold=&job_id=
                            (400 on bad params)
    /api/series?name=       health-plane time-series history for one
                            rmt_* metric; ?since=&window=&rate=&delta=
                            &quantile= plus any other key=value as a
                            tag filter (400 on bad params)
    /api/alerts             SLO rules engine alerts (firing + resolved
                            history); filters: ?state=&limit=
                            (400 on bad params)
    /metrics                Prometheus exposition text
"""

from __future__ import annotations

import json
import threading
from typing import Optional

_HTML = """<!doctype html>
<title>rmt dashboard</title>
<style>body{font-family:monospace;margin:2em}td,th{padding:2px 10px;
text-align:left}h2{margin-top:1.2em}</style>
<h1>rmt cluster</h1>
<div id=out>loading…</div>
<script>
const SECTIONS = ["cluster","nodes","actors","tasks","workers"];
async function refresh() {
  const out = document.createElement("div");
  for (const s of SECTIONS) {
    const data = await (await fetch("/api/" + s)).json();
    const h2 = document.createElement("h2");
    h2.textContent = s;                       // textContent: cluster data
    const pre = document.createElement("pre"); // is untrusted for HTML
    pre.textContent = JSON.stringify(data, null, 2);
    out.append(h2, pre);
  }
  document.getElementById("out").replaceChildren(...out.children);
}
refresh(); setInterval(refresh, 5000);
</script>
"""


class Dashboard:
    def __init__(self, host: str = "127.0.0.1", port: int = 8265):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        dash = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):
                try:
                    status, ctype, body = dash._route(self.path)
                except Exception as e:  # noqa: BLE001
                    status, ctype = 500, "application/json"
                    body = json.dumps({"error": str(e)}).encode()
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.end_headers()
                self.wfile.write(body)

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.host = host
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="rmt-dashboard")
        self._thread.start()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def _route(self, path: str):
        from urllib.parse import parse_qs, urlsplit

        from . import state

        parts = urlsplit(path)
        # first value per key: these routes take scalar filters only
        query = {k: v[0] for k, v in parse_qs(parts.query).items() if v}
        path = parts.path.rstrip("/") or "/"
        if path == "/":
            return 200, "text/html", _HTML.encode()
        if path == "/metrics":
            from .utils.metrics import export_prometheus

            return 200, "text/plain; version=0.0.4", \
                export_prometheus().encode()
        if path == "/api/cluster":
            from . import api

            data = {
                "resources_total": api.cluster_resources(),
                "resources_available": api.available_resources(),
                "nodes": len(api.nodes()),
            }
        elif path == "/api/nodes":
            data = state.list_nodes()
        elif path == "/api/actors":
            data = state.list_actors()
        elif path == "/api/tasks":
            data = state.list_tasks(job_id=query.get("job_id"))
        elif path == "/api/objects":
            data = state.list_objects(job_id=query.get("job_id"))
        elif path == "/api/workers":
            data = state.list_workers()
        elif path == "/api/placement_groups":
            data = state.list_placement_groups()
        elif path == "/api/jobs":
            from .job_submission import JobSubmissionClient

            data = JobSubmissionClient().list_jobs()
        elif path == "/api/drivers":
            # the GCS job table: the in-process driver + every thin-client
            # connection (gcs_job_manager.h:28), distinct from the
            # submission-queue jobs above
            data = state.list_jobs()
        elif path == "/api/events":
            from .utils import events as _events

            data = _events.list_events()
        elif path == "/api/task_summary":
            data = {
                "tasks": state.summarize_tasks(),
                "latencies": state.summarize_task_latencies(),
            }
        elif path == "/api/timeline":
            from .utils import timeline as _timeline

            limit = None
            if "limit" in query:
                try:
                    limit = max(0, int(query["limit"]))
                except ValueError:
                    limit = None
            data = {
                "traceEvents": _timeline.chrome_trace_events(
                    task_id=query.get("task_id"),
                    trace_id=query.get("trace_id"),
                    cat=query.get("cat"),
                    limit=limit),
                # ring evictions since start/clear: a non-zero value
                # warns that the export is a suffix, not the full run
                "dropped": _timeline.dropped_count(),
            }
        elif path == "/api/trace":
            trace_id = query.get("trace_id")
            if not trace_id:
                return (400, "application/json",
                        b'{"error": "trace_id query param required"}')
            data = {
                "trace": state.get_trace(trace_id),
                "critical_path": state.summarize_critical_path(trace_id),
            }
        elif path == "/api/logs":
            from .utils import structlog as _structlog

            limit = 1000
            if "limit" in query:
                try:
                    limit = int(query["limit"])
                except ValueError:
                    return (400, "application/json",
                            b'{"error": "limit must be an integer"}')
                if limit < 0:
                    return (400, "application/json",
                            b'{"error": "limit must be >= 0"}')
            since = None
            if "since" in query:
                try:
                    since = float(query["since"])
                except ValueError:
                    return (400, "application/json",
                            b'{"error": "since must be a timestamp"}')
            level = query.get("level")
            if level is not None and \
                    level.upper() not in _structlog.LEVELS:
                return (400, "application/json",
                        json.dumps({"error": "level must be one of "
                                    + "/".join(_structlog.LEVELS)}).encode())
            data = {
                "logs": state.get_logs(
                    task_id=query.get("task_id"),
                    trace_id=query.get("trace_id"),
                    node_id=query.get("node_id"),
                    level=level, since=since, limit=limit,
                    job_id=query.get("job_id")),
                # drops since start (worker buffer overflow seen locally
                # + store retention evictions): non-zero warns the view
                # is a suffix — mirrors /api/timeline
                "dropped": _structlog.dropped_count(),
            }
        elif path == "/api/profile":
            from .utils import profiler as _profiler

            limit = 10000
            if "limit" in query:
                try:
                    limit = int(query["limit"])
                except ValueError:
                    return (400, "application/json",
                            b'{"error": "limit must be an integer"}')
                if limit < 0:
                    return (400, "application/json",
                            b'{"error": "limit must be >= 0"}')
            since = None
            if "since" in query:
                try:
                    since = float(query["since"])
                except ValueError:
                    return (400, "application/json",
                            b'{"error": "since must be a timestamp"}')
            fold = True
            if "fold" in query:
                raw = query["fold"].lower()
                if raw not in ("0", "1", "true", "false"):
                    return (400, "application/json",
                            b'{"error": "fold must be 0/1/true/false"}')
                fold = raw in ("1", "true")
            data = {
                "profile": state.get_profile(
                    task_id=query.get("task_id"),
                    trace_id=query.get("trace_id"),
                    node_id=query.get("node_id"),
                    since=since, limit=limit, fold=fold,
                    job_id=query.get("job_id")),
                # drops since start (sampler aggregation overflow seen
                # locally + store retention evictions): non-zero warns
                # the view is a suffix — mirrors /api/logs
                "dropped": _profiler.dropped_count(),
            }
        elif path == "/api/series":
            name = query.get("name")
            if not name:
                return (400, "application/json",
                        b'{"error": "name query param required"}')
            since = None
            if "since" in query:
                try:
                    since = float(query["since"])
                except ValueError:
                    return (400, "application/json",
                            b'{"error": "since must be a timestamp"}')
            window = 60.0
            if "window" in query:
                try:
                    window = float(query["window"])
                except ValueError:
                    return (400, "application/json",
                            b'{"error": "window must be seconds"}')
                if window <= 0:
                    return (400, "application/json",
                            b'{"error": "window must be > 0"}')
            rate = delta = False
            for key in ("rate", "delta"):
                if key in query:
                    raw = query[key].lower()
                    if raw not in ("0", "1", "true", "false"):
                        return (400, "application/json",
                                json.dumps({"error": f"{key} must be "
                                            "0/1/true/false"}).encode())
                    if key == "rate":
                        rate = raw in ("1", "true")
                    else:
                        delta = raw in ("1", "true")
            quantile = None
            if "quantile" in query:
                try:
                    quantile = float(query["quantile"])
                except ValueError:
                    return (400, "application/json",
                            b'{"error": "quantile must be a number"}')
                if not 0.0 <= quantile <= 1.0:
                    return (400, "application/json",
                            b'{"error": "quantile must be in [0, 1]"}')
            # every remaining key=value is a tag filter (the series
            # analog of /api/logs' id filters)
            reserved = ("name", "since", "window", "rate", "delta",
                        "quantile")
            tags = {k: v for k, v in query.items() if k not in reserved}
            data = state.query_series(
                name, tags=tags or None, since=since, window=window,
                rate=rate, delta=delta, quantile=quantile)
        elif path == "/api/alerts":
            alert_state = query.get("state")
            if alert_state is not None and \
                    alert_state not in ("firing", "resolved"):
                return (400, "application/json",
                        b'{"error": "state must be firing or resolved"}')
            limit = 100
            if "limit" in query:
                try:
                    limit = int(query["limit"])
                except ValueError:
                    return (400, "application/json",
                            b'{"error": "limit must be an integer"}')
                if limit < 0:
                    return (400, "application/json",
                            b'{"error": "limit must be >= 0"}')
            data = {"alerts": state.get_alerts(state=alert_state,
                                               limit=limit)}
        else:
            return 404, "application/json", b'{"error": "not found"}'
        return 200, "application/json", json.dumps(data).encode()

    def stop(self) -> None:
        self._server.shutdown()


_dashboard: Optional[Dashboard] = None


def start_dashboard(host: str = "127.0.0.1",
                    port: int = 8265) -> Dashboard:
    global _dashboard
    if _dashboard is None:
        _dashboard = Dashboard(host, port)
    return _dashboard


def stop_dashboard() -> None:
    global _dashboard
    if _dashboard is not None:
        _dashboard.stop()
        _dashboard = None
