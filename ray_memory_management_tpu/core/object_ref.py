"""ObjectRef: a first-class future/reference to an immutable object.

The Python-visible half of the reference's ObjectRef (_raylet.pyx ObjectRef):
value-identity on the 16-byte id, picklable (so refs can be task args —
borrowing), and hooked into the owner's reference counter on destruction
(reference_count.h AddLocalReference/RemoveLocalReference analog).
Driver-created refs participate in the driver's distributed GC; refs
deserialized INSIDE a worker register with the worker's own reference
counter (set_deserialize_owner, installed by worker_main), which reports
still-held borrows to the head at task completion and releases them when
dropped — the borrowed-ref protocol of reference_count.h:39-61.
"""

from __future__ import annotations

from typing import Optional

# Per-process hooks. _DESERIALIZE_OWNER: the reference counter
# deserialized refs attach to — None on the driver (bare refs, owner-side
# pinning); worker_main installs the worker's proxy so borrows are
# tracked where they live. _SERIALIZE_OBSERVER: called with the id every
# time a ref is pickled — the worker marks its owned puts "escaped"
# (shipped in a return/arg/put), which blocks the free-on-owner-release
# optimization for ids some other process may now hold.
_DESERIALIZE_OWNER = None
_SERIALIZE_OBSERVER = None


def set_deserialize_owner(owner) -> None:
    global _DESERIALIZE_OWNER
    _DESERIALIZE_OWNER = owner


def set_serialize_observer(observer) -> None:
    global _SERIALIZE_OBSERVER
    _SERIALIZE_OBSERVER = observer


def _from_wire(object_id: bytes) -> "ObjectRef":
    return ObjectRef(object_id, _DESERIALIZE_OWNER)


class ObjectRef:
    __slots__ = ("_id", "_owner", "__weakref__")

    def __init__(self, object_id: bytes, owner=None, adopt: bool = False):
        """``adopt=True`` takes over a reference the owner ALREADY holds
        (submit_task pre-registers one per return id so a task finishing
        before the driver wraps its ids cannot see a refcount of zero)
        instead of adding a new one."""
        self._id = object_id
        self._owner = owner
        if owner is not None and not adopt:
            owner.add_local_ref(object_id)

    def binary(self) -> bytes:
        return self._id

    def hex(self) -> str:
        return self._id.hex()

    def __hash__(self):
        return hash(self._id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other._id == self._id

    def __repr__(self):
        return f"ObjectRef({self._id.hex()[:16]})"

    def __reduce__(self):
        # Refs serialize as bare ids. On the DRIVER the receiving side
        # does not register a local ref (borrowers are pinned by the
        # owner for the duration of the borrowing task). In a WORKER the
        # deserialize hook attaches the worker's reference counter, so a
        # ref kept alive past the task shows up in the done reply's
        # borrowed-ref table and stays pinned until the worker drops it
        # (reference_count.h:39-61 borrowing protocol).
        if _SERIALIZE_OBSERVER is not None:
            _SERIALIZE_OBSERVER(self._id)
        return (_from_wire, (self._id,))

    def __del__(self):
        owner = self._owner
        if owner is not None:
            try:
                owner.remove_local_ref(self._id)
            except Exception:
                pass

    def future(self):
        """A concurrent.futures.Future resolving to the object's value."""
        from .. import _worker_context

        return _worker_context.backend().future_for(self)
