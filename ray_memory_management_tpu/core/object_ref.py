"""ObjectRef: a first-class future/reference to an immutable object.

The Python-visible half of the reference's ObjectRef (_raylet.pyx ObjectRef):
value-identity on the 16-byte id, picklable (so refs can be task args —
borrowing), and hooked into the owner's reference counter on destruction
(reference_count.h AddLocalReference/RemoveLocalReference analog). Only
driver-created refs participate in distributed GC in round 1; worker-held
refs pin via the in-flight-task arg pin instead.
"""

from __future__ import annotations

from typing import Optional


class ObjectRef:
    __slots__ = ("_id", "_owner", "__weakref__")

    def __init__(self, object_id: bytes, owner=None, adopt: bool = False):
        """``adopt=True`` takes over a reference the owner ALREADY holds
        (submit_task pre-registers one per return id so a task finishing
        before the driver wraps its ids cannot see a refcount of zero)
        instead of adding a new one."""
        self._id = object_id
        self._owner = owner
        if owner is not None and not adopt:
            owner.add_local_ref(object_id)

    def binary(self) -> bytes:
        return self._id

    def hex(self) -> str:
        return self._id.hex()

    def __hash__(self):
        return hash(self._id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other._id == self._id

    def __repr__(self):
        return f"ObjectRef({self._id.hex()[:16]})"

    def __reduce__(self):
        # Refs serialize as bare ids; the receiving side does not register a
        # local ref (borrowers are pinned by the owner for the duration of the
        # borrowing task instead — simplified borrowing protocol).
        return (ObjectRef, (self._id,))

    def __del__(self):
        owner = self._owner
        if owner is not None:
            try:
                owner.remove_local_ref(self._id)
            except Exception:
                pass

    def future(self):
        """A concurrent.futures.Future resolving to the object's value."""
        from .. import _worker_context

        return _worker_context.backend().future_for(self)
