"""Persistent GCS table storage.

The pluggable-store analog of the reference's GCS fault-tolerance tier
(``InMemoryStoreClient`` vs ``RedisStoreClient``,
src/ray/gcs/store_client/redis_store_client.h:28 — Redis-backed tables are
what let detached actors and cluster KV survive a GCS restart). Here the
durable backend is sqlite — single-file, transactional, no external server
to manage, and good for the single-head control plane this runtime runs.

Schema: one namespaced KV table. GCS tables (detached actors, internal KV,
named placement groups) serialize rows into it under their own namespace.
"""

from __future__ import annotations

import os
import sqlite3
import threading
from typing import Dict, List, Optional, Tuple


class GcsStorage:
    """Interface: namespaced binary KV with prefix listing."""

    def put(self, ns: str, key: str, value: bytes) -> None:
        raise NotImplementedError

    def get(self, ns: str, key: str) -> Optional[bytes]:
        raise NotImplementedError

    def delete(self, ns: str, key: str) -> None:
        raise NotImplementedError

    def items(self, ns: str) -> List[Tuple[str, bytes]]:
        raise NotImplementedError

    def close(self) -> None:
        pass


class InMemoryGcsStorage(GcsStorage):
    """Default: tables die with the process (InMemoryStoreClient analog)."""

    def __init__(self):
        self._data: Dict[Tuple[str, str], bytes] = {}
        self._lock = threading.Lock()

    def put(self, ns: str, key: str, value: bytes) -> None:
        with self._lock:
            self._data[(ns, key)] = value

    def get(self, ns: str, key: str) -> Optional[bytes]:
        with self._lock:
            return self._data.get((ns, key))

    def delete(self, ns: str, key: str) -> None:
        with self._lock:
            self._data.pop((ns, key), None)

    def items(self, ns: str) -> List[Tuple[str, bytes]]:
        with self._lock:
            return [(k, v) for (n, k), v in self._data.items() if n == ns]


class SqliteGcsStorage(GcsStorage):
    """Durable tables in one sqlite file (RedisStoreClient analog)."""

    def __init__(self, path: str):
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._lock = threading.Lock()
        with self._lock:
            # WAL + NORMAL: a commit is one WAL append instead of two
            # rollback-journal fsyncs. Survives process crashes (the head
            # restart story) — an OS/power crash can lose the last few
            # commits but never corrupts, the right trade for control
            # state that is rebuilt from live nodes anyway. Directory
            # cold-batch spills commit on the ingest path, so per-commit
            # cost is directly in the pong-delta pipeline.
            self._conn.execute("PRAGMA journal_mode=WAL")
            self._conn.execute("PRAGMA synchronous=NORMAL")
            self._conn.execute(
                "CREATE TABLE IF NOT EXISTS gcs_kv ("
                " ns TEXT NOT NULL, key TEXT NOT NULL, value BLOB NOT NULL,"
                " PRIMARY KEY (ns, key))"
            )
            self._conn.commit()

    def put(self, ns: str, key: str, value: bytes) -> None:
        with self._lock:
            self._conn.execute(
                "INSERT OR REPLACE INTO gcs_kv (ns, key, value) "
                "VALUES (?, ?, ?)", (ns, key, value))
            self._conn.commit()

    def get(self, ns: str, key: str) -> Optional[bytes]:
        with self._lock:
            row = self._conn.execute(
                "SELECT value FROM gcs_kv WHERE ns = ? AND key = ?",
                (ns, key)).fetchone()
        return None if row is None else row[0]

    def delete(self, ns: str, key: str) -> None:
        with self._lock:
            self._conn.execute(
                "DELETE FROM gcs_kv WHERE ns = ? AND key = ?", (ns, key))
            self._conn.commit()

    def items(self, ns: str) -> List[Tuple[str, bytes]]:
        with self._lock:
            rows = self._conn.execute(
                "SELECT key, value FROM gcs_kv WHERE ns = ?", (ns,)
            ).fetchall()
        return [(k, v) for k, v in rows]

    def close(self) -> None:
        with self._lock:
            try:
                self._conn.close()
            except sqlite3.Error:
                pass


def open_storage(path: str) -> GcsStorage:
    """'' -> volatile in-memory tables; a path -> durable sqlite tables."""
    return SqliteGcsStorage(path) if path else InMemoryGcsStorage()
