"""Unified compression/quantization layer for both movement planes.

EQuARX shows quantized AllReduce inside XLA buys real wall-clock at pod
scale, and the cross-slice (DCN) links are the binding bandwidth
constraint ("Exploring the limits of Concurrency in ML Training on
Google TPUs") — so the cheapest byte is the one never sent. This module
is the single place both planes come for that:

  * **Wire codecs** (transfer plane, ``core/transfer.py``; spill tier,
    ``core/object_store.py``): lossless general-purpose compression
    (zlib always; lz4 when the wheel is present) applied per chunk
    frame above ``transfer_compress_min_bytes``, negotiated
    per-connection exactly like the ``crc``/``defer_above`` additive v2
    keys. Each frame carries a CRC32 of its COMPRESSED bytes (verified
    before decode) and the decoded payload still flows through the PR 3
    full-object CRC (verify after decode) — two independent integrity
    boundaries.
  * **Compressibility probe**: a trial-block heuristic
    (:func:`probe_compressible`) samples a few 4 KiB blocks and
    zlib-1 compresses them; incompressible payloads (ciphertext,
    already-compressed media, high-entropy floats) skip encoding
    entirely so the worst case stays within ~2% of the raw path.
  * **Quantization** (collective plane, ``collective/``): bf16 and
    block-wise-scaled int8 shard quantization (EQuARX-style) with
    full-precision accumulation, shared between the XLA mesh backend
    (jnp twin of the numpy kernels here) and the objstore backend
    (these kernels directly — the quantized payload IS what crosses
    the object plane, so the wire genuinely carries 2-4x fewer bytes).
  * **Dtype-aware downcast**: f32→bf16 truncation as an opt-in LOSSY
    wire codec for payloads the caller declares to be raw float32
    (device-store arrays, gradient shards) — never negotiated
    implicitly, never applied to opaque serialized objects.

Every encode/decode is observed per codec
(``rmt_transfer_compress_{bytes_in,bytes_out}_total``,
``rmt_transfer_compress_seconds{op=encode|decode}``) so a compression
regression shows in /metrics, not just in tail latency.
"""

from __future__ import annotations

import struct
import time
import zlib
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

from ..utils.integrity import crc32

# lz4 is optional (not in every image); zlib is stdlib and always there.
try:  # pragma: no cover - availability depends on the image
    import lz4.frame as _lz4  # type: ignore
except Exception:  # noqa: BLE001 - ImportError or a broken wheel
    _lz4 = None

IDENTITY = "identity"
ZLIB = "zlib"
LZ4 = "lz4"
ZRLE = "zrle"  # zero-run block elision: the fast path for sparse payloads
DOWNCAST_BF16 = "downcast-bf16"  # lossy, opt-in, f32 payloads only

#: precision levels for quantized collectives; F32 is the bit-exact
#: default (quantization is strictly opt-in)
PRECISIONS = ("f32", "bf16", "int8")
_INT8_BLOCK = 256  # block-wise scale granularity (EQuARX uses blocks too)

# probe: sample up to this many 4 KiB blocks; a trial zlib-1 ratio
# above _PROBE_SKIP_RATIO marks the payload incompressible
_PROBE_BLOCK = 4096
_PROBE_BLOCKS = 3
_PROBE_SKIP_RATIO = 0.9


class CodecError(Exception):
    """A frame failed to decode (corrupt stream that beat the frame CRC,
    or a peer spoke a codec this process cannot)."""


def available_codecs() -> Tuple[str, ...]:
    """Lossless wire codecs THIS process can decode, best-first. The
    negotiated codec is the client's first preference the server also
    supports; identity is always common ground. ``zrle`` (zero-run block
    elision, numpy-vectorized at memory bandwidth) trails the
    general-purpose codecs in the preference order — the serving side's
    payload probe (:func:`choose_codec`) promotes it when the sampled
    blocks are mostly zeros, where it beats deflate by >10x wall-clock."""
    if _lz4 is not None:
        return (LZ4, ZLIB, ZRLE, IDENTITY)
    return (ZLIB, ZRLE, IDENTITY)


def negotiate(client_codecs: Optional[Sequence[str]],
              server_codecs: Sequence[str]) -> Optional[str]:
    """First client preference the server supports; None when the peer
    offered nothing (a codec-unaware v2 peer) or nothing overlaps —
    callers fall back to identity (raw) encoding either way."""
    if not client_codecs:
        return None
    for name in client_codecs:
        if name != IDENTITY and name in server_codecs:
            return name
    return None


def client_codecs(config) -> Optional[Tuple[str, ...]]:
    """The codec preference list a fetch should offer, from config:
    None when compression is off (the request then carries no codec
    keys at all — indistinguishable from a codec-unaware peer), the
    full supported list for "auto", or the one named codec."""
    mode = getattr(config, "transfer_compression", "off") or "off"
    if mode == "off":
        return None
    if mode == "auto":
        return available_codecs()
    if mode not in available_codecs():
        return None  # e.g. lz4 requested but the wheel is absent
    return (mode,)


def encode(data, codec: str) -> bytes:
    """Compress one chunk with ``codec``; observed per codec."""
    t0 = time.monotonic()
    if codec == ZLIB:
        out = zlib.compress(bytes(data), 1)
    elif codec == LZ4 and _lz4 is not None:
        out = _lz4.compress(bytes(data))
    elif codec == ZRLE:
        out = _zrle_encode(data)
    elif codec == DOWNCAST_BF16:
        out = downcast_f32_bytes(data)
    elif codec == IDENTITY:
        out = bytes(data)
    else:
        raise CodecError(f"cannot encode codec {codec!r}")
    nbytes = len(data) if isinstance(data, bytes) else data.nbytes
    _observe(codec, "encode", nbytes, len(out), time.monotonic() - t0)
    return out


def decode(data: bytes, codec: str) -> bytes:
    """Decompress one chunk; raises :class:`CodecError` on a corrupt
    stream or an unknown codec (treated as object loss upstream — the
    fetch aborts its unsealed create and re-pulls, never seals)."""
    t0 = time.monotonic()
    try:
        if codec == ZLIB:
            out = zlib.decompress(data)
        elif codec == LZ4 and _lz4 is not None:
            out = _lz4.decompress(data)
        elif codec == ZRLE:
            out = _zrle_decode(data)
        elif codec == DOWNCAST_BF16:
            out = upcast_bf16_bytes(data)
        elif codec == IDENTITY:
            out = bytes(data)
        else:
            raise CodecError(f"cannot decode codec {codec!r}")
    except CodecError:
        raise
    except Exception as e:  # noqa: BLE001 - zlib.error, lz4 errors
        raise CodecError(f"{codec} decode failed: {e!r}") from e
    _observe(codec, "decode", len(out), len(data), time.monotonic() - t0)
    return out


def _sample_blocks(view, span: Optional[int] = None,
                   offset: int = 0) -> list:
    """Up to _PROBE_BLOCKS sampled 4 KiB blocks (start / middle / end of
    the range) the probe heuristics run over."""
    mv = memoryview(view).cast("B")
    n = span if span is not None else (len(mv) - offset)
    if n <= 0:
        return []
    if n <= _PROBE_BLOCK * _PROBE_BLOCKS:
        return [bytes(mv[offset:offset + n])]
    blocks = []
    step = max((n - _PROBE_BLOCK) // (_PROBE_BLOCKS - 1), 1)
    for i in range(_PROBE_BLOCKS):
        off = offset + min(i * step, n - _PROBE_BLOCK)
        blocks.append(bytes(mv[off:off + _PROBE_BLOCK]))
    return blocks


def probe_compressible(view, span: Optional[int] = None,
                       offset: int = 0) -> bool:
    """Trial-block compressibility heuristic: zlib-1 a few sampled 4 KiB
    blocks (start / middle / end of the range); compressible iff the
    sampled ratio beats ``_PROBE_SKIP_RATIO``. Costs ~tens of µs on a
    multi-MB payload — what keeps the incompressible worst case within
    ~2% of the raw path instead of paying a full-payload deflate that
    saves nothing."""
    blocks = _sample_blocks(view, span, offset)
    if not blocks:
        return False
    raw = sum(len(b) for b in blocks)
    comp = sum(len(zlib.compress(b, 1)) for b in blocks)
    return comp < raw * _PROBE_SKIP_RATIO


def choose_codec(offered: Optional[Sequence[str]],
                 supported: Sequence[str], view,
                 span: Optional[int] = None,
                 offset: int = 0) -> Tuple[Optional[str], Optional[str]]:
    """Pick the codec the serving side should use for ONE payload range:
    ``(codec, None)`` to encode, ``(None, skip_reason)`` to send raw.

    The probe samples a few 4 KiB blocks once and routes on what it saw:
    mostly-zero samples promote ``zrle`` (a vectorized scan at memory
    bandwidth — deflate would "win" the ratio but lose 10x wall-clock),
    otherwise the first mutually-supported general-purpose codec runs a
    trial compression, and an incompressible sample skips encoding
    entirely. Negotiation stays the client's preference order; only the
    zeros fast path re-ranks."""
    if not offered:
        return None, "no_codec"
    common = [c for c in offered
              if c in supported and c != IDENTITY]
    if not common:
        return None, "no_codec"
    blocks = _sample_blocks(view, span, offset)
    if not blocks:
        return None, "below_threshold"
    zero_blocks = sum(1 for b in blocks if not any(b))
    if ZRLE in common and zero_blocks * 2 >= len(blocks):
        return ZRLE, None
    general = [c for c in common if c != ZRLE]
    if not general:
        # zrle is the only common ground but the payload is not
        # zero-heavy: block elision would save nothing
        return None, "incompressible"
    raw = sum(len(b) for b in blocks)
    comp = sum(len(zlib.compress(b, 1)) for b in blocks)
    if comp < raw * _PROBE_SKIP_RATIO:
        return general[0], None
    return None, "incompressible"


# -------------------------------------------------- zero-run block elision
# The sparse-payload fast path: MoE/padded gradient shards, fresh arena
# pages, and zero-initialized checkpoint buffers are dominated by whole
# zero pages. Deflate compresses them superbly but at ~0.4 GB/s; a
# vectorized block scan runs at memory bandwidth, so the compressible
# fast path stays faster than the raw wire instead of trading bytes for
# CPU. Frame: u32 original length, packed per-4KiB-block occupancy
# bitmap, then the non-zero blocks verbatim.
_ZRLE_BLOCK = 4096
_ZRLE_HDR = struct.Struct(">I")


def _zrle_encode(data) -> bytes:
    mv = memoryview(data).cast("B")
    n = len(mv)
    arr = np.frombuffer(mv, dtype=np.uint8)
    pad = (-n) % _ZRLE_BLOCK
    if pad:
        arr = np.concatenate([arr, np.zeros(pad, np.uint8)])
    blocks = arr.reshape(-1, _ZRLE_BLOCK)
    # uint64 max != 0 <=> any nonzero byte; ~3x faster than .any(axis=1)
    mask = blocks.view(np.uint64).max(axis=1) != 0
    bitmap = np.packbits(mask)
    return _ZRLE_HDR.pack(n) + bitmap.tobytes() + blocks[mask].tobytes()


def _zrle_parse(data):
    """Validate one zrle frame -> (n, mask, src blocks, nblocks, k)."""
    if len(data) < _ZRLE_HDR.size:
        raise CodecError("zrle frame shorter than its header")
    (n,) = _ZRLE_HDR.unpack_from(data)
    nblocks = -(-n // _ZRLE_BLOCK)
    bmlen = (nblocks + 7) // 8
    body = len(data) - _ZRLE_HDR.size - bmlen
    if body < 0 or body % _ZRLE_BLOCK:
        raise CodecError("zrle frame truncated")
    bitmap = np.frombuffer(data, np.uint8, bmlen, offset=_ZRLE_HDR.size)
    mask = np.unpackbits(bitmap, count=nblocks).astype(bool)
    k = int(mask.sum())
    if k * _ZRLE_BLOCK != body:
        raise CodecError("zrle bitmap disagrees with frame body")
    src = np.frombuffer(data, np.uint8, body,
                        offset=_ZRLE_HDR.size + bmlen)
    return n, mask, src, nblocks, k


def _zrle_decode(data: bytes) -> bytes:
    n, mask, src, nblocks, k = _zrle_parse(data)
    if k == 0:
        return bytes(n)  # calloc fast path: no page-faulted copies
    if k == nblocks and n == k * _ZRLE_BLOCK:
        return src.tobytes()
    out = np.zeros((nblocks, _ZRLE_BLOCK), np.uint8)
    out[mask] = src.reshape(k, _ZRLE_BLOCK)
    return out.reshape(-1)[:n].tobytes()


def _zrle_decode_into(data: bytes, out) -> int:
    """Land one zrle frame directly in ``out`` (writable memoryview):
    zero blocks are one vectorized memset, non-zero blocks one gather
    copy — no intermediate buffers. Returns bytes written."""
    n, mask, src, nblocks, k = _zrle_parse(data)
    if n > len(out):
        raise CodecError(
            f"decoded chunk ({n} B) overflows the remaining buffer "
            f"({len(out)} B)")
    dst = np.frombuffer(out, np.uint8, n)
    nfull = n // _ZRLE_BLOCK
    src2d = src.reshape(k, _ZRLE_BLOCK) if k else src
    if nfull:
        full = dst[:nfull * _ZRLE_BLOCK].reshape(nfull, _ZRLE_BLOCK)
        fmask = mask[:nfull]
        full[~fmask] = 0
        kfull = int(fmask.sum())
        if kfull:
            full[fmask] = src2d[:kfull]
    tail = n - nfull * _ZRLE_BLOCK
    if tail:
        if mask[nfull]:
            dst[nfull * _ZRLE_BLOCK:] = src2d[-1][:tail]
        else:
            dst[nfull * _ZRLE_BLOCK:] = 0
    return n


# ------------------------------------------------------------- frame format
# One compressed chunk on the wire: 4-byte big-endian CRC32 of the
# COMPRESSED payload, then the payload. The CRC is verified BEFORE
# decode (a bit flip on the wire is caught without running the
# decompressor over poison); the decoded object is then still verified
# against the serving store's full-object CRC (the PR 3 boundary) —
# verify-after-decode. Framing (length) rides the multiprocessing
# connection's own 4-byte length prefix.
_FRAME_CRC = struct.Struct(">I")


def encode_frame(chunk, codec: str) -> bytes:
    """One chunk -> crc-prefixed compressed frame."""
    comp = encode(chunk, codec)
    return _FRAME_CRC.pack(crc32(comp)) + comp


def decode_frame(frame: bytes, codec: str,
                 verify_crc: bool = True) -> bytes:
    """crc-prefixed frame -> decoded chunk. A CRC mismatch raises
    :class:`FrameIntegrityError` BEFORE any decode work; a decode
    failure raises :class:`CodecError`. Both are treated as object loss
    by the fetch path (abort + re-pull), never silent corruption."""
    if len(frame) < _FRAME_CRC.size:
        raise FrameIntegrityError("compressed frame shorter than its CRC")
    (want,) = _FRAME_CRC.unpack_from(frame)
    comp = frame[_FRAME_CRC.size:]
    if verify_crc and crc32(comp) != want:
        raise FrameIntegrityError(
            "compressed frame checksum mismatch (bit flip on the wire)")
    return decode(comp, codec)


def decode_frame_into(frame: bytes, codec: str, out,
                      verify_crc: bool = True) -> int:
    """Like :func:`decode_frame` but lands the decoded chunk DIRECTLY in
    ``out`` (a writable memoryview over the destination buffer),
    returning the byte count written. For ``zrle`` this skips every
    intermediate materialization — zero blocks become one vectorized
    memset of the destination, non-zero blocks one copy — which is what
    makes the sparse fast path cheaper than the raw wire even on a
    single core. Other codecs decode to bytes and copy. Raises
    :class:`CodecError` if the chunk outgrows ``out``."""
    if len(frame) < _FRAME_CRC.size:
        raise FrameIntegrityError("compressed frame shorter than its CRC")
    (want,) = _FRAME_CRC.unpack_from(frame)
    comp = frame[_FRAME_CRC.size:]
    if verify_crc and crc32(comp) != want:
        raise FrameIntegrityError(
            "compressed frame checksum mismatch (bit flip on the wire)")
    if codec == ZRLE:
        t0 = time.monotonic()
        n = _zrle_decode_into(comp, out)
        _observe(ZRLE, "decode", n, len(comp), time.monotonic() - t0)
        return n
    chunk = decode(comp, codec)
    if len(chunk) > len(out):
        raise CodecError(
            f"decoded chunk ({len(chunk)} B) overflows the remaining "
            f"buffer ({len(out)} B)")
    out[:len(chunk)] = chunk
    return len(chunk)


class FrameIntegrityError(Exception):
    """A compressed frame's CRC32 disagreed with its payload — caught
    before the decoder ever ran."""


# ------------------------------------------------- dtype-aware downcast
def downcast_f32_bytes(data) -> bytes:
    """f32 payload -> bf16 truncation (round-to-nearest via the carry
    bit), HALVING the bytes on the wire. LOSSY: callers opt in per
    payload and only for buffers they know are raw float32 (nbytes must
    be a multiple of 4)."""
    buf = np.frombuffer(bytes(data), dtype=np.uint32)
    # round-to-nearest: add the highest dropped bit before truncating
    rounded = ((buf >> 16) + ((buf >> 15) & 1)).astype(np.uint16)
    return rounded.tobytes()


def upcast_bf16_bytes(data: bytes) -> bytes:
    """Inverse of :func:`downcast_f32_bytes`: bf16 halves -> f32 with
    zero-filled mantissa tails."""
    half = np.frombuffer(data, dtype=np.uint16)
    return (half.astype(np.uint32) << 16).tobytes()


# ------------------------------------------------- collective quantization
def quantize_array(arr, precision: str,
                   block: int = _INT8_BLOCK) -> Dict[str, object]:
    """Quantize one rank's contribution before the wire (numpy kernels;
    the mesh backend runs the jnp twins of this math inside shard_map).
    Returns a payload dict that is strictly smaller than the f32 input:
    ~2x for bf16, ~4x (minus per-block scales) for int8. Dequantize and
    ACCUMULATE at full precision with :func:`dequantize_array` —
    quantize-before-wire, f32 math after (EQuARX)."""
    if precision not in PRECISIONS:
        raise ValueError(
            f"unknown precision {precision!r} (want one of {PRECISIONS})")
    a = np.ascontiguousarray(np.asarray(arr, dtype=np.float32))
    if precision == "f32":
        return {"p": "f32", "q": a, "shape": a.shape}
    if precision == "bf16":
        u = a.view(np.uint32)
        q = ((u >> 16) + ((u >> 15) & 1)).astype(np.uint16)
        return {"p": "bf16", "q": q, "shape": a.shape}
    # int8, block-wise absmax scales: q = round(x / scale) with
    # scale = absmax(block)/127 — zeros stay exactly zero, each block's
    # dynamic range is its own (one outlier cannot flatten the tensor)
    flat = a.reshape(-1)
    pad = (-flat.size) % block
    padded = np.pad(flat, (0, pad)) if pad else flat
    blocks = padded.reshape(-1, block)
    scale = np.abs(blocks).max(axis=1, keepdims=True) / 127.0
    safe = np.where(scale == 0.0, 1.0, scale)
    q = np.clip(np.rint(blocks / safe), -127, 127).astype(np.int8)
    return {"p": "int8", "q": q, "scale": scale.astype(np.float32),
            "shape": a.shape, "n": flat.size}


def dequantize_array(payload: Dict[str, object]) -> np.ndarray:
    """Payload -> float32 array (the full-precision accumulation side)."""
    p = payload["p"]
    if p == "f32":
        return np.asarray(payload["q"], dtype=np.float32)
    if p == "bf16":
        q = np.asarray(payload["q"], dtype=np.uint16)
        return (q.astype(np.uint32) << 16).view(np.float32).reshape(
            payload["shape"])
    q = np.asarray(payload["q"], dtype=np.float32) * payload["scale"]
    return q.reshape(-1)[:payload["n"]].reshape(payload["shape"])


def quantized_nbytes(payload: Dict[str, object]) -> int:
    """Bytes this payload puts on the wire (the accuracy-vs-speed
    report's numerator)."""
    n = payload["q"].nbytes
    scale = payload.get("scale")
    if scale is not None:
        n += scale.nbytes
    return n


def count_quantized_op(op: str, precision: str) -> None:
    """Bump rmt_collective_quantized_ops_total{op,precision}; never
    fails the collective."""
    try:
        from . import metrics_defs as mdefs

        mdefs.collective_quantized_ops().inc(
            tags={"op": op, "precision": precision})
    except Exception:  # noqa: BLE001
        pass


def _observe(codec: str, op: str, raw: int, wire: int,
             seconds: float) -> None:
    """Per-codec movement accounting; never fails the data path.
    bytes_in counts the LOGICAL (decoded) side, bytes_out the wire side
    — bytes_out/bytes_in is the achieved ratio either direction."""
    try:
        from . import metrics_defs as mdefs

        tags = {"codec": codec}
        if op == "encode":
            mdefs.transfer_compress_bytes_in().inc(raw, tags=tags)
            mdefs.transfer_compress_bytes_out().inc(wire, tags=tags)
        mdefs.transfer_compress_seconds().observe(
            seconds, tags={"codec": codec, "op": op})
    except Exception:  # noqa: BLE001
        pass


def count_skip(reason: str) -> None:
    """One payload that bypassed encoding (too small / probe said
    incompressible / peer negotiated nothing)."""
    try:
        from . import metrics_defs as mdefs

        mdefs.transfer_compress_skipped().inc(tags={"reason": reason})
    except Exception:  # noqa: BLE001
        pass
