"""Scheduling strategies, mirroring python/ray/util/scheduling_strategies.py:15,41."""

from __future__ import annotations

from typing import Optional

DEFAULT = "DEFAULT"  # hybrid pack-then-spread (hybrid_scheduling_policy.h:48)
SPREAD = "SPREAD"    # least-utilized spread (spread_scheduling_policy)


class NodeAffinitySchedulingStrategy:
    """Pin to a node; ``soft=True`` allows fallback when the node is gone
    (scheduling_strategies.py:41)."""

    def __init__(self, node_id, soft: bool = False):
        self.node_id = node_id
        self.soft = soft

    def __repr__(self):
        return f"NodeAffinity({self.node_id}, soft={self.soft})"


class PlacementGroupSchedulingStrategy:
    """Run inside a placement-group bundle (scheduling_strategies.py:15)."""

    def __init__(
        self,
        placement_group,
        placement_group_bundle_index: int = -1,
        placement_group_capture_child_tasks: bool = False,
    ):
        self.placement_group = placement_group
        self.placement_group_bundle_index = placement_group_bundle_index
        self.placement_group_capture_child_tasks = placement_group_capture_child_tasks


class TopologySchedulingStrategy:
    """TPU-native addition: request ICI-contiguous placement.

    The reference's scheduler is topology-blind (SURVEY.md §7 hard parts); on
    TPU pods, ICI adjacency is a first-class scheduling dimension. ``form``
    selects the desired chip/host adjacency, e.g. "ici-ring" or "ici-torus-2d".
    """

    def __init__(self, form: str = "ici-ring", slice_name: Optional[str] = None):
        self.form = form
        self.slice_name = slice_name
