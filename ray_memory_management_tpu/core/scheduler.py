"""Cluster scheduler: node selection policies over GCS node state.

The ClusterResourceScheduler / policy-set analog (src/ray/raylet/scheduling/):
  - DEFAULT = hybrid pack-then-spread (policy/hybrid_scheduling_policy.h:48):
    prefer low-index nodes while their utilization stays under the spread
    threshold, then fall back to least-utilized.
  - SPREAD = least utilized first (spread_scheduling_policy).
  - NodeAffinity hard/soft (scheduling_strategies.py:41).
  - Placement-group bundles reserve resources up front and tasks draw from the
    bundle, not the free pool (placement_group_resource_manager.h) — handled
    in placement_group.py, which calls back into this scheduler for the
    initial bundle placement with PACK/SPREAD/STRICT_* policies
    (bundle_scheduling_policy.h:82-109).

The reference's two-level lease protocol (raylet_client.h:398) now has a
partial analog: LEAF tasks (no placement/affinity constraint, args
inline) are handed to a node agent's local lease pool and the AGENT
picks the worker, spilling back to this scheduler when its pool
saturates (Runtime._try_leaf_place / NodeManager.submit_leaf). Every
constrained task still takes this centralized pass, which is exact —
not an approximation — for a single driver.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

from ..config import Config
from ..ids import NodeID
from .gcs import GCS
from .metrics_defs import (
    scheduler_locality_bytes_avoided,
    scheduler_locality_hits,
    scheduler_locality_misses,
    scheduler_placements,
    scheduler_queue_depth,
)
from .resources import NodeResources, Resources
from .scheduling_strategies import (
    NodeAffinitySchedulingStrategy,
    PlacementGroupSchedulingStrategy,
    SPREAD,
)


class ClusterScheduler:
    def __init__(self, gcs: GCS, config: Optional[Config] = None,
                 load_fn=None):
        self.gcs = gcs
        self.config = config or Config()
        self._lock = threading.RLock()
        self._rr_counter = 0
        # queued-task depth per node (injected by the runtime); used to
        # balance leases when every feasible node is at capacity
        self.load_fn = load_fn or (lambda node_id: 0)
        self._m_placements = scheduler_placements()
        self._m_loc_hits = scheduler_locality_hits()
        self._m_loc_misses = scheduler_locality_misses()
        self._m_loc_bytes = scheduler_locality_bytes_avoided()

    # -- policy entry ---------------------------------------------------------
    def pick_node(self, req: Resources, strategy=None,
                  queue_if_busy: bool = True,
                  locality: Optional[Dict[NodeID, int]] = None
                  ) -> Optional[NodeID]:
        """``locality`` maps candidate node -> argument bytes already
        resident there (computed by the router's batched scheduling pass
        from the GCS object directory). None/empty means no ref args or
        locality disabled — the pre-locality policies apply unchanged."""
        node_id = self._pick_node(req, strategy, queue_if_busy, locality)
        if node_id is not None:
            self._m_placements.inc()
            if locality and self.config.scheduler_locality_weight > 0:
                resident = locality.get(node_id, 0)
                # hit/miss accounting engages only past the gate — below
                # it the policy never weighed data placement at all
                if max(locality.values()) >= self.config.locality_min_bytes:
                    if resident >= self.config.locality_min_bytes:
                        self._m_loc_hits.inc()
                    else:
                        self._m_loc_misses.inc()
                if resident:
                    # bytes the data plane never moves, however we landed
                    self._m_loc_bytes.inc(resident)
        return node_id

    def publish_load(self) -> None:
        """Refresh the per-node dispatch-queue-depth gauge (called from
        the runtime's heartbeat loop — not per pick, which is the task
        hot path)."""
        g = scheduler_queue_depth()
        for n in self.gcs.alive_nodes():
            g.set(float(self.load_fn(n.node_id)),
                  tags={"node_id": n.node_id.hex()[:12]})

    def _locality_pick(self, fitting, locality) -> Optional[NodeID]:
        """Soft locality score over the FITTING set (so it can never pick
        an infeasible or saturated node — spillback and feasibility were
        already decided). Engages only when some fitting node holds >=
        locality_min_bytes of the task's args; the weighted score trades
        resident bytes against utilization and dispatch-queue depth so a
        busy holder loses to an idle peer once the queue-delay cost
        outweighs the transfer it avoids. Device-tier (HBM-pinned) args
        arrive pre-weighted from _batch_locality — the holder of a live
        device pin counts the bytes double, since placing elsewhere pays
        a device→host materialization before the wire hop."""
        w = self.config.scheduler_locality_weight
        if not locality or w <= 0:
            return None
        max_bytes = max(locality.get(n.node_id, 0) for n in fitting)
        if max_bytes < self.config.locality_min_bytes:
            return None

        def score(n):
            # bytes term normalized to [0, w]; utilization in [0, 1];
            # queue depth squashed to [0, 1) so one pathological backlog
            # can't dominate the comparison
            load = self.load_fn(n.node_id)
            return (w * (locality.get(n.node_id, 0) / max_bytes)
                    - n.resources.utilization()
                    - load / (load + 4.0))

        return max(fitting, key=lambda n: (score(n), -n.index)).node_id

    def _pick_node(self, req: Resources, strategy=None,
                   queue_if_busy: bool = True, locality=None
                   ) -> Optional[NodeID]:
        """Select a node to lease the task to.

        With ``queue_if_busy`` (the task path) a task always lands on SOME
        feasible node: when every feasible node is at capacity it leases to
        the least-queued one and drains from that node's dispatch queue as
        resources free (the raylet-queue model — the owner never re-runs
        cluster scheduling per pump, which would be quadratic in backlog
        depth). Without it (the actor path, which allocates immediately on
        the chosen node) a busy cluster returns None so the caller can wait
        for real capacity. Raises ValueError if no alive node could EVER
        host the request (infeasible — the reference surfaces this as a
        pending infeasible task warning)."""
        with self._lock:
            nodes = self.gcs.alive_nodes()
            # single-node fast path: with one alive node and no strategy the
            # full policy walk always lands there — skip it (this sits on
            # the per-task submit path)
            if strategy is None and queue_if_busy and len(nodes) == 1:
                node = nodes[0]
                if node.resources.is_feasible(req):
                    return node.node_id
                raise ValueError(
                    f"infeasible resource request {req.to_dict()}: no alive "
                    f"node can ever satisfy it"
                )
            if isinstance(strategy, PlacementGroupSchedulingStrategy):
                raise RuntimeError(
                    "PG strategies are resolved by PlacementGroupManager"
                )
            if isinstance(strategy, NodeAffinitySchedulingStrategy):
                target = next(
                    (n for n in nodes if n.node_id == strategy.node_id), None
                )
                if target and target.resources.is_feasible(req):
                    if queue_if_busy or target.resources.can_fit(req):
                        return target.node_id  # queue on the pinned node
                    return None  # wait for resources on the pinned node
                if not strategy.soft:
                    raise ValueError(
                        f"node affinity unsatisfiable for {strategy.node_id}"
                    )
                # soft: fall through to default policy
            feasible = [n for n in nodes if n.resources.is_feasible(req)]
            if not feasible:
                raise ValueError(
                    f"infeasible resource request {req.to_dict()}: no alive "
                    f"node can ever satisfy it"
                )
            fitting = [n for n in feasible if n.resources.can_fit(req)]
            if not fitting:
                if not queue_if_busy:
                    return None
                # every feasible node is at capacity: lease to the node with
                # the shortest dispatch queue
                return min(
                    feasible,
                    key=lambda n: (self.load_fn(n.node_id), n.index),
                ).node_id
            if strategy == SPREAD:
                self._rr_counter += 1
                n_fit = len(fitting)
                rr = self._rr_counter
                fitting.sort(
                    key=lambda n: (n.resources.utilization(),
                                   (n.index + rr) % n_fit)
                )
                return fitting[0].node_id
            # soft locality (default policy only — SPREAD is explicit
            # anti-affinity, hard NodeAffinity/PG returned above): go to
            # the data when enough of it already sits on a fitting node
            chosen = self._locality_pick(fitting, locality)
            if chosen is not None:
                return chosen
            # hybrid: pack onto lowest-index node under the threshold, else
            # least-utilized (hybrid_scheduling_policy.h:48)
            threshold = self.config.scheduler_spread_threshold
            under = [n for n in fitting
                     if n.resources.utilization() < threshold]
            if under:
                return min(under, key=lambda n: n.index).node_id
            return min(fitting, key=lambda n: n.resources.utilization()).node_id

    # -- resource accounting --------------------------------------------------
    def allocate(self, node_id: NodeID, req: Resources) -> None:
        with self._lock:
            self.gcs.nodes[node_id].resources.allocate(req)

    def free(self, node_id: NodeID, req: Resources) -> None:
        with self._lock:
            info = self.gcs.nodes.get(node_id)
            if info is not None:
                info.resources.free(req)

    def cluster_resources(self) -> Dict[str, float]:
        total: Dict[str, float] = {}
        for n in self.gcs.alive_nodes():
            for k, v in n.resources.total.to_dict().items():
                total[k] = total.get(k, 0) + v
        return total

    def available_resources(self) -> Dict[str, float]:
        total: Dict[str, float] = {}
        for n in self.gcs.alive_nodes():
            for k, v in n.resources.available.to_dict().items():
                total[k] = total.get(k, 0) + v
        return total

    # -- bundle placement (used by PlacementGroupManager) ---------------------
    def place_bundles(
        self, bundles: List[Resources], policy: str
    ) -> Optional[List[NodeID]]:
        """Choose a node per bundle under PACK/SPREAD/STRICT_PACK/
        STRICT_SPREAD (bundle_scheduling_policy.h:82-109). Returns None if
        unplaceable now. Resources are NOT allocated here — the PG manager
        commits them (two-phase prepare/commit, as in the reference)."""
        with self._lock:
            nodes = self.gcs.alive_nodes()
            avail = {
                n.node_id: Resources.from_fixed(
                    n.resources.available.fixed()
                )
                for n in nodes
            }
            order = sorted(nodes, key=lambda n: n.index)

            def fit_on(node_id, req) -> bool:
                return req.fits_in(avail[node_id])

            def take(node_id, req):
                avail[node_id] = avail[node_id] - req

            result: List[Optional[NodeID]] = []
            if policy == "STRICT_PACK":
                for n in order:
                    trial = Resources.from_fixed(avail[n.node_id].fixed())
                    ok = True
                    for b in bundles:
                        if b.fits_in(trial):
                            trial = trial - b
                        else:
                            ok = False
                            break
                    if ok:
                        return [n.node_id] * len(bundles)
                return None
            if policy == "STRICT_SPREAD":
                used: set = set()
                for b in bundles:
                    cand = next(
                        (n for n in order
                         if n.node_id not in used and fit_on(n.node_id, b)),
                        None,
                    )
                    if cand is None:
                        return None
                    used.add(cand.node_id)
                    take(cand.node_id, b)
                    result.append(cand.node_id)
                return result
            if policy == "SPREAD":
                for b in bundles:
                    cands = [n for n in order if fit_on(n.node_id, b)]
                    if not cands:
                        return None
                    counts = {n.node_id: result.count(n.node_id) for n in cands}
                    cand = min(cands, key=lambda n: (counts[n.node_id], n.index))
                    take(cand.node_id, b)
                    result.append(cand.node_id)
                return result
            # PACK (default): fill low-index nodes first
            for b in bundles:
                cand = next((n for n in order if fit_on(n.node_id, b)), None)
                if cand is None:
                    return None
                take(cand.node_id, b)
                result.append(cand.node_id)
            return result
