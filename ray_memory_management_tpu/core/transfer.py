"""Peer-to-peer object transfer plane.

Every node — the head and each node agent — runs a :class:`TransferServer`
over its object store. All cross-node object movement is receiver-driven:
the destination dials the source's server and streams chunks STRAIGHT into
its own store allocation (``recv_bytes_into`` lands on the shm mapping, no
intermediate buffer). The head brokers only *locations* (who has the object,
where their server listens); payload bytes never transit the head.

This is the reference object manager's design (receiver-driven pulls over
dedicated gRPC streams, src/ray/object_manager/object_manager.h:114, chunked
per object_manager.proto:63-67) with admission control collapsed to two
caps: concurrent serving connections per source (the PullManager in-flight
cap analog, pull_manager.h:47) and concurrent fetches per destination.

Wire protocol (authenticated ``multiprocessing.connection``; versioned by
config.WIRE_PROTOCOL_VERSION — mismatches are refused at the request):
    client -> server   {"oid": <bytes>, "proto": <int>}
    server -> client   {"size": <int>}   or   {"error": <str>}
    server -> client   raw chunk frames until ``size`` bytes are sent
"""

from __future__ import annotations

import os
import socket
import struct
import threading
import time
from typing import Optional

_CONNECT_TIMEOUT = 20.0


def _observe_transfer(direction: str, nbytes: int, seconds: float) -> None:
    """Record one completed transfer in the size/latency histograms; never
    lets instrumentation fail a transfer."""
    try:
        from . import metrics_defs as mdefs

        tags = {"direction": direction}
        mdefs.transfer_bytes().observe(float(nbytes), tags=tags)
        mdefs.transfer_latency_seconds().observe(seconds, tags=tags)
    except Exception:  # noqa: BLE001
        pass


def _set_io_timeout(fd: int, seconds: float) -> None:
    """SO_RCVTIMEO/SO_SNDTIMEO on the connection's underlying socket
    (options live in the shared kernel socket, so setting them through a
    dup'd fd sticks; 0 clears)."""
    tv = struct.pack("ll", int(seconds), int((seconds % 1.0) * 1e6))
    s = socket.socket(fileno=os.dup(fd))
    try:
        s.setsockopt(socket.SOL_SOCKET, socket.SO_RCVTIMEO, tv)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_SNDTIMEO, tv)
    finally:
        s.close()


class TransferServer:
    """Serves one store's objects to peers. Spilled objects are served from
    the spill file (``store.read``) — serving never forces an allocation in
    a full store."""

    def __init__(self, store, authkey: bytes, chunk_size: int,
                 bind_host: str = "0.0.0.0", max_conns: int = 4):
        from multiprocessing.connection import Listener

        self.store = store
        self.chunk_size = chunk_size
        self._authkey = authkey
        # NO authkey on the Listener: accept() would run the challenge
        # handshake on the single accept thread, letting one stalled peer
        # wedge the whole server. The handshake runs per-connection on the
        # serve thread instead, under a socket IO timeout.
        self._listener = Listener((bind_host, 0))
        self.port: int = self._listener.address[1]
        self._sem = threading.BoundedSemaphore(max_conns)
        self._stop = threading.Event()
        threading.Thread(target=self._accept_loop, daemon=True,
                         name="xfer-accept").start()

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn = self._listener.accept()
            except Exception:  # noqa: BLE001 — closed listener
                if self._stop.is_set():
                    return
                continue
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True, name="xfer-serve").start()

    def _serve_conn(self, conn) -> None:
        """One request per connection; concurrency capped by the semaphore
        so a burst of pulls cannot monopolize the host (admission control,
        the PullManager cap analog)."""
        from multiprocessing.connection import (
            answer_challenge, deliver_challenge,
        )

        try:
            # bounded handshake: a peer that never answers times out the
            # recv instead of parking this thread forever (the accept
            # thread is already safe — it only spawns us). 30s matches
            # the client's per-operation budget: on a loaded single-core
            # host a BURST of concurrent handshakes contends for the GIL
            # and 10s was observed flaking a legitimate 8-way fetch.
            _set_io_timeout(conn.fileno(), 30.0)
            deliver_challenge(conn, self._authkey)
            answer_challenge(conn, self._authkey)
            # keep a (longer) IO timeout for the serve itself: a peer that
            # stalls mid-download would otherwise hold a semaphore slot and
            # a store read ref forever — max_conns such peers would wedge
            # this node's whole p2p plane
            _set_io_timeout(conn.fileno(), 60.0)
        except Exception:  # noqa: BLE001 — bad key / timeout / EOF
            try:
                conn.close()
            except OSError:
                pass
            return
        with self._sem:
            try:
                req = conn.recv()
                from ..config import WIRE_PROTOCOL_VERSION

                # strict: a missing proto is a pre-versioning peer
                if req.get("proto") != WIRE_PROTOCOL_VERSION:
                    conn.send({"error": (
                        "wire protocol mismatch: server speaks "
                        f"v{WIRE_PROTOCOL_VERSION}, peer spoke "
                        f"v{req.get('proto')}")})
                    return
                oid = req["oid"]
                view = self.store.read(oid)
                if view is None:
                    conn.send({"error": "object not in store"})
                    return
                t0 = time.monotonic()
                try:
                    n = len(view) if isinstance(view, bytes) else view.nbytes
                    conn.send({"size": n})
                    mv = memoryview(view)
                    try:
                        for off in range(0, n, self.chunk_size):
                            conn.send_bytes(mv[off:off + self.chunk_size])
                    finally:
                        mv.release()
                    _observe_transfer("serve", n, time.monotonic() - t0)
                finally:
                    if isinstance(view, memoryview):
                        self.store.release(oid)
            except (EOFError, OSError, KeyError, TypeError):
                pass
            except Exception:  # noqa: BLE001 — a bad peer must not leak
                pass  # the semaphore slot or kill the accept loop
            finally:
                try:
                    conn.close()
                except OSError:
                    pass

    def close(self) -> None:
        self._stop.set()
        try:
            self._listener.close()
        except OSError:
            pass


def create_or_wait(dst_store, oid: bytes, size: int, timeout: float = 30.0):
    """Allocate ``oid`` in ``dst_store``, handling the racing-fetch case:
    create() refuses while another fetch's copy of the SAME object is
    unsealed and in flight, and success is only real once the object is
    actually readable (the racer may die mid-stream and abort its
    partial copy — so create is RETRIED, not just waited out). Shared by
    the TCP pull and the same-host shm copy. Returns (buf, None) on a
    fresh allocation, (None, None) when the racing copy became readable,
    (None, error) on timeout."""
    deadline = time.monotonic() + timeout
    while True:
        try:
            return dst_store.create(oid, size), None
        except ValueError:
            pass
        if dst_store.contains(oid):
            return None, None
        if time.monotonic() >= deadline:
            return None, "concurrent transfer of this object never completed"
        time.sleep(0.05)


def fetch_object(host: str, port: int, authkey: bytes, oid: bytes,
                 dst_store, chunk_size: int,
                 timeout: float = 120.0) -> Optional[str]:
    """Pull one object from a peer's TransferServer straight into
    ``dst_store``. Returns None on success, an error string on failure.

    The receive lands chunk-by-chunk in the store allocation itself
    (``recv_bytes_into`` on the shm view) — no full-object staging buffer
    anywhere, which is what keeps a GB-scale transfer O(chunk) in memory
    on both ends.

    Every IO step is bounded: connect by _CONNECT_TIMEOUT, each recv/send
    by a per-operation socket timeout — a suspended or partitioned source
    fails the fetch instead of hanging the calling thread (and, on an
    agent, instead of pinning the oid unsealed forever, which would block
    the head's push fallback)."""
    from multiprocessing import AuthenticationError
    from multiprocessing.connection import (
        Connection, answer_challenge, deliver_challenge,
    )

    last_exc: Optional[BaseException] = None
    conn = None
    for attempt in range(2):
        # the connect/handshake phase retries ONCE: nothing has streamed
        # yet, and on a saturated host a GIL-starved peer can miss even a
        # generous handshake budget (observed: a full-suite teardown
        # starving an 8-way fetch's challenge past 30s). Data-phase
        # failures below stay single-shot — callers own those retries.
        try:
            sock = socket.create_connection((host, port),
                                            timeout=_CONNECT_TIMEOUT)
            sock.settimeout(None)  # timeouts via SO_RCVTIMEO below
            conn = Connection(sock.detach())
            # per-operation bound: a healthy stream always progresses
            # within seconds; 30s of silence on any single recv means
            # the peer is gone
            _set_io_timeout(conn.fileno(), min(timeout, 30.0))
            answer_challenge(conn, authkey)
            deliver_challenge(conn, authkey)
            break
        except Exception as e:  # noqa: BLE001 — peer down / auth refused
            last_exc = e
            if conn is not None:
                try:
                    conn.close()
                except OSError:
                    pass
                conn = None
            if isinstance(e, AuthenticationError):
                break  # a wrong key will not become right on retry
    if conn is None:
        return f"connect to {host}:{port} failed: {last_exc!r}"
    t0 = time.monotonic()
    try:
        from ..config import WIRE_PROTOCOL_VERSION

        conn.send({"oid": oid, "proto": WIRE_PROTOCOL_VERSION})
        hdr = conn.recv()
        err = hdr.get("error")
        if err:
            return err
        size = hdr["size"]
        buf, race_err = create_or_wait(dst_store, oid, size,
                                       timeout=min(timeout, 30.0))
        if buf is None:
            return race_err  # None: the racing copy became readable
        got = 0
        try:
            while got < size:
                n = conn.recv_bytes_into(buf[got:])
                got += n
        except BaseException:
            # abort the unsealed create so retries can re-allocate.
            # delete() handles unsealed entries directly (obj_delete
            # "aborts an unsealed create", shmstore.cpp:379) — sealing
            # first would briefly publish the TRUNCATED object as real,
            # and a concurrent reader's ref could make that permanent
            del buf
            try:
                dst_store.delete(oid)
            except Exception:  # noqa: BLE001
                pass
            raise
        dst_store.seal(oid)
        _observe_transfer("pull", size, time.monotonic() - t0)
        return None
    except (EOFError, OSError) as e:
        return f"transfer from {host}:{port} failed: {e!r}"
    except Exception as e:  # noqa: BLE001 — store full after wait, etc.
        return repr(e)
    finally:
        try:
            conn.close()
        except OSError:
            pass
