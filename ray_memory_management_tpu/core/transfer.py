"""Peer-to-peer object transfer plane.

Every node — the head and each node agent — runs a :class:`TransferServer`
over its object store. All cross-node object movement is receiver-driven:
the destination dials the source's server and streams chunks STRAIGHT into
its own store allocation (``recv_bytes_into`` lands on the shm mapping, no
intermediate buffer). The head brokers only *locations* (who has the object,
where their server listens); payload bytes never transit the head.

This is the reference object manager's design (receiver-driven pulls over
dedicated gRPC streams, src/ray/object_manager/object_manager.h:114, chunked
per object_manager.proto:63-67) with three throughput refinements:

  * **Striped pulls** (wire protocol v2): objects at or above
    ``transfer_stripe_threshold`` are fetched as ``transfer_stripe_count``
    parallel range requests, each streaming a disjoint ``{oid, offset,
    length}`` slice of the SAME destination allocation over its own
    connection. The object is sealed once after every stripe lands; any
    stripe failure aborts the unsealed create so a retry re-allocates.
  * **Connection reuse**: the server runs a request LOOP per authenticated
    connection (idle-timeout bounded) instead of one request per
    connection, and clients keep idle connections in a
    :class:`ConnectionPool` keyed by (host, port, authkey). The
    challenge/response handshake — two round trips plus HMAC, the dominant
    cost of a metadata-sized pull — is paid once per pooled connection,
    not once per object.
  * **Admission per request**: the ``max_conns`` semaphore caps concurrent
    *serving* requests (the PullManager in-flight cap analog,
    pull_manager.h:47); idle pooled connections hold no slot.

Wire protocol v2 (authenticated ``multiprocessing.connection``; versioned by
config.WIRE_PROTOCOL_VERSION — mismatches are refused per request, naming
both versions):
    client -> server   {"oid": <bytes>, "proto": <int>,
                        "offset": <int>?, "length": <int>?,
                        "defer_above": <int>?, "trace": <list>?}
    ..."trace" is an additive optional (trace_id, span_id, parent) tuple
    naming the task the pull serves; the server records its serve span
    under it so stripe pulls and broadcast-tree hops land on the
    submitting task's causal chain in the timeline dump.
    server -> client   {"size": <span>, "total": <nbytes>}      (payload)
                  or   {"size": <nbytes>, "deferred": true}     (no payload)
                  or   {"error": <str>}
    ...full-object replies also carry "crc" (CRC32 of the whole payload,
    additive optional key — still protocol v2) when the serving store can
    produce it; clients verify at stripe completion / stream end and
    treat a mismatch as object loss (re-pull), never silent corruption.
    server -> client   raw chunk frames until ``size`` bytes are sent
    ...the connection then awaits the next request (idle timeout applies).

Codec negotiation (additive, still v2 — the same pattern as ``crc`` /
``defer_above``): a payload-bearing request MAY carry ``"codecs": (names
best-first)`` naming the lossless wire codecs the CLIENT can decode. A
codec-unaware server ignores the key and streams raw; a codec-aware
server picks the first name it also supports and — only when the span
clears ``compress_min_bytes`` AND a trial-block probe says the bytes are
actually compressible — answers with ``"codec": <name>`` and streams
CRC-PREFIXED COMPRESSED FRAMES (4-byte big-endian CRC32 of the
compressed chunk, then the chunk) instead of raw chunks. A codec-unaware
client never sends the key, so it never sees a compressed frame. Frame
CRCs are verified BEFORE decode (a wire bit flip never reaches the
decompressor); the decoded payload is still verified against the
full-object ``crc`` (verify after decode). Either failure is object loss
— abort the unsealed create and re-pull — never silent corruption.

``defer_above`` lets one request serve both sizes: a small object streams
immediately (single round trip); a large one answers with its size only so
the client can allocate once and fan the payload out as range requests.

The multi-destination distribution TREE (who pulls from whom when one
object resolves to many destinations) lives in runtime.py's
``_transfer_from`` gate — this module only moves bytes point to point.
"""

from __future__ import annotations

import os
import socket
import struct
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from ..utils import faults
from ..utils.integrity import crc32, crc32_combine
from ..utils.retry import RetryPolicy
from . import codec as wire_codec

_CONNECT_TIMEOUT = 20.0
# per-stripe progress deadline default (config: transfer_stripe_deadline_s):
# a stripe whose socket makes no progress for this long is declared dead
# and its range re-pulled from an alternate holder
_DEFAULT_STRIPE_DEADLINE = 30.0
# module defaults used when a caller passes no explicit striping config
# (unit-level callers); runtime/node_agent call sites pass their scoped
# Config values explicitly
_DEFAULT_STRIPE_THRESHOLD = 8 * 1024 * 1024
_DEFAULT_STRIPE_COUNT = 4
_MIN_STRIPE_BYTES = 1 << 20  # never split below 1 MiB per stripe
_DEFAULT_COMPRESS_MIN = 64 * 1024  # config: transfer_compress_min_bytes


def _observe_transfer(direction: str, nbytes: int, seconds: float) -> None:
    """Record one completed transfer in the size/latency histograms; never
    lets instrumentation fail a transfer."""
    try:
        from . import metrics_defs as mdefs

        tags = {"direction": direction}
        mdefs.transfer_bytes().observe(float(nbytes), tags=tags)
        mdefs.transfer_latency_seconds().observe(seconds, tags=tags)
    except Exception:  # noqa: BLE001
        pass


def _count(metric_accessor: str, n: int = 1) -> None:
    """Bump one metrics_defs counter by accessor name; never fails the
    transfer path."""
    try:
        from . import metrics_defs as mdefs

        getattr(mdefs, metric_accessor)().inc(n)
    except Exception:  # noqa: BLE001
        pass


def _store_crc(store, oid: bytes) -> Optional[int]:
    """Full-object CRC32 from the serving store's lazy checksum cache
    (NodeObjectStore.checksum); None when the store has no cache or the
    object vanished. Never fails the serve path."""
    fn = getattr(store, "checksum", None)
    if fn is None:
        return None
    try:
        return fn(oid)
    except Exception:  # noqa: BLE001
        return None


def _set_io_timeout(fd: int, seconds: float) -> None:
    """SO_RCVTIMEO/SO_SNDTIMEO on the connection's underlying socket
    (options live in the shared kernel socket, so setting them through a
    dup'd fd sticks; 0 clears)."""
    tv = struct.pack("ll", int(seconds), int((seconds % 1.0) * 1e6))
    s = socket.socket(fileno=os.dup(fd))
    try:
        s.setsockopt(socket.SOL_SOCKET, socket.SO_RCVTIMEO, tv)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_SNDTIMEO, tv)
    finally:
        s.close()


def _shutdown_fd(fd: int) -> None:
    """shutdown(SHUT_RDWR) the kernel socket behind ``fd``. A plain
    close() does NOT free a socket another thread is blocked in
    accept()/recv() on — the in-flight syscall holds a kernel reference,
    the listen port stays bound, and a same-port rebind fails. shutdown
    wakes the blocked syscall so the socket actually dies."""
    try:
        s = socket.socket(fileno=os.dup(fd))
    except OSError:
        return
    try:
        s.shutdown(socket.SHUT_RDWR)
    except OSError:
        pass
    finally:
        s.close()


def _set_nodelay(fd: int) -> None:
    """TCP_NODELAY on both ends of every transfer connection: the
    request/reply exchanges are small frames, and Nagle + delayed ACK
    turns each into a ~40 ms stall — the entire latency budget of a
    metadata-sized pull (observed: 44 ms -> sub-ms p50 on loopback)."""
    try:
        s = socket.socket(fileno=os.dup(fd))
    except OSError:
        return  # e.g. an AF_UNIX test double
    try:
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    except OSError:
        pass
    finally:
        s.close()


class TransferServer:
    """Serves one store's objects to peers. Spilled objects are served from
    the spill file (``store.read``) — serving never forces an allocation in
    a full store.

    Each accepted connection runs a REQUEST LOOP after its handshake: the
    ``max_conns`` semaphore is held only while a request is actively
    serving, so a pool of idle peer connections costs no admission slots.
    A connection idle past ``idle_timeout`` is closed (clients re-dial)."""

    def __init__(self, store, authkey: bytes, chunk_size: int,
                 bind_host: str = "0.0.0.0", max_conns: int = 32,
                 idle_timeout: float = 30.0, bind_port: int = 0,
                 compression: str = "auto",
                 compress_min_bytes: int = _DEFAULT_COMPRESS_MIN):
        from multiprocessing.connection import Listener

        self.store = store
        self.chunk_size = chunk_size
        self.idle_timeout = idle_timeout
        self._authkey = authkey
        # serve-side willingness to compress: "auto" honors whatever the
        # CLIENT offers (the puller drives, receiver-driven like
        # everything else here), a codec name pins that one, "off" never
        # compresses. The client-side knob is config.transfer_compression
        # (it decides whether a fetch OFFERS codecs at all).
        self.compress_min_bytes = int(compress_min_bytes)
        if compression == "off":
            self._codecs: Tuple[str, ...] = ()
        elif compression == "auto":
            self._codecs = wire_codec.available_codecs()
        else:
            self._codecs = (compression,) if (
                compression in wire_codec.available_codecs()) else ()
        # NO authkey on the Listener: accept() would run the challenge
        # handshake on the single accept thread, letting one stalled peer
        # wedge the whole server. The handshake runs per-connection on the
        # serve thread instead, under a socket IO timeout.
        self._listener = Listener((bind_host, bind_port))
        self.port: int = self._listener.address[1]
        self._sem = threading.BoundedSemaphore(max_conns)
        self._stop = threading.Event()
        self._conns_mu = threading.Lock()
        self._conns: set = set()  # live serving connections  # guarded-by: _conns_mu
        # observability (read by tests/bench; += is GIL-atomic enough for
        # monotonic counters)
        self.connections_accepted = 0
        self.requests_served = 0
        self.bytes_served = 0        # logical payload bytes (decoded)
        self.bytes_served_wire = 0   # bytes actually on the wire
        self.compressed_serves = 0
        threading.Thread(target=self._accept_loop, daemon=True,
                         name="xfer-accept").start()

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn = self._listener.accept()
            except Exception:  # noqa: BLE001 — closed listener
                if self._stop.is_set():
                    return
                continue
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True, name="xfer-serve").start()

    def _serve_conn(self, conn) -> None:
        """Handshake once, then serve requests until the peer hangs up or
        goes idle. Concurrency is capped per REQUEST by the semaphore so a
        burst of pulls cannot monopolize the host (admission control, the
        PullManager cap analog) while idle pooled connections stay free."""
        from multiprocessing.connection import (
            answer_challenge, deliver_challenge,
        )

        try:
            # bounded handshake: a peer that never answers times out the
            # recv instead of parking this thread forever (the accept
            # thread is already safe — it only spawns us). 30s matches
            # the client's per-operation budget: on a loaded single-core
            # host a BURST of concurrent handshakes contends for the GIL
            # and 10s was observed flaking a legitimate 8-way fetch.
            _set_io_timeout(conn.fileno(), 30.0)
            _set_nodelay(conn.fileno())
            deliver_challenge(conn, self._authkey)
            answer_challenge(conn, self._authkey)
        except Exception:  # noqa: BLE001 — bad key / timeout / EOF
            try:
                conn.close()
            except OSError:
                pass
            return
        self.connections_accepted += 1
        with self._conns_mu:
            self._conns.add(conn)
        try:
            while not self._stop.is_set():
                try:
                    # idle bound between requests: a pooled connection
                    # nobody uses must not hold a thread + fd forever
                    _set_io_timeout(conn.fileno(), self.idle_timeout)
                    req = conn.recv()
                except Exception:  # noqa: BLE001 — EOF / idle timeout
                    return
                with self._sem:
                    try:
                        # serve under a (longer) IO timeout: a peer that
                        # stalls mid-download would otherwise hold a
                        # semaphore slot and a store read ref forever —
                        # max_conns such peers would wedge this node's
                        # whole p2p plane
                        _set_io_timeout(conn.fileno(), 60.0)
                        if not self._serve_request(conn, req):
                            return
                    except (EOFError, OSError, KeyError, TypeError):
                        return
                    except Exception:  # noqa: BLE001 — a bad peer must
                        return  # not leak the slot or kill the server
        finally:
            with self._conns_mu:
                self._conns.discard(conn)
            try:
                conn.close()
            except OSError:
                pass

    def _serve_request(self, conn, req: dict) -> bool:
        """Serve one v2 request. Returns True when the connection stays
        usable for another request, False when it must close (protocol
        mismatch, or a failure mid-stream)."""
        from ..config import WIRE_PROTOCOL_VERSION

        # strict: a missing proto is a pre-versioning peer
        if req.get("proto") != WIRE_PROTOCOL_VERSION:
            conn.send({"error": (
                "wire protocol mismatch: server speaks "
                f"v{WIRE_PROTOCOL_VERSION}, peer spoke "
                f"v{req.get('proto')}")})
            return False
        # fault plane, serve side: drop vanishes mid-request (peer sees
        # EOF), stall delays the reply past the client's stripe deadline,
        # error answers with a refusal, corrupt flips a payload byte on
        # the wire BEFORE any encode (the decoded-payload crc catches
        # it), corrupt-compressed flips a byte inside a compressed frame
        # AFTER its frame crc is stamped (the pre-decode frame crc
        # catches it; a no-op on uncompressed serves). The store's copy
        # is NEVER touched.
        act = faults.fire("transfer.send")
        if act is not None:
            if act.mode == "stall":
                act.sleep()
            elif act.mode == "error":
                conn.send({"error": (
                    f"injected error at transfer.send (#{act.seq})")})
                return True
            elif act.mode == "drop":
                return False
        corrupt = act is not None and act.mode == "corrupt"
        corrupt_comp = act is not None and act.mode == "corrupt-compressed"
        oid = req["oid"]
        trace = req.get("trace")
        w0 = time.time()
        view = self.store.read(oid)
        if view is None:
            conn.send({"error": "object not in store"})
            return True
        try:
            n = len(view) if isinstance(view, bytes) else view.nbytes
            offset = int(req.get("offset") or 0)
            length = req.get("length")
            defer_above = req.get("defer_above")
            if length is None and defer_above is not None and n > defer_above:
                # size-only answer: the client allocates once, then fans
                # the payload out as parallel range requests. The full-
                # object crc rides here so the client can verify the
                # combined stripes against it.
                reply = {"size": n, "deferred": True}
                c = _store_crc(self.store, oid)
                if c is not None:
                    reply["crc"] = c
                conn.send(reply)
                self.requests_served += 1
                return True
            span = (n - offset) if length is None else int(length)
            if offset < 0 or span < 0 or offset + span > n:
                conn.send({"error": (
                    f"bad range [{offset}, {offset + span}) for "
                    f"{n}-byte object")})
                return True
            t0 = time.monotonic()
            reply = {"size": span, "total": n}
            if offset == 0 and span == n:
                c = _store_crc(self.store, oid)
                if c is not None:
                    reply["crc"] = c
            # codec negotiation: compress only when the client offered a
            # codec we speak, the span clears the threshold, AND the
            # trial-block probe says the bytes will actually shrink —
            # incompressible payloads (ciphertext, random floats) skip
            # encoding entirely so the worst case stays ~the raw path
            cname = None
            offered = req.get("codecs")
            if offered and self._codecs:
                if span < self.compress_min_bytes:
                    wire_codec.count_skip("below_threshold")
                else:
                    cname, skip = wire_codec.choose_codec(
                        offered, self._codecs, view, span, offset)
                    if cname is None:
                        wire_codec.count_skip(skip)
                    else:
                        reply["codec"] = cname
            conn.send(reply)
            mv = memoryview(view)
            wire_bytes = 0
            try:
                for off in range(offset, offset + span, self.chunk_size):
                    end = min(off + self.chunk_size, offset + span)
                    chunk = mv[off:end]
                    if corrupt and off == offset:
                        chunk = faults.corrupt_bytes(chunk)
                    if cname is None:
                        conn.send_bytes(chunk)
                        wire_bytes += end - off
                    else:
                        frame = wire_codec.encode_frame(chunk, cname)
                        if corrupt_comp and off == offset:
                            # flip a byte of the COMPRESSED payload after
                            # its crc was stamped — exactly a wire bit
                            # flip; the client's frame verify must catch
                            # it before the decoder runs
                            frame = frame[:4] + faults.corrupt_bytes(
                                frame[4:])
                        conn.send_bytes(frame)
                        wire_bytes += len(frame)
            finally:
                mv.release()
            # byte/codec counters first, requests_served LAST: the client's
            # fetch returns the instant the final chunk lands, so readers
            # (bench, tests) use requests_served as the barrier proving
            # this request's accounting is complete
            self.bytes_served_wire += wire_bytes
            self.bytes_served += span
            if cname is not None:
                self.compressed_serves += 1
            self.requests_served += 1
            if offset or (length is not None and span < n):
                _count("transfer_stripe_requests")
            _observe_transfer("serve", span, time.monotonic() - t0)
            if trace:
                # serve-side span in THIS process's ring (agents ship it
                # to the head on the keepalive pong), carrying the trace
                # of the task the pull serves
                try:
                    from ..utils import timeline, tracing

                    timeline.record_event(
                        f"serve::{oid.hex()[:8]}", "transfer", w0,
                        time.time(),
                        extra={"offset": offset, "length": span},
                        trace=tracing.from_wire(trace))
                except Exception:  # noqa: BLE001 — never fail a serve
                    pass
            return True
        finally:
            if isinstance(view, memoryview):
                self.store.release(oid)

    def close(self) -> None:
        self._stop.set()
        # wake the blocked accept() so the listen socket actually dies
        # (close() alone leaves it bound — see _shutdown_fd)
        sl = getattr(self._listener, "_listener", None)
        ls = getattr(sl, "_socket", None)
        if ls is not None:
            _shutdown_fd(ls.fileno())
        try:
            self._listener.close()
        except OSError:
            pass
        # tear down live serving connections too: an idle pooled peer
        # connection would otherwise pin a serve thread (blocked in
        # recv) and its socket for up to idle_timeout after shutdown
        with self._conns_mu:
            conns = list(self._conns)
            self._conns.clear()
        for c in conns:
            try:
                _shutdown_fd(c.fileno())
                c.close()
            except OSError:
                pass


def _dial(host: str, port: int, authkey: bytes, timeout: float,
          retry: Optional[RetryPolicy] = None):
    """Dial a TransferServer and run the handshake. Returns (conn, None)
    or (None, error_string). The connect/handshake phase retries under
    the unified RetryPolicy (default: 2 attempts, the pre-policy budget):
    nothing has streamed yet, and on a saturated host a GIL-starved peer
    can miss even a generous handshake budget (observed: a full-suite
    teardown starving an 8-way fetch's challenge past 30s).

    An authentication refusal returns a DISTINCT error string
    ("authentication failed ...") that retry loops classify as permanent
    — a wrong key is indistinguishable from peer death under the old
    generic "connect ... failed" message — and bumps its own counter."""
    from multiprocessing import AuthenticationError
    from multiprocessing.connection import (
        Connection, answer_challenge, deliver_challenge,
    )

    policy = retry if retry is not None else RetryPolicy(
        max_attempts=2, base_backoff_s=0.05, plane="transfer.dial")
    attempt = 0
    while True:
        conn = None
        try:
            act = faults.fire("transfer.dial")
            if act is not None:
                if act.mode == "stall":
                    act.sleep()
                else:  # drop / error / corrupt: the dial just fails
                    act.raise_()
            sock = socket.create_connection((host, port),
                                            timeout=_CONNECT_TIMEOUT)
            sock.settimeout(None)  # timeouts via SO_RCVTIMEO below
            conn = Connection(sock.detach())
            # per-operation bound: a healthy stream always progresses
            # within seconds; 30s of silence on any single recv means
            # the peer is gone
            _set_io_timeout(conn.fileno(), min(timeout, 30.0))
            _set_nodelay(conn.fileno())
            answer_challenge(conn, authkey)
            deliver_challenge(conn, authkey)
            return conn, None
        except Exception as e:  # noqa: BLE001 — peer down / auth refused
            if conn is not None:
                try:
                    conn.close()
                except OSError:
                    pass
            if isinstance(e, AuthenticationError):
                # a wrong key will not become right on retry
                _count("transfer_auth_failures")
                return None, (f"authentication failed dialing "
                              f"{host}:{port}: {e!r}")
            if not policy.backoff(attempt):
                return None, f"connect to {host}:{port} failed: {e!r}"
            attempt += 1


class ConnectionPool:
    """Authenticated transfer connections kept alive across pulls, keyed
    by (host, port, authkey). ``acquire`` hands back an idle pooled
    connection when one exists (a HIT — no dial, no handshake) or dials a
    fresh one (a MISS). ``release`` returns a healthy connection for
    reuse, capped at ``max_idle_per_peer`` idle connections per peer.

    Staleness is detected on use, not here: the fetch path discards a
    pooled connection whose first request errors (server restarted, idle
    timeout fired) and retries on a freshly dialed one."""

    def __init__(self, max_idle_per_peer: int = 8):
        self.max_idle_per_peer = max_idle_per_peer
        self._mu = threading.Lock()
        self._idle: Dict[tuple, List] = {}  # guarded-by: _mu
        self._closed = False  # guarded-by: _mu
        self.hits = 0
        self.misses = 0

    def acquire(self, host: str, port: int, authkey: bytes,
                timeout: float = 120.0):
        """Returns (conn, pooled, error): ``pooled`` True means the
        connection came from the pool and MAY be stale — the caller must
        retry its first request on a fresh connection if it errors."""
        key = (host, port, bytes(authkey))
        with self._mu:
            idle = self._idle.get(key)
            if idle:
                self.hits += 1
                conn = idle.pop()
                _count("transfer_pool_hits")
                return conn, True, None
            self.misses += 1
        _count("transfer_pool_misses")
        conn, err = _dial(host, port, authkey, timeout)
        return conn, False, err

    def release(self, host: str, port: int, authkey: bytes, conn) -> None:
        """Return a HEALTHY connection (request fully consumed) for reuse;
        closes it when the pool is full or shut down."""
        key = (host, port, bytes(authkey))
        with self._mu:
            if not self._closed and self.max_idle_per_peer > 0:
                idle = self._idle.setdefault(key, [])
                if len(idle) < self.max_idle_per_peer:
                    idle.append(conn)
                    return
        try:
            conn.close()
        except OSError:
            pass

    @staticmethod
    def discard(conn) -> None:
        """Drop a connection whose stream state is unknown (errored or
        abandoned mid-payload): never back into the pool."""
        try:
            conn.close()
        except OSError:
            pass

    def close(self) -> None:
        with self._mu:
            self._closed = True
            conns = [c for idle in self._idle.values() for c in idle]
            self._idle.clear()
        for c in conns:
            try:
                c.close()
            except OSError:
                pass


def create_or_wait(dst_store, oid: bytes, size: int, timeout: float = 30.0):
    """Allocate ``oid`` in ``dst_store``, handling the racing-fetch case:
    create() refuses while another fetch's copy of the SAME object is
    unsealed and in flight, and success is only real once the object is
    actually readable (the racer may die mid-stream and abort its
    partial copy — so create is RETRIED, not just waited out). Shared by
    the TCP pull and the same-host shm copy. Returns (buf, None) on a
    fresh allocation, (None, None) when the racing copy became readable,
    (None, error) on timeout.

    When the store exposes ``wait_for_object_change`` (NodeObjectStore's
    seal/delete condition), the wait wakes within microseconds of the
    racing copy sealing or aborting; a short poll tick remains only as
    the backstop for seals performed by ANOTHER process through the shm
    segment directly (no in-process notification exists for those)."""
    deadline = time.monotonic() + timeout
    waiter = getattr(dst_store, "wait_for_object_change", None)
    while True:
        try:
            return dst_store.create(oid, size), None
        except ValueError:
            pass
        if dst_store.contains(oid):
            return None, None
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            return None, "concurrent transfer of this object never completed"
        if waiter is not None:
            waiter(min(remaining, 0.05))
        else:
            time.sleep(0.05)


def _recv_exact(conn, sub) -> None:
    """Stream exactly ``sub.nbytes`` into the (shm) view ``sub``; the
    per-operation socket timeout bounds every recv. Split out so tests
    can fault-inject a mid-stripe connection kill.

    Fault plane, receive side: drop kills this connection under the
    in-flight stream (the next recv sees EOF), stall delays past the
    stripe deadline, error raises mid-receive, corrupt flips a byte in
    the landed buffer AFTER the stream (what a bad DIMM/NIC on the
    receive path does — only the checksum can catch it)."""
    act = faults.fire("transfer.recv")
    if act is not None:
        if act.mode == "stall":
            act.sleep()
        elif act.mode == "error":
            act.raise_()
        elif act.mode == "drop":
            _shutdown_fd(conn.fileno())
    size = sub.nbytes
    got = 0
    while got < size:
        got += conn.recv_bytes_into(sub[got:])
    if act is not None and act.mode == "corrupt" and size:
        sub[0:1] = bytes([sub[0] ^ 0xFF])


def _recv_compressed(conn, sub, cname: str,
                     verify_frames: bool = True) -> None:
    """Stream CRC-prefixed compressed frames into ``sub`` until its
    span is fully decoded. Each frame's CRC is verified BEFORE decode;
    a frame integrity or decode failure raises OSError so the caller
    discards the connection (the stream position is unknowable) and the
    fetch aborts its unsealed create and re-pulls — the same loss path
    a raw checksum mismatch takes, never sealing garbage.

    Fault plane: same receive-side physics as :func:`_recv_exact`
    (corrupt flips a landed byte AFTER decode — only the decoded-payload
    crc can catch that one)."""
    act = faults.fire("transfer.recv")
    if act is not None:
        if act.mode == "stall":
            act.sleep()
        elif act.mode == "error":
            act.raise_()
        elif act.mode == "drop":
            _shutdown_fd(conn.fileno())
    size = sub.nbytes
    got = 0
    while got < size:
        frame = conn.recv_bytes()
        try:
            # decode lands directly in the destination view (zrle's zero
            # blocks become one memset — no intermediate materialization)
            got += wire_codec.decode_frame_into(
                frame, cname, sub[got:], verify_crc=verify_frames)
        except (wire_codec.FrameIntegrityError,
                wire_codec.CodecError) as e:
            _count("transfer_checksum_mismatch")
            raise OSError(
                f"compressed frame ({cname}) failed integrity/decode: "
                f"{e}") from e
    if act is not None and act.mode == "corrupt" and size:
        sub[0:1] = bytes([sub[0] ^ 0xFF])


def _request_range(conn, oid: bytes, offset: int, length: int, sub,
                   proto: int, trace=None, codecs=None,
                   verify_checksum: bool = True) -> None:
    """One range request on an authenticated connection: header exchange,
    then stream the span straight into ``sub``. Raises on any mismatch
    or stream failure (caller aborts the whole fetch)."""
    req = {"oid": oid, "proto": proto, "offset": offset,
           "length": length}
    if trace:
        req["trace"] = tuple(trace)
    if codecs:
        req["codecs"] = tuple(codecs)
    conn.send(req)
    hdr = conn.recv()
    err = hdr.get("error")
    if err:
        raise OSError(f"range [{offset}, {offset + length}) refused: {err}")
    if hdr["size"] != length:
        raise OSError(f"range [{offset}, {offset + length}) answered "
                      f"{hdr['size']} bytes")
    cname = hdr.get("codec")
    if cname:
        _recv_compressed(conn, sub, cname, verify_frames=verify_checksum)
    else:
        _recv_exact(conn, sub)


def _stripe_ranges(total: int, stripe_count: int) -> List[Tuple[int, int]]:
    """Split ``total`` bytes into up to ``stripe_count`` contiguous
    (offset, length) ranges, each at least _MIN_STRIPE_BYTES."""
    n = max(1, min(stripe_count, total // _MIN_STRIPE_BYTES))
    base, extra = divmod(total, n)
    ranges = []
    off = 0
    for i in range(n):
        span = base + (1 if i < extra else 0)
        ranges.append((off, span))
        off += span
    return ranges


def fetch_object(host: str, port: int, authkey: bytes, oid: bytes,
                 dst_store, chunk_size: int,
                 timeout: float = 120.0,
                 pool: Optional[ConnectionPool] = None,
                 stripe_threshold: Optional[int] = None,
                 stripe_count: Optional[int] = None,
                 alt_sources: Optional[Callable] = None,
                 retry: Optional[RetryPolicy] = None,
                 verify_checksum: bool = True,
                 stripe_deadline: Optional[float] = None,
                 trace=None, codecs=None) -> Optional[str]:
    """Pull one object from a peer's TransferServer straight into
    ``dst_store``. Returns None on success, an error string on failure.

    ``codecs``: lossless wire codecs THIS client can decode, best-first
    (``codec.client_codecs(config)``); None (the default) sends no codec
    keys at all — indistinguishable on the wire from a codec-unaware v2
    peer, so every existing caller keeps today's raw path.

    The receive lands chunk-by-chunk in the store allocation itself
    (``recv_bytes_into`` on the shm view) — no full-object staging buffer
    anywhere, which is what keeps a GB-scale transfer O(chunk) in memory
    on both ends. Objects at or above ``stripe_threshold`` are fetched as
    ``stripe_count`` parallel range requests into disjoint slices of that
    one allocation, sealed once after all stripes land.

    Failure handling, innermost to outermost:

      * A failed/stalled STRIPE (socket silent past ``stripe_deadline``)
        re-pulls just its range from an alternate holder resolved via
        ``alt_sources`` into the same unsealed create — mid-pull holder
        failover, no lineage re-execution, no re-transfer of the ranges
        that already landed.
      * A payload whose CRC32 disagrees with the serving store's ("crc"
        in the reply) is aborted and counted, never sealed — the outer
        loop re-pulls it.
      * The whole fetch retries under ``retry`` (unified RetryPolicy;
        default 3 attempts with jittered backoff), rotating across
        ``alt_sources()`` so a dead source is abandoned, not hammered.
        Non-retryable failures (authentication, protocol mismatch)
        surface immediately.

    ``alt_sources``: zero-arg callable returning the CURRENT live holder
    list as (host, port) tuples — typically a closure over the GCS object
    directory, re-invoked at each failover so holders that died since the
    fetch began are excluded and new copies are found.

    ``pool``: a ConnectionPool amortizes the dial + challenge handshake
    across pulls (and serves stripe connections). Without one, every
    connection is fresh and closed after use (the v1 economics). A stale
    pooled connection (server restarted / idle-timed-out) is detected on
    the first request and transparently retried on a fresh dial.

    Every IO step is bounded: connect by _CONNECT_TIMEOUT, each recv/send
    by a per-operation socket timeout — a suspended or partitioned source
    fails the fetch instead of hanging the calling thread (and, on an
    agent, instead of pinning the oid unsealed forever, which would block
    the head's push fallback)."""
    policy = retry if retry is not None else RetryPolicy(
        max_attempts=3, base_backoff_s=0.05, plane="transfer")
    sources: List[Tuple[str, int]] = [(host, port)]
    attempt = 0
    while True:
        h, p = sources[attempt % len(sources)]
        err = _fetch_once(h, p, authkey, oid, dst_store, chunk_size,
                          timeout, pool, stripe_threshold, stripe_count,
                          alt_sources, verify_checksum, stripe_deadline,
                          trace=trace, codecs=codecs)
        if err is None:
            return None
        if not policy.is_retryable(err):
            return err
        if alt_sources is not None:
            # rotate to the CURRENT holder set, preferring anything that
            # is not the source that just failed
            try:
                alts = [tuple(s) for s in (alt_sources() or [])]
            except Exception:  # noqa: BLE001
                alts = []
            if alts:
                rest = [s for s in alts if s != (h, p)]
                sources = rest or alts
        if not policy.backoff(attempt):
            return err
        attempt += 1


def _fetch_once(host: str, port: int, authkey: bytes, oid: bytes,
                dst_store, chunk_size: int, timeout: float,
                pool: Optional[ConnectionPool],
                stripe_threshold: Optional[int],
                stripe_count: Optional[int],
                alt_sources: Optional[Callable],
                verify_checksum: bool,
                stripe_deadline: Optional[float],
                trace=None, codecs=None) -> Optional[str]:
    """One fetch attempt from one source (the pre-policy fetch_object
    body). Returns None on success, an error string on failure; never
    leaves an unsealed create behind."""
    from ..config import WIRE_PROTOCOL_VERSION

    if stripe_threshold is None:
        stripe_threshold = _DEFAULT_STRIPE_THRESHOLD
    if not stripe_count:  # None or 0 = auto: parallel stripes need cores
        stripe_count = min(_DEFAULT_STRIPE_COUNT, os.cpu_count() or 1)
    if stripe_count <= 1:
        stripe_threshold = 1 << 62  # one stream: never defer/stripe

    def _acquire():
        if pool is not None:
            return pool.acquire(host, port, authkey, timeout)
        conn, err = _dial(host, port, authkey, timeout)
        return conn, False, err

    def _release(conn):
        if pool is not None:
            pool.release(host, port, authkey, conn)
        else:
            try:
                conn.close()
            except OSError:
                pass

    # first request, with one stale-pooled-connection retry: a pooled
    # connection the server already dropped (restart, idle timeout) fails
    # here before any payload moved — discard it and redo on a fresh dial
    conn = None
    hdr = None
    for _attempt in range(2):
        conn, pooled, err = _acquire()
        if conn is None:
            return err
        try:
            # re-arm the per-operation timeout: a pooled connection keeps
            # whatever (possibly stripe-deadline-short) timeout its last
            # user set
            _set_io_timeout(conn.fileno(), min(timeout, 30.0))
            first_req = {"oid": oid, "proto": WIRE_PROTOCOL_VERSION,
                         "defer_above": stripe_threshold}
            if trace:
                first_req["trace"] = tuple(trace)
            if codecs:
                first_req["codecs"] = tuple(codecs)
            conn.send(first_req)
            hdr = conn.recv()
            break
        except Exception as e:  # noqa: BLE001 — dead pooled conn
            ConnectionPool.discard(conn)
            conn = None
            if not pooled:
                return f"transfer from {host}:{port} failed: {e!r}"
    if conn is None or hdr is None:
        return f"transfer from {host}:{port} failed: stale connection"

    t0 = time.monotonic()
    try:
        err = hdr.get("error")
        if err:
            _release(conn)
            conn = None
            return err
        size = hdr["size"]
        expect_crc = hdr.get("crc")
        buf, race_err = create_or_wait(dst_store, oid, size,
                                       timeout=min(timeout, 30.0))
        if not hdr.get("deferred"):
            # single stream: the payload is already on the wire
            if buf is None:
                # a racing copy won (or timed out): the stream on this
                # connection is now unconsumable — never pool it
                ConnectionPool.discard(conn)
                conn = None
                return race_err
            try:
                cname = hdr.get("codec")
                if cname:
                    _recv_compressed(conn, buf, cname,
                                     verify_frames=verify_checksum)
                else:
                    _recv_exact(conn, buf)
                if verify_checksum and expect_crc is not None \
                        and crc32(buf) != expect_crc:
                    _count("transfer_checksum_mismatch")
                    raise _ChecksumMismatch(
                        f"payload checksum mismatch pulling "
                        f"{oid.hex()[:12]} from {host}:{port}")
            except BaseException:
                # abort the unsealed create so retries can re-allocate.
                # delete() handles unsealed entries directly (obj_delete
                # "aborts an unsealed create", shmstore.cpp:379) — sealing
                # first would briefly publish the TRUNCATED object as
                # real, and a concurrent reader's ref could make that
                # permanent
                del buf
                try:
                    dst_store.delete(oid)
                except Exception:  # noqa: BLE001
                    pass
                raise
            dst_store.seal(oid)
            _release(conn)
            conn = None
            _observe_transfer("pull", size, time.monotonic() - t0)
            return None

        # deferred header: no payload pending, the connection is clean
        if buf is None:
            _release(conn)
            conn = None
            return race_err
        first_conn, conn = conn, None  # ownership moves to the striped path
        return _striped_fetch(host, port, authkey, oid, dst_store, buf,
                              size, stripe_count, first_conn, pool,
                              _release, timeout, t0,
                              alt_sources=alt_sources,
                              expect_crc=expect_crc,
                              verify_checksum=verify_checksum,
                              stripe_deadline=stripe_deadline,
                              trace=trace, codecs=codecs)
    except _ChecksumMismatch as e:
        # the stream was fully consumed before the verify — the
        # connection stays usable, but the payload is poison
        return str(e)
    except (EOFError, OSError) as e:
        return f"transfer from {host}:{port} failed: {e!r}"
    except Exception as e:  # noqa: BLE001 — store full after wait, etc.
        return repr(e)
    finally:
        if conn is not None:
            ConnectionPool.discard(conn)


class _ChecksumMismatch(Exception):
    """Internal: a fully-received payload failed its CRC verify."""


def _striped_fetch(host: str, port: int, authkey: bytes, oid: bytes,
                   dst_store, buf, total: int, stripe_count: int,
                   first_conn, pool: Optional[ConnectionPool], _release,
                   timeout: float, t0: float,
                   alt_sources: Optional[Callable] = None,
                   expect_crc: Optional[int] = None,
                   verify_checksum: bool = True,
                   stripe_deadline: Optional[float] = None,
                   trace=None, codecs=None) -> Optional[str]:
    """Fan ``total`` bytes out as parallel range requests into disjoint
    slices of ``buf`` (the already-created, unsealed allocation).
    ``first_conn`` carries stripe 0; each other stripe acquires its own
    connection (pooled when available). Owns ``buf``: seals on success,
    aborts the create on any failure.

    A stripe that errors or stalls past ``stripe_deadline`` does NOT
    abort the fetch: its missing range is re-pulled from the alternate
    holders ``alt_sources()`` resolves at that moment — into the same
    unsealed allocation, leaving the stripes that already landed in
    place. Each stripe's CRC is computed in its own thread (overlapped
    with the other stripes' socket reads) and combined via
    ``crc32_combine`` against the serving store's full-object crc."""
    from ..config import WIRE_PROTOCOL_VERSION

    if stripe_deadline is None or stripe_deadline <= 0:
        stripe_deadline = _DEFAULT_STRIPE_DEADLINE
    ranges = _stripe_ranges(total, stripe_count)
    crcs: Dict[int, int] = {}  # offset -> crc32 of that landed range
    errors: List[str] = []
    mu = threading.Lock()

    def pull_range(offset: int, span: int, conn, release_fn,
                   src: Tuple[str, int]) -> bool:
        sub = buf[offset:offset + span]
        try:
            # the per-stripe progress deadline: silence on this socket
            # past it means the holder is stalled/dead — fail the stripe
            # (NOT the fetch) so its range can fail over
            _set_io_timeout(conn.fileno(),
                            min(stripe_deadline, timeout))
            _request_range(conn, oid, offset, span, sub,
                           WIRE_PROTOCOL_VERSION, trace=trace,
                           codecs=codecs, verify_checksum=verify_checksum)
            # crc over the DECODED stripe — the verify-after-decode half
            # of the integrity story (the frame crc already covered the
            # compressed bytes pre-decode)
            c = crc32(sub) if verify_checksum else 0
        except BaseException as e:  # noqa: BLE001
            ConnectionPool.discard(conn)
            with mu:
                errors.append(f"stripe [{offset}, {offset + span}) from "
                              f"{src[0]}:{src[1]} failed: {e!r}")
            return False
        finally:
            sub.release()
        with mu:
            crcs[offset] = c
        release_fn(conn)
        return True

    def pull_range_fresh(offset: int, span: int) -> None:
        if pool is not None:
            conn, _pooled, err = pool.acquire(host, port, authkey, timeout)
        else:
            conn, err = _dial(host, port, authkey, timeout)
        if conn is None:
            with mu:
                errors.append(err)
            return
        pull_range(offset, span, conn, _release, (host, port))

    threads = []
    for offset, span in ranges[1:]:
        t = threading.Thread(target=pull_range_fresh, args=(offset, span),
                             daemon=True, name="xfer-stripe")
        t.start()
        threads.append(t)
    pull_range(ranges[0][0], ranges[0][1], first_conn, _release,
               (host, port))
    for t in threads:
        t.join()

    missing = [(o, s) for (o, s) in ranges if o not in crcs]
    if missing and alt_sources is not None:
        # mid-pull holder failover: re-resolve LIVE holders and re-pull
        # only the missing ranges into the same unsealed create — the
        # landed stripes are kept, nothing re-runs lineage
        try:
            alts = [tuple(s) for s in (alt_sources() or [])]
        except Exception:  # noqa: BLE001
            alts = []
        alts = [s for s in alts if s != (host, port)]
        for offset, span in missing:
            for ah, ap in alts:
                if pool is not None:
                    conn, _pooled, err = pool.acquire(ah, ap, authkey,
                                                      timeout)
                else:
                    conn, err = _dial(ah, ap, authkey, timeout)
                if conn is None:
                    with mu:
                        errors.append(err)
                    continue

                def rel(c, _h=ah, _p=ap):
                    if pool is not None:
                        pool.release(_h, _p, authkey, c)
                    else:
                        try:
                            c.close()
                        except OSError:
                            pass

                if pull_range(offset, span, conn, rel, (ah, ap)):
                    _count("transfer_failovers")
                    break
        missing = [(o, s) for (o, s) in ranges if o not in crcs]

    if missing:
        # unrecoverable: abort the unsealed create (all stripe threads
        # are done, their subviews released) so a retry can re-allocate
        del buf
        try:
            dst_store.delete(oid)
        except Exception:  # noqa: BLE001
            pass
        return errors[0] if errors else (
            f"striped pull of {oid.hex()[:12]} left ranges {missing}")

    if verify_checksum and expect_crc is not None:
        combined = 0
        for offset, span in ranges:
            combined = crc32_combine(combined, crcs[offset], span)
        if combined != expect_crc:
            _count("transfer_checksum_mismatch")
            del buf
            try:
                dst_store.delete(oid)
            except Exception:  # noqa: BLE001
                pass
            return (f"payload checksum mismatch pulling "
                    f"{oid.hex()[:12]} from {host}:{port} (striped)")
    dst_store.seal(oid)
    _count("transfer_striped_fetches")
    _observe_transfer("pull", total, time.monotonic() - t0)
    return None


# --------------------------------------------------------------------------
# ICI-first device transfer plane
#
# When producer and consumer sit on the SAME mesh — the same process, or
# processes joined into one jax distributed mesh — a device object moves
# device-to-device over the interconnect (a jitted transfer compiled per
# (shape, dtype, src, dst)) instead of paying device→host copy, host
# serialization, and the shm/DCN wire. Everything else falls back to the
# v2 striped host path above; the decision is made where the directory
# already resolves holders (runtime._ensure_device_materialized /
# _batch_locality). On CPU-backed jax (tier-1) every process is its own
# single-device mesh, so the decision logic and the fallback path are
# exercised end-to-end while the compiled move degrades to an identity
# jit on the one local device.

_ici_lock = threading.Lock()
_ici_moves: Dict[tuple, Callable] = {}  # guarded-by: _ici_lock
_ici_fingerprint: Optional[tuple] = None  # guarded-by: _ici_lock
_PROCESS_TOKEN = os.urandom(8).hex()


def mesh_fingerprint() -> Optional[tuple]:
    """Identity of the mesh THIS process's devices belong to. Processes
    with equal fingerprints can move device objects over the
    interconnect without a host hop. A process inside a multi-process
    jax distributed mesh is identified by the global device set; a lone
    process (CPU tier-1, single-host dev) is its OWN mesh — a random
    process token keeps two unrelated CPU processes from aliasing.
    None when jax is unavailable or uninitialized."""
    global _ici_fingerprint
    with _ici_lock:
        if _ici_fingerprint is not None:
            return _ici_fingerprint
    try:
        import jax

        platform = jax.default_backend()
        if jax.process_count() > 1:
            fp = (platform, jax.device_count(), "distributed")
        else:
            fp = (platform,
                  tuple(d.id for d in jax.local_devices()),
                  _PROCESS_TOKEN)
    except Exception:  # noqa: BLE001 — no jax, no device plane
        return None
    with _ici_lock:
        _ici_fingerprint = fp
    return _ici_fingerprint


def same_mesh(a: Optional[tuple], b: Optional[tuple]) -> bool:
    """True when two processes' device sets share one interconnect
    domain (fingerprints match). The ICI route is only taken when this
    holds; otherwise the host wire path is authoritative."""
    if a is None or b is None:
        return False
    return tuple(a) == tuple(b)


def _source_device(arr):
    try:
        devs = getattr(arr, "devices", None)
        if callable(devs):
            ds = list(devs())
            if ds:
                return ds[0]
        return getattr(arr, "device", None)
    except Exception:  # noqa: BLE001
        return None


def ici_move(arr, dst_device, donate: bool = False):
    """Move a device array to ``dst_device`` with a jitted
    device-to-device transfer, compiled once per (shape, dtype, src,
    dst) and cached — steady-state handoffs pay only the interconnect
    copy. ``donate`` releases the source buffer into the move (the
    consuming side of a last-reader handoff); donation is skipped on
    CPU where XLA does not honor it. Counts
    ``rmt_device_ici_transfers_total``."""
    import jax

    src = _source_device(arr)
    if src is not None and dst_device is not None and src == dst_device:
        _count("device_ici_transfers")
        return arr  # already home: the zero-length transfer
    key = (tuple(getattr(arr, "shape", ())), str(getattr(arr, "dtype", "")),
           getattr(src, "id", None), getattr(dst_device, "id", None),
           bool(donate))
    with _ici_lock:
        fn = _ici_moves.get(key)
    if fn is None:
        from jax.sharding import SingleDeviceSharding

        kwargs = {"out_shardings": SingleDeviceSharding(dst_device)}
        if donate and jax.default_backend() != "cpu":
            kwargs["donate_argnums"] = (0,)
        fn = jax.jit(lambda x: x, **kwargs)
        with _ici_lock:
            _ici_moves[key] = fn
    out = fn(arr)
    out.block_until_ready()
    _count("device_ici_transfers")
    return out


def ici_allgather_move(arr, mesh_devices, dst_index: int):
    """One-hot psum transfer across an explicit device list: each
    non-source position contributes zeros and the psum lands the payload
    on every mesh position, from which ``dst_index`` keeps its shard —
    the collective spelling of a point-to-point move for backends where
    direct device_put between chips bounces through the host. Falls
    back to :func:`ici_move` when shard_map is unavailable or the mesh
    is a single device."""
    from ..utils.jax_compat import HAS_SHARD_MAP

    if not HAS_SHARD_MAP or len(mesh_devices) < 2:
        return ici_move(arr, mesh_devices[dst_index])
    try:
        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        from ..utils.jax_compat import shard_map

        mesh = Mesh(list(mesh_devices), ("x",))

        def _relay(x):
            return jax.lax.psum(x, "x")

        moved = shard_map(_relay, mesh=mesh, in_specs=P(),
                          out_specs=P())(jnp.asarray(arr))
        out = jax.device_put(moved, mesh_devices[dst_index])
        out.block_until_ready()
        _count("device_ici_transfers")
        return out
    except Exception:  # noqa: BLE001 — collective spelling is best-effort
        return ici_move(arr, mesh_devices[dst_index])
