"""Zygote fork-server: pre-imports the worker stack once, forks workers in ms.

The reference's WorkerPool keeps worker *processes* warm (prestart + startup
tokens, src/ray/raylet/worker_pool.h:104,349,427) because forking a Python
interpreter that has already imported the runtime is two orders of magnitude
cheaper than exec'ing a fresh one. Here the gap is even larger: on this
image a cold interpreter pays ~2.3s of TPU-plugin registration (interpreter
sitecustomize) or ~0.1s with the trigger env dropped, while a fork of a
warmed zygote costs ~2ms — on a small host creating hundreds of actors,
cold spawns serialize on the CPU and cap actor creation at a few per
second (the round-3 scale bench measured 2.8/s vs the reference's 510/s).

One zygote process serves one node (it is env-configured for that node's
store/socket). Protocol over an authenticated Unix socket, one connection
per spawn:

    request:  {"env": {full worker environment}}
    reply:    {"pid": <forked worker pid>}  or  {"error": "..."}
    request:  {"type": "shutdown"}          -> zygote exits

The fork is safe by construction: the zygote's only thread is the accept
loop (no locks can be held across fork), and it never imports jax or
touches the TPU — TPU-platform workers need the interpreter-startup plugin
registration, so they always cold-spawn through subprocess instead
(node_manager.build_worker_env keeps their trigger env).

Forked workers are auto-reaped (SIGCHLD ignored in the zygote; the child
restores default handling so user code's subprocesses wait() normally).
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import threading
import time
from typing import Any, Dict, Optional


def serve(socket_path: str, authkey: bytes) -> None:
    """Zygote main loop. Runs in a dedicated process.

    One PERSISTENT connection per client (request/reply in lockstep): the
    per-spawn cost is one small recv + fork + one small send, not a fresh
    socket connect + HMAC challenge (which costs more than the fork
    itself). Clients reconnect if the connection drops."""
    from multiprocessing.connection import Listener

    # preload everything a worker touches so forked children import nothing:
    # the worker module pulls in serialization (cloudpickle), the native shm
    # client, and the task executor machinery; numpy dominates user payloads.
    # The tail of lazy imports (cloudpickle, json, runtime_env, utils — all
    # touched on the first create_actor/exec) was measured at ~0.2s of
    # per-child CPU; importing them here moves that cost to zygote startup,
    # paid once.
    import dataclasses  # noqa: F401
    import json  # noqa: F401

    import cloudpickle  # noqa: F401
    import numpy  # noqa: F401

    from .. import runtime_env, serialization, utils  # noqa: F401
    from ..utils import actor_pool, queue, timeline  # noqa: F401
    from . import (  # noqa: F401
        device_store,
        placement_group,
        resources,
        scheduling_strategies,
        worker,
        worker_main,
    )

    # freeze the preloaded heap into gc's permanent generation: forked
    # children's collector then never scans (and so never copy-on-writes)
    # the module objects they inherited — the standard prefork-server gc
    # discipline for CPython
    import gc

    gc.collect()
    gc.freeze()

    signal.signal(signal.SIGCHLD, signal.SIG_IGN)  # auto-reap forked workers
    listener = Listener(socket_path, family="AF_UNIX", authkey=authkey)
    # Child baseline for the env-delta protocol. The CLIENT ships its
    # _base_env with each fresh connection ("base_env" key on the first
    # frame): children must reset to the exact dict deltas were computed
    # against. Neither the zygote's launch environ nor a serve-time
    # snapshot can stand in for it — this interpreter's own startup
    # (sitecustomize setting JAX_PLATFORMS for the TPU image) and any
    # preloaded class's imports mutate os.environ before/after serve
    # begins, and that drift must never leak into workers. The startup
    # snapshot below is only the fallback for a client that never sent
    # one (then deltas were computed against the same launch env).
    base_env = {k: v for k, v in os.environ.items()
                if k != "RMT_ZYGOTE_AUTHKEY"}

    def jax_backend_live() -> bool:
        mod = sys.modules.get("jax")
        if mod is None:
            return False
        try:
            from jax._src import xla_bridge

            return bool(xla_bridge._backends)
        except Exception:  # noqa: BLE001 — structure drift: assume live
            return True
        # (conservative: a layout we can't inspect is treated as live)

    # actor-class preload cache: the FIRST spawn carrying a given
    # cls_blob unpickles it HERE, once — every subsequent fork inherits
    # the loaded class via COW and skips the per-child cloudpickle.loads
    # (measured at a meaningful slice of the 2,000-actor burst's
    # per-child CPU). worker.create_actor checks this cache by cls_id.
    # Loading user code pre-fork risks the no-live-jax-backend invariant
    # (a blob whose import chain initializes a PJRT client would hand
    # every future child a fork-broken backend), so a load that trips
    # the guard below retires this zygote: the client cold-spawns the
    # current worker, blacklists the class, and starts a fresh zygote.
    def handle_one(req: dict) -> dict:
        """Serve one spawn request: preload (with the taint guard), fork,
        and — in the parent — return the reply dict. The forked child
        never returns (it becomes the worker and _exits)."""
        bootstrap = req.get("bootstrap")
        cls_cached = False
        if bootstrap is not None and not req.get("no_preload"):
            cls_id = bootstrap.get("cls_id")
            if cls_id is not None:
                if cls_id in worker.PRELOADED_CLASSES:
                    cls_cached = True
                elif bootstrap.get("cls_blob") is not None:
                    try:
                        worker.PRELOADED_CLASSES[cls_id] = \
                            cloudpickle.loads(bootstrap["cls_blob"])
                        cls_cached = True
                    except Exception:  # noqa: BLE001 — child loads
                        pass           # it from the blob as before
                    if jax_backend_live():
                        # the load initialized a backend in THIS
                        # process: forking now is unsafe. Retire.
                        worker.PRELOADED_CLASSES.pop(cls_id, None)
                        return {"cls_taint": True}
        try:
            pid = os.fork()
        except OSError as e:
            return {"error": repr(e)}
        if pid == 0:
            # --- child: become the worker ---------------------------------
            try:
                conn.close()
                listener.close()
                signal.signal(signal.SIGCHLD, signal.SIG_DFL)
                if "env" in req:
                    os.environ.clear()
                    os.environ.update(req["env"])
                else:
                    # delta protocol: the child resets to the FROZEN
                    # launch snapshot (the dict the client computed its
                    # delta against) — per spawn only the handful of
                    # per-worker vars cross the socket instead of the
                    # full ~3KB environment
                    os.environ.clear()
                    os.environ.update(base_env)
                    for k in req.get("env_removed") or ():
                        os.environ.pop(k, None)
                    os.environ.update(req.get("env_delta") or {})
                worker_main._bootstrap = bootstrap
                worker_main.main()
            except BaseException:  # noqa: BLE001 — never unwind into
                os._exit(1)        # the zygote's stack in a fork child
            os._exit(0)
        # --- parent -------------------------------------------------------
        # cls_cached acks the preload: the client then strips the
        # multi-KB cls_blob from subsequent spawns of this class
        return {"pid": pid, "cls_cached": cls_cached}

    while True:
        try:
            conn = listener.accept()
        except (OSError, EOFError):
            return
        while True:
            try:
                msg = conn.recv()
            except (EOFError, OSError):
                conn.close()
                break
            if msg.get("type") == "shutdown":
                conn.close()
                try:
                    listener.close()
                    os.unlink(socket_path)
                except OSError:
                    pass
                return
            if "base_env" in msg:
                base_env = {k: v for k, v in msg["base_env"].items()
                            if k != "RMT_ZYGOTE_AUTHKEY"}
            # batched spawns: concurrent client spawners combine into one
            # frame — a 2,000-actor burst pays one socket round trip (two
            # scheduling handoffs on a contended CPU) per BATCH of forks,
            # not per fork
            reqs = msg["spawns"] if "spawns" in msg else [msg]
            replies = []
            retire = False
            for req in reqs:
                rep = handle_one(req)
                replies.append(rep)
                if rep.get("cls_taint"):
                    retire = True  # unserved tail: client cold-spawns it
                    break
            out = {"replies": replies} if "spawns" in msg else replies[0]
            try:
                conn.send(out)
            except (OSError, BrokenPipeError):
                conn.close()
                break
            if retire:
                conn.close()
                try:
                    listener.close()
                    os.unlink(socket_path)
                except OSError:
                    pass
                return


class ForkedProc:
    """Popen-shaped facade over a worker forked by the zygote (we are not
    its parent, so liveness is a signal-0 probe and death is primarily
    detected by the runtime seeing the worker's pipe EOF — the same
    split RemoteProc uses for agent-spawned workers).

    PID-reuse guard: the kernel start time from /proc/<pid>/stat is
    recorded at creation; a recycled PID (worker died, auto-reaped, pid
    handed to an unrelated process) has a different start time, so poll()
    reports dead and terminate()/kill() refuse to signal the stranger."""

    __slots__ = ("pid", "returncode", "_starttime")

    def __init__(self, pid: int):
        self.pid = pid
        self.returncode: Optional[int] = None
        self._starttime = self._read_starttime(pid)
        if self._starttime is None:
            self.returncode = 1  # already gone before we looked

    @staticmethod
    def _read_starttime(pid: int) -> Optional[int]:
        try:
            with open(f"/proc/{pid}/stat", "rb") as f:
                # field 22, counting from 1, after the parenthesized comm
                return int(f.read().rsplit(b")", 1)[1].split()[19])
        except (OSError, IndexError, ValueError):
            return None

    def _alive(self) -> bool:
        st = self._read_starttime(self.pid)
        return st is not None and st == self._starttime

    def poll(self) -> Optional[int]:
        if self.returncode is not None:
            return self.returncode
        if not self._alive():
            self.returncode = 1
            return 1
        return None

    def wait(self, timeout: Optional[float] = None) -> int:
        deadline = None if timeout is None else time.monotonic() + timeout
        while self.poll() is None:
            if deadline is not None and time.monotonic() >= deadline:
                raise subprocess.TimeoutExpired(
                    f"forked-worker-{self.pid}", timeout)
            time.sleep(0.02)
        return self.returncode  # type: ignore[return-value]

    def terminate(self) -> None:
        if self.poll() is not None:
            return  # dead or pid recycled: never signal a stranger
        try:
            os.kill(self.pid, signal.SIGTERM)
        except (ProcessLookupError, PermissionError):
            self.returncode = self.returncode or 1

    def kill(self) -> None:
        if self.poll() is not None:
            return
        try:
            os.kill(self.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            self.returncode = self.returncode or 1


class _SpawnEntry:
    """One queued spawn request in the client's combining queue."""

    __slots__ = ("req", "reply", "done")

    def __init__(self, req: dict):
        self.req = req
        self.reply: Optional[dict] = None
        self.done = threading.Event()


class ZygoteClient:
    """Owns one zygote process and requests forks from it.

    ``spawn(env)`` returns a :class:`ForkedProc` or None (zygote not up /
    fork failed), in which case the caller falls back to a cold
    ``subprocess.Popen`` — the zygote is an accelerator, never a single
    point of failure."""

    def __init__(self, base_env: Dict[str, str], tag: str = "z"):
        self._authkey = os.urandom(16)
        self._socket_path = (
            f"/tmp/rmtZ_{os.getpid()}_{tag}_{os.urandom(3).hex()}.sock")
        env = dict(base_env)
        env["RMT_ZYGOTE_AUTHKEY"] = self._authkey.hex()
        # the zygote itself must never register the TPU plugin (fork would
        # hand every child a broken client); the env it serves workers is
        # passed per-request, so dropping the triggers here is always safe
        from ..config import Config

        for var in Config().cpu_worker_env_drop.split(","):
            if var:
                env.pop(var.strip(), None)
        # CPU platform, pinned: the zygote only ever forks CPU workers
        # (spawn_worker_process gates on JAX_PLATFORMS == "cpu"; TPU
        # workers always cold-spawn), and jax CAPTURES the platform list
        # at import — a class preload whose module chain imports jax
        # under any other value would poison every later child with a
        # platform no env reset can undo (the delta protocol resets
        # os.environ, not an already-imported jax's captured config)
        env["JAX_PLATFORMS"] = "cpu"
        # children inherit this exact dict; spawn() ships only the delta
        self._base_env = dict(env)
        self._proc = subprocess.Popen(
            [sys.executable, "-m",
             "ray_memory_management_tpu.core.zygote", self._socket_path],
            env=env, close_fds=True,
        )
        self._lock = threading.Lock()
        self._conn = None  # persistent request/reply connection
        self._ready = False
        # combining queue: concurrent spawners enqueue requests; whoever
        # holds the lock ships EVERY queued request as one batch frame
        self._q_mu = threading.Lock()
        self._q: list = []
        # actor classes the zygote confirmed preloaded (children inherit
        # them via COW): spawns of these ship WITHOUT the cls_blob
        self._cached_classes: set = set()
        # phase accounting for the scale bench (fork share of actor
        # creation): total forks requested, batch round trips made, and
        # seconds spent in them (seconds/forks = amortized per-fork RT)
        self.spawn_count = 0
        self.spawn_batches = 0
        self.spawn_seconds = 0.0

    def _connect(self, timeout: float = 10.0):
        from multiprocessing.connection import Client

        deadline = time.monotonic() + timeout
        while True:
            try:
                return Client(self._socket_path, family="AF_UNIX",
                              authkey=self._authkey)
            except (FileNotFoundError, ConnectionRefusedError, OSError):
                if (time.monotonic() >= deadline
                        or self._proc.poll() is not None):
                    return None
                time.sleep(0.02)

    def spawn(self, env: Dict[str, str],
              bootstrap: Optional[dict] = None) -> Optional[ForkedProc]:
        if self._proc.poll() is not None:
            return None
        base = self._base_env
        req: Dict[str, Any] = {
            "env_delta": {k: v for k, v in env.items()
                          if base.get(k) != v},
            "env_removed": [k for k in base
                            if k != "RMT_ZYGOTE_AUTHKEY"
                            and k not in env],
        }
        if bootstrap is not None:
            cls_id = bootstrap.get("cls_id")
            if cls_id is not None and cls_id in _taint_classes:
                # this class's preload once initialized a jax backend
                # inside a zygote: never preload it again
                req["no_preload"] = True
            elif cls_id is not None \
                    and cls_id in self._cached_classes \
                    and bootstrap.get("cls_blob") is not None:
                bootstrap = dict(bootstrap)
                del bootstrap["cls_blob"]  # zygote preloaded it
            req["bootstrap"] = bootstrap
        # combining: enqueue, then either become the leader (ship every
        # queued request as ONE batch frame) or wait for a leader to ship
        # ours. An actor burst's concurrent spawners pay one socket round
        # trip per batch instead of one per fork.
        entry = _SpawnEntry(req)
        with self._q_mu:
            self._q.append(entry)
        while not entry.done.is_set():
            if self._lock.acquire(timeout=0.02):
                try:
                    if not entry.done.is_set():
                        self._serve_batch_locked()
                finally:
                    self._lock.release()
            else:
                entry.done.wait(0.05)
        reply = entry.reply
        if reply is None:
            return None
        if reply.get("cls_taint"):
            # the zygote retired itself rather than fork with a live
            # backend; blacklist the class and cold-spawn this worker
            # (get_global() starts a fresh zygote on the next spawn)
            cid = bootstrap.get("cls_id") if bootstrap else None
            if cid is not None:
                _taint_classes.add(cid)
            return None
        pid = reply.get("pid")
        if pid and bootstrap is not None and reply.get("cls_cached"):
            cid = bootstrap.get("cls_id")
            if cid is not None:
                self._cached_classes.add(cid)
        return ForkedProc(pid) if pid else None

    def _serve_batch_locked(self) -> None:
        """With the leader lock held: ship every queued spawn request as
        one frame, distribute replies, wake the waiters. Entries the
        zygote did not serve (connection loss, taint retirement mid-
        batch, ANY unexpected error) resolve to None and their callers
        cold-spawn — a leader must never strand the spawners riding its
        batch, so nothing here may raise once the queue is drained."""
        with self._q_mu:
            batch = self._q
            self._q = []
        if not batch:
            return
        try:
            self._serve_batch(batch)
        finally:
            for e in batch:  # idempotent: already-served entries are set
                if not e.done.is_set():
                    e.reply = None
                    e.done.set()

    def _serve_batch(self, batch) -> None:
        t0 = time.monotonic()
        if self._proc.poll() is not None:
            return
        # first use waits for the zygote to finish its import preload
        frame = {"spawns": [e.req for e in batch]}
        if self._conn is None:
            try:
                self._conn = self._connect(
                    timeout=1.0 if self._ready else 15.0)
            except Exception:  # noqa: BLE001 — e.g. AuthenticationError
                self._conn = None
            if self._conn is None:
                return
            self._ready = True
            # fresh connection: ship the baseline the deltas are computed
            # against — the zygote's own environ has drifted from it by
            # interpreter startup (sitecustomize) and preload imports
            frame["base_env"] = self._base_env
        try:
            self._conn.send(frame)
            replies = self._conn.recv()["replies"]
        except Exception:  # noqa: BLE001 — conn loss, protocol drift:
            try:                          # reset; the batch cold-spawns
                self._conn.close()
            except OSError:
                pass
            self._conn = None
            return
        self.spawn_seconds += time.monotonic() - t0
        self.spawn_count += len(batch)
        self.spawn_batches += 1
        for i, e in enumerate(batch):
            e.reply = replies[i] if i < len(replies) else None
            e.done.set()

    def close(self) -> None:
        if self._proc.poll() is None:
            with self._lock:
                conn = self._conn if self._conn is not None \
                    else self._connect(timeout=0.5)
                self._conn = None
                if conn is not None:
                    try:
                        conn.send({"type": "shutdown"})
                    except (OSError, BrokenPipeError):
                        pass
                    try:
                        conn.close()
                    except OSError:
                        pass
            try:
                self._proc.wait(timeout=2.0)
            except subprocess.TimeoutExpired:
                self._proc.terminate()
        try:
            os.unlink(self._socket_path)
        except OSError:
            pass


# ---------------------------------------------------------------- singleton
# One zygote serves every node hosted by this OS process (the worker env is
# per-request, so the server is node-agnostic): the driver's head-local
# nodes share one, each node agent has its own in its own process.
_global: Optional[ZygoteClient] = None
_global_mu = threading.Lock()
# classes whose preload initialized a jax backend inside a zygote (which
# then retired itself): survives zygote replacement so the same class
# can never taint the successor
_taint_classes: set = set()


def peek_global() -> Optional[ZygoteClient]:
    """The current zygote if one is running — never starts one. For
    observers (bench phase accounting) that must not pay for, or gate on,
    a fork server the config may have disabled."""
    return _global


def get_global() -> Optional[ZygoteClient]:
    """The process-wide zygote, started on first use. None if disabled or
    its process died (callers then cold-spawn)."""
    global _global
    with _global_mu:
        if _global is not None and _global._proc.poll() is not None:
            _global = None  # zygote died: replace it
        if _global is None:
            from .node_manager import package_env

            try:
                _global = ZygoteClient(package_env())
            except Exception:  # noqa: BLE001 — never block worker spawn
                return None
        return _global


def shutdown_global() -> None:
    global _global
    with _global_mu:
        if _global is not None:
            _global.close()
            _global = None


def main(argv=None) -> int:
    socket_path = (argv or sys.argv[1:])[0]
    authkey = bytes.fromhex(os.environ.pop("RMT_ZYGOTE_AUTHKEY"))
    # die with the owning process: a head/agent that exits without a clean
    # shutdown (SIGKILL, crashed test) must not leak a forever-accepting
    # zygote. PDEATHSIG is cleared on fork, so workers are unaffected.
    try:
        import ctypes
        import signal as _sig

        PR_SET_PDEATHSIG = 1
        ctypes.CDLL("libc.so.6", use_errno=True).prctl(
            PR_SET_PDEATHSIG, _sig.SIGTERM, 0, 0, 0)
        if os.getppid() == 1:
            return 0  # parent already gone before prctl landed
    except Exception:  # noqa: BLE001 — non-Linux: rely on clean shutdown
        pass
    serve(socket_path, authkey)
    return 0


if __name__ == "__main__":
    sys.exit(main())
