"""Node agent: the per-host daemon of the multi-host plane.

The raylet-process analog (the reference runs one raylet per node,
src/ray/raylet/main.cc, joined to the head over gRPC — node registration
src/ray/gcs/gcs_server/gcs_node_manager.h:36, object transfer
src/ray/object_manager/object_manager.h:114). Run on each additional host:

    python -m ray_memory_management_tpu.core.node_agent \
        --address HEAD_HOST:PORT --authkey HEX [--num-cpus N] [--num-tpus N]

Design: one authenticated TCP channel to the head carries EVERYTHING —
worker-connection tunneling, task dispatch, chunked object push/pull, and
liveness. The agent owns the host-local pieces a kernel boundary forces:
the shared-memory object store and the worker process pool. All ownership,
scheduling, and object-directory state stays at the head (centralized
ownership is this runtime's single-driver simplification; the tunnel keeps
every existing head-side code path — dispatch, nested worker requests,
actor lifecycles — working unchanged for remote workers).

Channel frames, head -> agent:
    start_worker {wid, dedicated, env}     spawn a worker process
    wsend       {wid, msg}                 deliver msg to worker wid
    lease_exec  {task_id, msg}             leaf task: agent picks the worker
    kill_worker {wid}                      terminate a worker process
    obj_push    {oid, size}                begin receiving an object
    obj_chunk   {oid, off, data}           one chunk of it
    obj_seal    {oid, req}                 seal; reply push_ack
    obj_pull    {oid, req}                 stream the object back
    obj_free    {oid}                      drop from the local store
    ping                                   liveness probe
    shutdown                               stop workers, close store, exit

agent -> head:
    register_node {...}                    hello (first frame)
    wmsg        {wid, msg}                 tunneled worker message
    wdeath      {wid}                      worker pipe EOF
    lease_spill {task_id}                  leaf pool saturated: head reroutes
    lease_dead  {task_id}                  leased task's worker died
    lease_cancel {task_id}                 job sweep: kill the pool worker
                                           running a dead job's leased task
    push_ack    {req, error}               object landed (or failed)
    pull_data   {req, off, data, eof, error}
    pong
"""

from __future__ import annotations

import argparse
import os
import socket
import subprocess
import sys
import threading
from collections import deque
from typing import Any, Dict, Optional

from ..config import Config
from ..utils import faults
from ..utils.retry import RetryPolicy
from . import codec as wire_codec
from .object_store import NodeObjectStore


def _reap_stale_agent_stores() -> None:
    """A SIGKILLed agent cannot unlink its shm store; reclaim segments whose
    owning pid (embedded in the name) is gone. Runs at agent start so a
    crash-looping host converges instead of filling /dev/shm."""
    from ..native import reap_stale_stores

    reap_stale_stores("rmtA_")


class NodeAgent:
    def __init__(self, head_host: str, head_port: int, authkey: bytes,
                 num_cpus: int, num_tpus: int = 0,
                 resources: Optional[Dict[str, float]] = None,
                 labels: Optional[Dict[str, str]] = None):
        from multiprocessing.connection import Client, Listener

        self.channel = Client((head_host, head_port), authkey=authkey)
        self._cluster_authkey = authkey
        self._channel_lock = threading.Lock()
        # this host's reachable IP on the route to the head, and the head's
        # IP as we see it — peers dial us at the former; obj_fetch frames
        # with host="" mean "fetch from the head" and resolve to the latter
        self._my_ip = "127.0.0.1"
        self._head_ip = head_host
        try:
            sock = socket.socket(fileno=os.dup(self.channel.fileno()))
            self._my_ip = sock.getsockname()[0]
            self._head_ip = sock.getpeername()[0]
            sock.close()
        except OSError:
            pass
        from ..config import WIRE_PROTOCOL_VERSION

        self._send({
            "type": "register_node",
            "proto": WIRE_PROTOCOL_VERSION,
            "num_cpus": num_cpus,
            "num_tpus": num_tpus,
            "resources": resources or {},
            "labels": labels or {},
            "hostname": socket.gethostname(),
            "pid": os.getpid(),
        })
        hello = self.channel.recv()
        if hello.get("type") != "registered":
            raise RuntimeError(f"head rejected registration: {hello}")
        self.node_id: bytes = hello["node_id"]
        self.config = Config(**hello["config"])
        self.inline_limit = self.config.max_direct_call_object_size
        # adopt the cluster's fault-injection plane (same seed/spec the
        # head exported) so a chaos run is replayable across every host
        faults.configure_from(self.config)
        # agent-process records (transfer serves, spill IO) join the log
        # plane stamped with this node's identity; they ship to the head
        # on the ping/pong piggyback like events and spans
        from ..utils import structlog as _structlog

        _structlog.configure(node_id=self.node_id.hex(), role="agent")
        _structlog.install_logging_capture()
        # continuous stack sampling of the agent process (transfer serves,
        # spill IO); samples ship on the ping/pong piggyback below
        from ..utils import profiler as _profiler

        _profiler.configure(node_id=self.node_id.hex(), role="agent")
        _profiler.start_sampler()

        _reap_stale_agent_stores()
        self.store_name = f"/rmtA_{os.getpid()}_{os.urandom(4).hex()}"
        self.store = NodeObjectStore(self.store_name, self.config,
                                     create=True)
        self._push_bufs: Dict[bytes, memoryview] = {}

        # peer-to-peer object plane: serve this store to other nodes and
        # pull directly from theirs — payload bytes never transit the head
        # (transfer.py; the reference's object-manager peer pulls,
        # object_manager.h:114)
        from concurrent.futures import ThreadPoolExecutor

        from .transfer import (
            ConnectionPool, TransferServer, fetch_object as _fetch_object,
        )

        self._fetch_object = _fetch_object
        self._shm_peers: Dict[str, Any] = {}  # same-host peer store maps
        self.transfer_server = TransferServer(
            self.store, authkey, self.config.object_manager_chunk_size,
            max_conns=self.config.transfer_max_conns,
            idle_timeout=self.config.transfer_idle_timeout_s,
            compress_min_bytes=self.config.transfer_compress_min_bytes)
        # authenticated peer connections reused across pulls
        self._xfer_conn_pool = ConnectionPool(
            max_idle_per_peer=self.config.transfer_pool_size)
        self._fetch_pool = ThreadPoolExecutor(
            max_workers=8, thread_name_prefix="agent-fetch")
        self._send({
            "type": "transfer_ready",
            "host": self._my_ip,
            "port": self.transfer_server.port,
            # same-host peers (other agents, the head) map this shm store
            # directly instead of pulling over TCP — the named segment IS
            # the shared-memory object plane on one host
            "store_name": self.store_name,
        })

        # permission-trusted worker socket, like the head's (0600 file;
        # no HMAC challenge — two round trips saved per worker connect)
        self._socket_path = f"/tmp/rmtA_{os.getpid()}_{os.urandom(4).hex()}.sock"
        self._listener = Listener(self._socket_path, family="AF_UNIX")
        os.chmod(self._socket_path, 0o600)
        self._workers: Dict[bytes, Any] = {}        # wid -> conn  # guarded-by: _lock
        self._worker_procs: Dict[bytes, Any] = {}   # wid -> Popen  # guarded-by: _lock
        self._pending_bootstrap: Dict[bytes, dict] = {}  # cold-spawn tokens  # guarded-by: _lock
        self._worker_send_locks: Dict[bytes, threading.Lock] = {}  # guarded-by: _lock
        # agent-local leaf scheduling (lease_exec): the head grants this
        # node lease credits in bulk; each lease_exec frame carries a
        # fully-built exec msg and THIS process picks the least-loaded
        # connected pool worker — the decentralized-control-plane half of
        # the two-level lease protocol (raylet_client.h:398). Dedicated
        # (actor / conda) workers never take leased tasks.
        self._lease_dedicated: set = set()          # wid  # guarded-by: _lock
        self._lease_inflight: Dict[bytes, int] = {}  # wid -> depth  # guarded-by: _lock
        self._lease_task_wid: Dict[bytes, bytes] = {}  # task -> wid  # guarded-by: _lock
        self._lease_known: Dict[bytes, set] = {}    # wid -> fn ids  # guarded-by: _lock
        # fn blobs ship once per NODE (head-side lease_known_fns); the
        # agent re-attaches from this cache per WORKER as needed
        self._lease_fn_blobs: Dict[bytes, bytes] = {}  # guarded-by: _lock
        # delta-compressed heartbeats: each pong carries a sequence
        # number and only the status keys (and held-row deltas, for
        # agents that own rows — see _dir_report) that changed since the
        # last pong we SENT; the head applies them in seq order and asks
        # for full state via the ping's resync flag when it detects a
        # gap. Committed only after a successful send so the delta base
        # is exactly the stream the head holds. Recv-loop-private: the
        # ping handler is the only reader and writer, so no lock guards
        # these.
        self._hb_seq = 0
        self._hb_stat_sent: Dict[str, Any] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        # The object plane runs on its OWN thread: a push/ensure into a
        # full store waits (bounded) for capacity, and that wait must never
        # starve liveness pings, task dispatch (wsend), or the obj_free
        # frames that drain capacity. FIFO per-frame ordering within the
        # plane (push -> chunk -> seal) is preserved by the single queue.
        self._obj_q: deque = deque()  # guarded-by: _obj_cond
        self._obj_q_bytes = 0  # payload bytes admitted (accounted at push)  # guarded-by: _obj_cond
        # cap on queued payload so a blocked store never buffers an entire
        # multi-GB transfer backlog in agent RAM. The recv loop must NEVER
        # park on this: while parked it stops reading ping and obj_free —
        # obj_free is exactly what frees store capacity so the plane can
        # drain, and a filled TCP buffer blocks head-side channel_send,
        # stalling the head's serial heartbeat loop for EVERY node. Instead
        # a push whose declared size would exceed the budget is nacked
        # (push_ack error) and its chunks discarded as they arrive; the
        # head-side push_object returns False and the caller retries or
        # routes elsewhere (the reference's PullManager bounds in-flight
        # bytes by admission the same way, pull_manager.h:47).
        self._obj_q_limit = max(64 << 20,
                                4 * self.config.object_manager_chunk_size)
        self._push_acct: Dict[bytes, int] = {}  # oid -> unaccounted bytes  # guarded-by: _obj_cond
        # push-lifecycle markers are mutated from BOTH the recv thread
        # (admission/nack) and the object-plane thread (full store,
        # seal): their mutex is _free_mu, which already serializes the
        # free-vs-push decisions they feed. Lock order: _obj_cond may
        # nest _free_mu inside it, never the reverse.
        self._dropped_pushes: Dict[bytes, bool] = {}  # oid -> nack pending  # guarded-by: _free_mu
        # pushes whose create hit a transiently-full store: _obj_seal acks
        # these "retryable" so the head backs off and re-pushes while its
        # source read ref keeps the object live (admission control, never
        # object loss — pull_manager.h:47 / create_request_queue.h:32)
        self._full_pushes: Dict[bytes, bool] = {}  # guarded-by: _free_mu
        self._obj_cond = threading.Condition()
        # frees that arrived while a push of the same object was still
        # queued/mid-flight: consumed by _obj_push/_obj_seal so the freed
        # object is not resurrected by the late-landing push. _free_mu makes
        # the free's contains-or-mark and the seal's mark-or-seal decisions
        # atomic against each other (recv thread vs object-plane thread);
        # dict (insertion-ordered) so overflow evicts the STALEST marker
        self._freed_while_pushing: Dict[bytes, bool] = {}  # guarded-by: _free_mu
        self._free_mu = threading.Lock()
        # warm the fork server while the node is idle: the first actor
        # burst should never pay the zygote's preload
        if self.config.worker_fork_server:
            from . import zygote as _zygote

            threading.Thread(target=_zygote.get_global, daemon=True,
                             name="agent-zygote-warm").start()
        threading.Thread(target=self._obj_plane_loop, daemon=True,
                         name="agent-objplane").start()
        threading.Thread(target=self._accept_loop, daemon=True,
                         name="agent-accept").start()
        threading.Thread(target=self._reap_loop, daemon=True,
                         name="agent-reaper").start()

    # ---------------------------------------------------------------- channel
    def _send(self, msg: dict) -> None:
        with self._channel_lock:
            self.channel.send(msg)

    # ---------------------------------------------------------------- workers
    def _accept_loop(self) -> None:
        """Local workers dial in exactly as they would dial a head-local
        runtime (worker_main.py is unchanged); their frames are tunneled."""
        while not self._stop.is_set():
            try:
                conn = self._listener.accept()
            except (OSError, EOFError):
                if self._stop.is_set():
                    return
                continue
            try:
                msg = conn.recv()
            except (EOFError, OSError):
                conn.close()
                continue
            # a bootstrapped worker can reply so fast that its sender
            # coalesces ready + actor_ready into one batch frame: forward
            # the trailing replies as separate wmsg frames after the ready
            trailing = []
            if msg.get("type") == "batch" and msg["msgs"]:
                trailing = msg["msgs"][1:]
                msg = msg["msgs"][0]
            if msg.get("type") != "ready":
                conn.close()
                continue
            wid = msg["worker_id"]
            with self._lock:
                self._workers[wid] = conn
                self._worker_send_locks[wid] = threading.Lock()
                boot = self._pending_bootstrap.pop(wid, None)
            if boot is not None:
                # cold-spawned worker with a held startup token: deliver
                # it now, before the head even learns the worker is up
                try:
                    conn.send(boot)
                except (OSError, BrokenPipeError):
                    pass  # reader thread will report wdeath
            self._send({"type": "wmsg", "wid": wid, "msg": msg})
            for m in trailing:
                self._send({"type": "wmsg", "wid": wid, "msg": m})
            threading.Thread(target=self._worker_reader, args=(wid, conn),
                             daemon=True, name="agent-wreader").start()

    def _worker_reader(self, wid: bytes, conn) -> None:
        while not self._stop.is_set():
            try:
                msg = conn.recv()
            except (EOFError, OSError):
                break
            self._lease_note_reply(wid, msg)
            try:
                self._send({"type": "wmsg", "wid": wid, "msg": msg})
            except (OSError, BrokenPipeError):
                return  # channel gone: the process is shutting down
        with self._lock:
            self._workers.pop(wid, None)
            self._worker_send_locks.pop(wid, None)
            # leased tasks bound to this worker die with it: the head
            # retries them (lease_dead), exactly like its own
            # worker-death inflight sweep for queue-dispatched tasks
            dead_leases = [tid for tid, w in self._lease_task_wid.items()
                           if w == wid]
            for tid in dead_leases:
                self._lease_task_wid.pop(tid, None)
            self._lease_inflight.pop(wid, None)
            self._lease_known.pop(wid, None)
            self._lease_dedicated.discard(wid)
        for tid in dead_leases:
            try:
                self._send({"type": "lease_dead", "task_id": tid})
            except (OSError, BrokenPipeError):
                break
        try:
            self._send({"type": "wdeath", "wid": wid})
        except (OSError, BrokenPipeError):
            pass

    def _start_worker(self, msg: dict) -> None:
        from .node_manager import build_worker_env, spawn_worker_process

        wid_hex = msg["wid_hex"]
        wid = bytes.fromhex(wid_hex)
        if msg.get("dedicated") or msg.get("conda") is not None:
            # actor / conda workers never take leased leaf tasks
            with self._lock:
                self._lease_dedicated.add(wid)
        env = build_worker_env(wid_hex, self.node_id.hex(), self.store_name,
                               self._socket_path, "",
                               self.config)
        env.update(msg.get("env") or {})
        bootstrap = msg.get("bootstrap")
        conda_spec = msg.get("conda")

        def queue_bootstrap():
            # cold spawn: hold the token and deliver it when the worker
            # dials in (the _accept_loop checks this map). Runs before
            # the process exists, so the dial-in cannot have happened.
            with self._lock:
                self._pending_bootstrap[wid] = bootstrap

        def spawn(python_exe=None):
            proc = spawn_worker_process(env, self.config, bootstrap,
                                        queue_bootstrap,
                                        python_exe=python_exe)
            with self._lock:
                self._worker_procs[wid] = proc

        if conda_spec is None:
            spawn()
            return

        def resolve_and_spawn():
            # conda resolution/creation can take minutes: never on the
            # recv loop. On failure the head must LEARN the worker died —
            # no process ever exists, so the reap loop can't see it: send
            # the wdeath explicitly (the event says why).
            try:
                from .. import runtime_env as re_mod

                spawn(python_exe=re_mod.conda_python(conda_spec))
            except Exception as e:  # noqa: BLE001
                from ..utils import events

                events.emit(
                    "CONDA_ENV_FAILED",
                    f"conda env {conda_spec!r} unavailable on "
                    f"{self.node_id.hex()[:8]}: {e!r}",
                    severity=events.ERROR, source="node_agent")
                try:
                    self._send({"type": "wdeath", "wid": wid})
                except (OSError, BrokenPipeError):
                    pass

        threading.Thread(target=resolve_and_spawn, daemon=True,
                         name=f"conda-spawn-{wid_hex[:6]}").start()

    # ------------------------------------------------------------ leaf leases
    def _lease_exec(self, msg: dict) -> None:
        """Place one leased leaf task on a local pool worker — the
        agent-local scheduling decision. Saturation (every eligible
        worker at the pipelining depth) spills the task back to the head
        router (lease_spill), which reroutes it through the full
        scheduling path; a vanished worker is reported as lease_dead so
        the head can retry. Runs on the channel recv loop and never
        parks: the decision is a dict scan under _lock."""
        task_id = msg["task_id"]
        inner = msg["msg"]
        fn_id = inner.get("fn_id")
        blob = inner.pop("fn_blob", None)
        depth = max(1, self.config.max_tasks_in_flight_per_worker)
        attach = False
        with self._lock:
            if blob is not None and fn_id is not None:
                self._lease_fn_blobs[fn_id] = blob
            best = None
            best_n = depth
            for wid in self._workers:
                if wid in self._lease_dedicated:
                    continue
                n = self._lease_inflight.get(wid, 0)
                if n < best_n:
                    best, best_n = wid, n
                    if n == 0:
                        break
            if best is not None:
                conn = self._workers.get(best)
                lock = self._worker_send_locks.get(best)
                known = self._lease_known.setdefault(best, set())
                if fn_id is not None and fn_id not in known:
                    blob = self._lease_fn_blobs.get(fn_id)
                    if blob is None:
                        best = None  # blob never arrived: cannot run here
                    else:
                        known.add(fn_id)
                        attach = True
                if best is not None:
                    self._lease_inflight[best] = best_n + 1
                    self._lease_task_wid[task_id] = best
        if best is None:
            try:
                self._send({"type": "lease_spill", "task_id": task_id})
            except (OSError, BrokenPipeError):
                pass
            return
        if attach:
            inner = dict(inner)
            inner["fn_blob"] = blob
        try:
            with lock:
                conn.send(inner)
        except (OSError, BrokenPipeError, ValueError):
            # the pick raced the worker's death: unbind and tell the head
            # (its retry path reruns the task elsewhere). The reader's EOF
            # sweep may race this — finish_leaf at the head is idempotent.
            with self._lock:
                self._lease_task_wid.pop(task_id, None)
                n = self._lease_inflight.get(best, 0)
                if n > 0:
                    self._lease_inflight[best] = n - 1
            try:
                self._send({"type": "lease_dead", "task_id": task_id})
            except (OSError, BrokenPipeError):
                pass

    def _lease_note_reply(self, wid: bytes, msg: dict) -> None:
        """Settle lease depth accounting from a tunneled worker reply
        (done frames, possibly inside a batch)."""
        t = msg.get("type")
        if t == "batch":
            for m in msg["msgs"]:
                self._lease_note_reply(wid, m)
            return
        if t == "done":
            with self._lock:
                if self._lease_task_wid.pop(msg.get("task_id"),
                                            None) is not None:
                    n = self._lease_inflight.get(wid, 0)
                    if n > 0:
                        self._lease_inflight[wid] = n - 1

    def _reap_loop(self) -> None:
        """Detect workers that die WITHOUT ever dialing in (import error,
        OOM at startup): no pipe means no EOF, so without this the head
        would count them as starting forever. Also reaps the zombies."""
        import time as _time

        while not self._stop.is_set():
            _time.sleep(1.0)
            try:
                self.store.sweep_pins()  # expire obj_ensure residency pins
            except Exception:
                pass
            try:
                # abort creates left unsealed past the deadline (a peer
                # that died mid-push leaks the reservation otherwise)
                self.store.sweep_unsealed()
            except Exception:
                pass
            with self._lock:
                dead = [(wid, p) for wid, p in self._worker_procs.items()
                        if p.poll() is not None]
                for wid, _ in dead:
                    self._worker_procs.pop(wid, None)
                    # a worker that died before dialing in never collected
                    # its startup token; drop it or it leaks (cls blobs
                    # are multi-KB and actor churn is unbounded)
                    self._pending_bootstrap.pop(wid, None)
                connected = set(self._workers)
            for wid, _ in dead:
                if wid not in connected:
                    try:
                        self._send({"type": "wdeath", "wid": wid})
                    except (OSError, BrokenPipeError):
                        return

    # ----------------------------------------------------------- object plane
    def _obj_push(self, msg: dict) -> None:
        oid = msg["oid"]
        if oid in self._freed_while_pushing:
            return  # freed before this push landed: don't resurrect it
        if oid in self._push_bufs:
            return  # an identical push is mid-flight; let it finish
        from ..exceptions import ObjectStoreFullError

        try:
            # SHORT create budget: a pressured push nacks retryable fast
            # (the head backs off and retries, holding its read ref)
            # instead of parking the shared object-plane thread for the
            # whole full-store wait
            self._push_bufs[oid] = self.store.create(oid, msg["size"],
                                                     timeout_s=1.0)
        except ValueError:
            pass  # already sealed in the store: ignore this push's chunks
        except ObjectStoreFullError:
            # nack NOW as well (the push frame carries req): the head's
            # chunk loop aborts on the early ack instead of streaming the
            # whole payload per retry; mark the push dropped so the recv
            # thread discards the chunks already in flight. The seal may
            # already be queued on this plane — _full_pushes answers it
            # retryable too (the head ignores the duplicate ack: its
            # request state was popped by the first one).
            with self._free_mu:
                while len(self._full_pushes) > 4096:
                    self._full_pushes.pop(next(iter(self._full_pushes)))
                self._full_pushes[oid] = True  # _obj_seal acks retryable
                self._dropped_pushes[oid] = True
            try:
                self._send({
                    "type": "push_ack", "req": msg["req"],
                    "error": "receiver store full (retryable)"})
            except (OSError, BrokenPipeError):
                pass
        except Exception:  # noqa: BLE001 — store full even after waiting:
            pass  # drop the chunks; _obj_seal acks the push with an error

    def _obj_chunk(self, msg: dict) -> None:
        buf = self._push_bufs.get(msg["oid"])
        if buf is not None:
            off = msg["off"]
            data = msg["data"]
            buf[off:off + len(data)] = data

    def _obj_seal(self, msg: dict) -> None:
        oid = msg["oid"]
        err = None
        # the mark-or-seal decision is atomic against the recv thread's
        # contains-or-mark in obj_free: without the mutex a free landing
        # between our marker check and store.seal() would resurrect the
        # freed object with no future delete ever coming. The freed path
        # also runs UNDER the mutex and deletes the unsealed create
        # directly (delete() aborts unsealed entries, shmstore.cpp:379):
        # seal-then-delete would briefly publish the freed object as live,
        # and a concurrent reader ref in that window — or a failed delete —
        # would resurrect it with no future delete ever coming.
        with self._free_mu:
            freed = self._freed_while_pushing.pop(oid, None) is not None
            if freed:
                self._full_pushes.pop(oid, None)
                buf = self._push_bufs.pop(oid, None)
                if buf is not None:
                    del buf
                    try:
                        self.store.delete(oid)
                    except Exception:
                        pass
                err = "object freed during push"
            elif oid in self._push_bufs:
                del self._push_bufs[oid]
                self._full_pushes.pop(oid, None)
                try:
                    self.store.seal(oid)
                except Exception as e:  # noqa: BLE001
                    err = repr(e)
            elif self._full_pushes.pop(oid, None) is not None \
                    and not self.store.contains(oid):
                # transiently-full store refused the create: tell the head
                # to back off and retry (its read ref keeps the source copy
                # live) — pressure is slowness, never loss
                err = "receiver store full (retryable)"
            elif not self.store.contains(oid):
                # this push's create was refused and nobody else sealed it:
                # acking success would poison the head's object directory
                err = "push raced an incomplete object"
        self._send({"type": "push_ack", "req": msg["req"], "error": err})

    def _obj_pull(self, msg: dict) -> None:
        oid, req = msg["oid"], msg["req"]
        try:
            # read() serves spilled objects straight from the spill file —
            # a pull must never force an allocation in a full store
            view = self.store.read(oid)
        except Exception as e:  # noqa: BLE001
            view = None
            err = repr(e)
        else:
            err = "object not in store"
        if view is None:
            self._send({"type": "pull_data", "req": req, "off": 0,
                        "data": b"", "eof": True, "error": err})
            return
        try:
            chunk = self.config.object_manager_chunk_size
            n = len(view) if isinstance(view, bytes) else view.nbytes
            if n == 0:
                self._send({"type": "pull_data", "req": req, "off": 0,
                            "data": b"", "eof": True, "error": None})
                return
            for off in range(0, n, chunk):
                end = min(off + chunk, n)
                self._send({
                    "type": "pull_data", "req": req, "off": off,
                    "data": bytes(view[off:end]), "eof": end >= n,
                    "error": None,
                })
        finally:
            if isinstance(view, memoryview):
                self.store.release(oid)

    def _obj_fetch(self, msg: dict) -> None:
        """Pull an object DIRECTLY from a peer's transfer server into this
        store (receiver-driven transfer; host "" = the head). When the
        head marked the source as same-host ("src_store"), map the
        source's shm segment and memcpy — no TCP, no chunk protocol —
        falling back to the server pull if the object isn't shm-resident
        there (spilled) or the mapping fails. Runs on the fetch pool so a
        slow source never blocks the object plane or the channel loop."""
        host = msg["host"] or self._head_ip
        port, oid, req = msg["port"], msg["oid"], msg["req"]
        src_store = msg.get("src_store")
        trace = msg.get("trace")
        # alternate live holders (head-resolved) for mid-pull failover;
        # host "" means the head itself, as with the primary source
        alts = [(h or self._head_ip, p) for h, p in msg.get("alts") or ()]

        def run():
            err = None
            if src_store:
                err = self._fetch_same_host(src_store, oid)
            if src_store is None or err is not None:
                try:
                    err = self._fetch_object(
                        host, port, self._cluster_authkey, oid, self.store,
                        self.config.object_manager_chunk_size,
                        pool=self._xfer_conn_pool,
                        stripe_threshold=self.config.transfer_stripe_threshold,
                        stripe_count=self.config.transfer_stripe_count,
                        alt_sources=(lambda: alts) if alts else None,
                        retry=RetryPolicy(
                            max_attempts=self.config.transfer_retry_attempts,
                            base_backoff_s=self.config.transfer_retry_backoff_s,
                            plane="transfer"),
                        verify_checksum=self.config.transfer_verify_checksum,
                        stripe_deadline=self.config.transfer_stripe_deadline_s,
                        trace=trace,
                        codecs=wire_codec.client_codecs(self.config))
                except Exception as e:  # noqa: BLE001
                    err = repr(e)
            try:
                self._send({"type": "fetch_ack", "req": req, "error": err})
            except (OSError, BrokenPipeError):
                pass

        self._fetch_pool.submit(run)

    def _fetch_same_host(self, store_name: str, oid: bytes) -> Optional[str]:
        """shm-to-shm copy from a same-host peer's segment. Returns None
        on success, else a reason string (caller falls back to the TCP
        pull — e.g. the object is spilled inside the source process,
        invisible through its segment)."""
        try:
            cli = self._shm_peers.get(store_name)
            if cli is None:
                from .object_store import StoreClient

                cli = StoreClient(store_name)
                self._shm_peers[store_name] = cli
            view = cli.get(oid)  # shared-segment reader ref (plasma-style)
            if view is None:
                return "not shm-resident at source"
            from .transfer import create_or_wait

            try:
                buf, race_err = create_or_wait(self.store, oid, view.nbytes)
                if buf is None:
                    return race_err  # None: racing copy became readable
                try:
                    try:
                        buf[:] = view
                    finally:
                        del buf  # drop the mapping before seal/abort
                    self.store.seal(oid)
                except BaseException:
                    # abort the unsealed create so retries can re-allocate
                    try:
                        self.store.delete(oid)
                    except Exception:  # noqa: BLE001
                        pass
                    raise
                return None
            finally:
                cli.release(oid)
        except Exception as e:  # noqa: BLE001
            return repr(e)

    def _obj_spill(self, msg: dict) -> None:
        """Head-requested spill: a worker's direct shm put needs room (the
        raylet-spills-for-plasma-creates path; policy lives in
        NodeObjectStore.make_room, shared with the head's local stores)."""
        try:
            self.store.make_room(int(msg["bytes"]))
            err = None
        except Exception as e:  # noqa: BLE001
            err = repr(e)
        self._send({"type": "spill_ack", "req": msg["req"], "error": err})

    def _obj_ensure(self, msg: dict) -> None:
        """Restore the object(s) into shm (if spilled) and pin briefly so
        the requesting worker's direct shm read cannot race a re-spill
        (head-side _serve_get answers "local" only after this ack). Accepts
        a batch ("oids") — one frame + one ack for a whole get request."""
        oids = msg.get("oids")
        if oids is None:
            oids = [msg["oid"]]
        failed = []
        for oid in oids:
            try:
                if not self.store.ensure_resident(oid):
                    failed.append(oid)
            except Exception:  # noqa: BLE001 — full store etc: per-oid fail
                failed.append(oid)
        self._send({"type": "ensure_ack", "req": msg["req"], "error": None,
                    "failed": failed})

    def _obj_plane_loop(self) -> None:
        handlers = {
            "obj_push": self._obj_push,
            "obj_chunk": self._obj_chunk,
            "obj_seal": self._obj_seal,
            "obj_pull": self._obj_pull,
            "obj_ensure": self._obj_ensure,
            "obj_spill": self._obj_spill,
        }
        while not self._stop.is_set():
            with self._obj_cond:
                while not self._obj_q:
                    self._obj_cond.wait(timeout=1.0)
                    if self._stop.is_set():
                        return
                msg = self._obj_q.popleft()
                if msg["type"] == "obj_chunk":
                    rem = self._push_acct.get(msg["oid"])
                    if rem is not None:
                        dec = min(len(msg["data"]), rem)
                        self._push_acct[msg["oid"]] = rem - dec
                        self._obj_q_bytes -= dec
                elif msg["type"] == "obj_seal":
                    # release whatever the chunks didn't cover (a push that
                    # errored mid-stream must not leak admitted bytes)
                    self._obj_q_bytes -= self._push_acct.pop(msg["oid"], 0)
            try:
                handlers[msg["type"]](msg)
            except Exception:  # noqa: BLE001 — one bad frame must not
                pass  # take down the whole object plane

    # ------------------------------------------------------------------- main
    def run(self) -> None:
        try:
            self._run_loop()
        finally:
            self._shutdown()

    def _hb_status(self) -> Dict[str, Any]:
        """O(1) agent status snapshot for the pong delta stream (store
        bytes, lease depth, worker count). The head mirrors the merged
        dict per node, so steady-state pongs usually carry NO status at
        all — only the keys that moved since the last acked pong."""
        used = cap = 0
        try:
            u = self.store.usage()
            used, cap = int(u[0]), int(u[1])
        except Exception:  # noqa: BLE001 — status must never kill a pong
            pass
        with self._lock:
            depth = sum(self._lease_inflight.values())
            workers = len(self._workers)
        return {"store_used": used, "store_cap": cap,
                "spilled": self.store.spilled_count(),
                "lease_depth": depth, "workers": workers}

    def _dir_report(self, full: bool):
        """Held-row delta report ``(dadd, ddel)`` for agents that OWN
        directory rows, or None. The real agent returns None: its rows
        are maintained authoritatively by the head's done/free paths,
        so re-asserting them every pong would burn exactly the ingress
        the delta plane exists to avoid. The simulated agent plane
        (utils/sim_agent.py) overrides this to drive pod-scale row
        churn through the same wire frames."""
        return None

    def _run_loop(self) -> None:
        while True:
            try:
                msg = self.channel.recv()
            except (EOFError, OSError):
                return  # head gone: shut down this node
            t = msg["type"]
            if t == "wsend":
                wid = msg["wid"]
                with self._lock:
                    conn = self._workers.get(wid)
                    lock = self._worker_send_locks.get(wid)
                    if msg["msg"].get("type") == "create_actor":
                        # a pooled worker converted into an actor worker
                        # (dedicate_to_actor): stop leasing onto it
                        self._lease_dedicated.add(wid)
                if conn is not None and lock is not None:
                    try:
                        with lock:
                            conn.send(msg["msg"])
                    except (OSError, BrokenPipeError, ValueError):
                        pass  # reader thread will report wdeath
            elif t == "lease_exec":
                self._lease_exec(msg)
            elif t == "lease_batch":
                # per-node coalesced leaf grants (head-side flush_leases):
                # one frame carries a scheduling pass's worth of leases;
                # each entry takes the same worker-pick path as a lone
                # lease_exec, spilling/failing individually
                for sub in msg["tasks"]:
                    self._lease_exec(sub)
            elif t == "start_worker":
                self._start_worker(msg)
            elif t == "kill_worker":
                proc = self._worker_procs.get(msg["wid"])
                if proc is not None:
                    try:
                        proc.terminate()
                    except Exception:
                        pass
            elif t == "lease_cancel":
                # job sweep: a leased task of a dead job may be RUNNING
                # on a pool worker only this agent can name — kill that
                # worker; wdeath/lease_dead settle the accounting and
                # the head fails the (cancelled) retry
                with self._lock:
                    wid = self._lease_task_wid.get(msg["task_id"])
                    proc = (self._worker_procs.get(wid)
                            if wid is not None else None)
                if proc is not None:
                    try:
                        proc.terminate()
                    except Exception:
                        pass
            elif t == "obj_fetch":
                self._obj_fetch(msg)  # non-blocking: pool submit
            elif t == "obj_push":
                # admission control, never parking: admit the push if its
                # declared size fits the payload budget, else nack it and
                # discard its chunks as they stream past (the recv loop
                # must keep reading ping/obj_free — see _obj_q_limit)
                oid = msg["oid"]
                with self._obj_cond:
                    dup = oid in self._push_acct
                    # an idle plane always admits, whatever the size —
                    # otherwise a single object larger than the budget
                    # could never transfer at all; with bytes already
                    # queued the backlog is bounded at limit + one object
                    over = (not dup and self._obj_q_bytes > 0
                            and self._obj_q_bytes + msg["size"]
                            > self._obj_q_limit)
                    if not over:
                        # a stale dropped-marker from an earlier nacked
                        # attempt must not swallow this admitted push's
                        # chunks (and leak its admitted bytes forever)
                        with self._free_mu:
                            self._dropped_pushes.pop(oid, None)
                        if not dup:
                            self._push_acct[oid] = msg["size"]
                            self._obj_q_bytes += msg["size"]
                        self._obj_q.append(msg)
                        self._obj_cond.notify()
                if over:
                    with self._free_mu:
                        while len(self._dropped_pushes) > 4096:
                            self._dropped_pushes.pop(
                                next(iter(self._dropped_pushes)))
                        self._dropped_pushes[oid] = True
                    # nack NOW (the push frame carries req): the head's
                    # chunk loop aborts on the early ack instead of
                    # streaming the whole payload just to be discarded
                    try:
                        self._send({
                            "type": "push_ack", "req": msg["req"],
                            "error": "push dropped: object plane over "
                                     "budget (retryable)"})
                    except (OSError, BrokenPipeError):
                        pass
            elif t == "obj_chunk" and msg["oid"] in self._dropped_pushes:
                pass  # chunk of a nacked push: discard without queueing
            elif t == "obj_seal" and msg["oid"] in self._dropped_pushes:
                # the nack already went out with the obj_push's req; the
                # seal of a dropped push just clears the marker — and
                # releases the payload-budget bytes if this push was
                # ADMITTED before being dropped (the full-store early
                # nack drops mid-stream: without this the admitted bytes
                # leak and the plane budget shrinks permanently)
                with self._free_mu:
                    self._dropped_pushes.pop(msg["oid"], None)
                with self._obj_cond:
                    self._obj_q_bytes -= self._push_acct.pop(msg["oid"], 0)
            elif t in ("obj_chunk", "obj_seal", "obj_pull",
                       "obj_ensure", "obj_spill"):
                with self._obj_cond:
                    self._obj_q.append(msg)
                    self._obj_cond.notify()
            elif t == "obj_free":
                oid = msg["oid"]
                try:
                    with self._free_mu:
                        if self.store.contains(oid):
                            self.store.delete(oid)
                        else:
                            # a push of this object may still be queued on
                            # the object plane; mark it so the late-landing
                            # push does not resurrect a freed object
                            while len(self._freed_while_pushing) > 4096:
                                self._freed_while_pushing.pop(
                                    next(iter(self._freed_while_pushing)))
                            self._freed_while_pushing[oid] = True
                except Exception:
                    pass
            elif t == "ping":
                from ..utils import events as _events
                from ..utils import profiler as _profiler
                from ..utils import structlog as _structlog
                from ..utils import timeline as _timeline

                evs = _events.drain_events(node_id=self.node_id.hex())
                # timeline spans recorded in THIS process (transfer
                # serves, spill IO) ship on the keepalive reply — the
                # agent analog of the worker's profile piggyback; without
                # it agent-side spans never reach the head's dump
                prof = _timeline.drain_events_if_due(min_batch=1)
                lgs = _structlog.drain_records()
                smp = _profiler.drain_samples()
                pong: Dict[str, Any] = {"type": "pong"}
                if evs:
                    pong["events"] = evs
                if prof:
                    pong["profile"] = prof
                if lgs:
                    pong["logs"] = lgs
                if smp:
                    pong["samples"] = smp
                # delta-compressed control state: ship only the status
                # keys that changed since the last pong we sent. The
                # pings are pipelined — the head's ack naturally lags a
                # round trip behind our committed seq, so a stale ack is
                # NOT a desync signal (treating it as one degenerates to
                # full pongs under load). The head detects real gaps
                # itself (seq != hb_seq+1) and raises the explicit
                # resync flag, which is the only full-state trigger.
                stat = self._hb_status()
                seq = self._hb_seq + 1
                pong["seq"] = seq
                full = bool(msg.get("resync"))
                if full:
                    pong["stat"] = stat
                    pong["dfull"] = True
                else:
                    delta = {k: v for k, v in stat.items()
                             if self._hb_stat_sent.get(k) != v}
                    if delta:
                        pong["stat"] = delta
                rep = self._dir_report(full)
                if rep is not None:
                    dadd, ddel = rep
                    if dadd or full:
                        pong["dadd"] = dadd
                    if ddel:
                        pong["ddel"] = ddel
                try:
                    self._send(pong)
                except (OSError, BrokenPipeError):
                    if evs:
                        _events.ingest(evs)  # retry on next ping
                    if prof:
                        _timeline.ingest_events(prof)
                    if lgs:
                        _structlog.reingest(lgs)
                    if smp:
                        _profiler.reingest(smp)
                    return
                # commit AFTER the successful send: a failed send means
                # the head never saw seq, its next ack still names the
                # old epoch, and the delta base stays exact
                self._hb_seq = seq
                self._hb_stat_sent = stat
            elif t == "shutdown":
                return

    def _shutdown(self) -> None:
        self._stop.set()
        try:
            self.transfer_server.close()
        except Exception:
            pass
        try:
            self._xfer_conn_pool.close()
        except Exception:
            pass
        self._fetch_pool.shutdown(wait=False)
        for proc in list(self._worker_procs.values()):
            try:
                proc.terminate()
            except Exception:
                pass
        from . import zygote as _zygote

        _zygote.shutdown_global()
        try:
            self._listener.close()
        except Exception:
            pass
        try:
            os.unlink(self._socket_path)
        except OSError:
            pass
        self.store.close(unlink=True)
        try:
            self.channel.close()
        except Exception:
            pass


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description="rmt node agent")
    p.add_argument("--address", required=True,
                   help="head node listener, HOST:PORT")
    p.add_argument("--authkey", required=True, help="hex cluster authkey")
    p.add_argument("--num-cpus", type=int, default=4)
    p.add_argument("--num-tpus", type=int, default=0)
    args = p.parse_args(argv)
    host, port = args.address.rsplit(":", 1)
    agent = NodeAgent(host, int(port), bytes.fromhex(args.authkey),
                      num_cpus=args.num_cpus, num_tpus=args.num_tpus)
    agent.run()
    return 0


if __name__ == "__main__":
    sys.exit(main())
