"""External (spill) storage tiers below the shared-memory store.

Mirrors python/ray/_private/external_storage.py: an ``ExternalStorage`` ABC
(reference :72) with a filesystem implementation (:243). Spill files carry the
serialized envelope verbatim, so restore is a straight copy back into the
store. Cloud storage (GCS/S3) plugs in by subclassing ``ExternalStorage`` —
the reference uses smart_open for this (:204); here a URI-prefix registry
selects the implementation.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Tuple


class ExternalStorage:
    def spill(self, object_id: bytes, data: memoryview) -> str:
        """Persist and return an opaque URL for restore."""
        raise NotImplementedError

    def restore(self, object_id: bytes, url: str) -> bytes:
        raise NotImplementedError

    def delete(self, url: str) -> None:
        raise NotImplementedError


class FileSystemStorage(ExternalStorage):
    """One file per spilled object under ``directory`` (reference :243; the
    reference also packs small objects into fused files — elided here because
    min_spilling_size batching already amortizes file overhead)."""

    def __init__(self, directory: str):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)

    def spill(self, object_id: bytes, data: memoryview) -> str:
        path = os.path.join(self.directory, object_id.hex())
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, path)
        return path

    def restore(self, object_id: bytes, url: str) -> bytes:
        with open(url, "rb") as f:
            return f.read()

    def delete(self, url: str) -> None:
        try:
            os.remove(url)
        except FileNotFoundError:
            pass


def storage_for_uri(uri: str) -> ExternalStorage:
    if uri.startswith("file://"):
        return FileSystemStorage(uri[len("file://"):])
    if "://" not in uri:
        return FileSystemStorage(uri)
    raise ValueError(f"unsupported spill storage uri: {uri}")
