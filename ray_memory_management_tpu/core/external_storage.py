"""External (spill) storage tiers below the shared-memory store.

Mirrors python/ray/_private/external_storage.py: an ``ExternalStorage`` ABC
(reference :72) with a filesystem implementation (:243). Spill files carry the
serialized envelope verbatim, so restore is a straight copy back into the
store. Cloud storage (GCS/S3) plugs in by subclassing ``ExternalStorage`` —
the reference uses smart_open for this (:204); here a URI-prefix registry
selects the implementation.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Tuple


class ExternalStorage:
    def spill(self, object_id: bytes, data: memoryview) -> str:
        """Persist and return an opaque URL for restore."""
        raise NotImplementedError

    def restore(self, object_id: bytes, url: str) -> bytes:
        raise NotImplementedError

    def delete(self, url: str) -> None:
        raise NotImplementedError

    # -- named-blob surface (checkpoint/artifact IO) --------------------------
    # The spill surface above is keyed by opaque object ids; checkpoints
    # need NAMED keys under a caller-chosen prefix (the reference reuses
    # smart_open for both — here the same backend object serves both
    # surfaces so s3://gs:// IO code lives in exactly one place).
    def put_blob(self, url: str, data: bytes) -> None:
        raise NotImplementedError(
            f"{type(self).__name__} does not support named blobs")

    def get_blob(self, url: str) -> bytes:
        raise NotImplementedError(
            f"{type(self).__name__} does not support named blobs")

    def list_blobs(self, url_prefix: str) -> List[str]:
        """Full URLs of blobs under the prefix (recursive)."""
        raise NotImplementedError(
            f"{type(self).__name__} does not support named blobs")

    def delete_prefix(self, url_prefix: str) -> None:
        for url in self.list_blobs(url_prefix):
            try:
                self.delete(url)
            except Exception:  # noqa: BLE001 - best-effort GC
                pass

    def probe(self) -> bool:
        """Write-and-delete a tiny sentinel object; True when the backend
        is usable. The store's spill-degraded mode calls this to decide
        when to resume spilling after persistent IO failure (a flaky
        volume that recovered, a bucket whose credentials were fixed)."""
        try:
            url = self.spill(b"\x00" * 8 + b"rmtprobe", memoryview(b"ok"))
            self.delete(url)
            return True
        except Exception:  # noqa: BLE001
            return False


class FileSystemStorage(ExternalStorage):
    """One file per spilled object under ``directory`` (reference :243; the
    reference also packs small objects into fused files — elided here because
    min_spilling_size batching already amortizes file overhead)."""

    def __init__(self, directory: str):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)

    def spill(self, object_id: bytes, data: memoryview) -> str:
        path = os.path.join(self.directory, object_id.hex())
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, path)
        return path

    def restore(self, object_id: bytes, url: str) -> bytes:
        with open(url, "rb") as f:
            return f.read()

    def delete(self, url: str) -> None:
        try:
            os.remove(url)
        except FileNotFoundError:
            pass

    @staticmethod
    def _path_of(url: str) -> str:
        return url[len("file://"):] if url.startswith("file://") else url

    def put_blob(self, url: str, data: bytes) -> None:
        path = self._path_of(url)
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, path)

    def get_blob(self, url: str) -> bytes:
        with open(self._path_of(url), "rb") as f:
            return f.read()

    def list_blobs(self, url_prefix: str) -> List[str]:
        root = self._path_of(url_prefix)
        out: List[str] = []
        for dirpath, _dirs, files in os.walk(root):
            for name in files:
                out.append(os.path.join(dirpath, name))
        return sorted(out)


class InMemoryStorage(ExternalStorage):
    """Blob/spill storage backed by a dict — the test double for cloud
    tiers: register it under any scheme with ``register_storage_scheme``
    and the full Checkpoint.to_uri/from_uri path runs without an SDK or a
    network. All instances constructed for the same uri share one bucket
    dict, matching real object-store semantics (two clients, one
    bucket)."""

    _buckets: Dict[str, Dict[str, bytes]] = {}

    def __init__(self, uri: str = "mem://test"):
        self.uri = uri.rstrip("/")
        root = self.uri.split("://", 1)[-1].split("/", 1)[0]
        self._blobs = self._buckets.setdefault(root, {})

    def spill(self, object_id: bytes, data: memoryview) -> str:
        url = f"{self.uri}/{object_id.hex()}"
        self._blobs[url] = bytes(data)
        return url

    def restore(self, object_id: bytes, url: str) -> bytes:
        return self._blobs[url]

    def delete(self, url: str) -> None:
        self._blobs.pop(url, None)

    def put_blob(self, url: str, data: bytes) -> None:
        self._blobs[url] = bytes(data)

    def get_blob(self, url: str) -> bytes:
        return self._blobs[url]

    def list_blobs(self, url_prefix: str) -> List[str]:
        pfx = url_prefix.rstrip("/") + "/"
        return sorted(u for u in self._blobs if u.startswith(pfx))


def resolve_cloud_credentials(config=None) -> Dict[str, Optional[str]]:
    """Per-field credential resolution for the cloud tiers, in order:

      1. the explicit Config flag (``cloud_storage_*``) — a cluster-level
         override that wins over whatever the process environment says;
      2. the SDK's conventional environment variable;
      3. ``None`` — the SDK's own default chain (instance metadata,
         ``~/.aws``, application-default credentials) takes over.

    Returns every field, resolved-or-None, so callers can pass only what
    resolved and never mask the SDK chain with empty strings."""

    def pick(flag: str, env: str) -> Optional[str]:
        v = getattr(config, flag, "") if config is not None else ""
        if v:
            return v
        return os.environ.get(env) or None

    return {
        "access_key": pick("cloud_storage_access_key",
                           "AWS_ACCESS_KEY_ID"),
        "secret_key": pick("cloud_storage_secret_key",
                           "AWS_SECRET_ACCESS_KEY"),
        "endpoint": pick("cloud_storage_endpoint", "AWS_ENDPOINT_URL"),
        "region": pick("cloud_storage_region", "AWS_DEFAULT_REGION"),
        "credentials_file": pick("cloud_storage_credentials_file",
                                 "GOOGLE_APPLICATION_CREDENTIALS"),
    }


class CloudStorage(ExternalStorage):
    """Object-storage spill tier (the reference's smart_open path, :204-230):
    one key per object under ``<scheme>://bucket/prefix``. The transport is a
    lazily-imported client (boto3 for s3://, google.cloud.storage for gs://) —
    absent SDKs raise at construction with a clear message, never at spill
    time. Credentials resolve via :func:`resolve_cloud_credentials`
    (Config flag → env var → SDK default chain)."""

    def __init__(self, uri: str, config=None):
        self.uri = uri.rstrip("/")
        scheme = uri.split("://", 1)[0]
        creds = resolve_cloud_credentials(config)
        if scheme == "s3":
            try:
                import boto3  # type: ignore
            except ImportError as e:  # pragma: no cover - sdk not in image
                raise RuntimeError(
                    "s3:// spill storage requires boto3") from e
            kw: Dict[str, str] = {}
            if creds["access_key"]:
                kw["aws_access_key_id"] = creds["access_key"]
            if creds["secret_key"]:
                kw["aws_secret_access_key"] = creds["secret_key"]
            if creds["endpoint"]:
                # MinIO / GCS-interop / on-prem S3 endpoints
                kw["endpoint_url"] = creds["endpoint"]
            if creds["region"]:
                kw["region_name"] = creds["region"]
            self._client = boto3.client("s3", **kw)
            self._kind = "s3"
        elif scheme == "gs":
            try:
                from google.cloud import storage as gcs  # type: ignore
            except ImportError as e:  # pragma: no cover
                raise RuntimeError(
                    "gs:// spill storage requires google-cloud-storage"
                ) from e
            if creds["credentials_file"]:
                self._client = gcs.Client.from_service_account_json(
                    creds["credentials_file"])
            else:
                self._client = gcs.Client()
            self._kind = "gs"
        else:  # pragma: no cover - registry filters schemes
            raise ValueError(f"unsupported cloud scheme: {scheme}")
        rest = self.uri.split("://", 1)[1]
        self.bucket, _, self.prefix = rest.partition("/")

    def _key(self, object_id: bytes) -> str:
        return f"{self.prefix}/{object_id.hex()}" if self.prefix \
            else object_id.hex()

    def spill(self, object_id: bytes, data: memoryview) -> str:
        key = self._key(object_id)
        if self._kind == "s3":
            self._client.put_object(Bucket=self.bucket, Key=key,
                                    Body=bytes(data))
        else:
            self._client.bucket(self.bucket).blob(key).upload_from_string(
                bytes(data))
        return f"{self.uri.split('://', 1)[0]}://{self.bucket}/{key}"

    def restore(self, object_id: bytes, url: str) -> bytes:
        key = url.split("://", 1)[1].split("/", 1)[1]
        if self._kind == "s3":
            return self._client.get_object(
                Bucket=self.bucket, Key=key)["Body"].read()
        return self._client.bucket(self.bucket).blob(key) \
            .download_as_bytes()

    def delete(self, url: str) -> None:
        key = url.split("://", 1)[1].split("/", 1)[1]
        try:
            if self._kind == "s3":
                self._client.delete_object(Bucket=self.bucket, Key=key)
            else:
                self._client.bucket(self.bucket).blob(key).delete()
        except Exception:
            pass

    def _url_key(self, url: str) -> str:
        """bucket-relative key of a full ``scheme://bucket/key`` url."""
        rest = url.split("://", 1)[1]
        _bucket, _, key = rest.partition("/")
        return key

    def put_blob(self, url: str, data: bytes) -> None:
        key = self._url_key(url)
        if self._kind == "s3":
            self._client.put_object(Bucket=self.bucket, Key=key,
                                    Body=bytes(data))
        else:
            self._client.bucket(self.bucket).blob(key).upload_from_string(
                bytes(data))

    def get_blob(self, url: str) -> bytes:
        key = self._url_key(url)
        if self._kind == "s3":
            return self._client.get_object(
                Bucket=self.bucket, Key=key)["Body"].read()
        return self._client.bucket(self.bucket).blob(key).download_as_bytes()

    def list_blobs(self, url_prefix: str) -> List[str]:
        pfx = self._url_key(url_prefix.rstrip("/")) + "/"
        scheme = self.uri.split("://", 1)[0]
        out: List[str] = []
        if self._kind == "s3":
            token = None
            while True:
                kw = dict(Bucket=self.bucket, Prefix=pfx)
                if token:
                    kw["ContinuationToken"] = token
                resp = self._client.list_objects_v2(**kw)
                out.extend(f"{scheme}://{self.bucket}/{row['Key']}"
                           for row in resp.get("Contents", []))
                if not resp.get("IsTruncated"):
                    break
                token = resp.get("NextContinuationToken")
        else:
            for blob in self._client.bucket(self.bucket).list_blobs(
                    prefix=pfx):
                out.append(f"{scheme}://{self.bucket}/{blob.name}")
        return sorted(out)


# scheme -> factory(uri) registry; third-party tiers plug in the way the
# reference's external storage is selected by the object_spilling_config
# type field (_private/external_storage.py:316 setup_external_storage)
_SCHEMES: Dict[str, "type"] = {
    "s3": CloudStorage,
    "gs": CloudStorage,
}


def register_storage_scheme(scheme: str, factory) -> None:
    """Register ``factory(uri) -> ExternalStorage`` for ``scheme://`` spill
    URIs (the custom external-storage plugin point)."""
    _SCHEMES[scheme] = factory


def storage_for_uri(uri: str, config=None) -> ExternalStorage:
    if "://" not in uri:
        return FileSystemStorage(uri)
    scheme = uri.split("://", 1)[0]
    factory = _SCHEMES.get(scheme)  # registry wins: file:// is overridable
    if factory is not None:
        if factory is CloudStorage:
            # built-in cloud tiers take the Config for credential
            # resolution; registered third-party factories keep the
            # plain factory(uri) contract
            return factory(uri, config=config)
        return factory(uri)
    if scheme == "file":
        return FileSystemStorage(uri[len("file://"):])
    raise ValueError(f"unsupported spill storage uri: {uri}")
