"""Worker process: executes tasks and hosts actors.

The analog of the reference's worker side of CoreWorker (task execution path
src/ray/core_worker/core_worker.cc:2181 → python/ray/_raylet.pyx:850,533) plus
the worker main loop (_raylet.pyx:1226 run_task_loop). Differences driven by
the TPU host-process model:

  - Transport is a same-host pipe to the driver-side node manager, not gRPC;
    args/returns ride the shared-memory store exactly like plasma.
  - The worker doubles as the reference's "IO worker" and nested-call client:
    tasks running here may call ``remote()``/``get()``/``put()``, which are
    proxied over the pipe to the owner runtime (the reference gives every
    worker a full CoreWorker; centralizing ownership in the driver is a
    single-host simplification, revisited for multi-host in the DCN plane).
  - Accelerator isolation: an exec message may carry ``visible_chips``; the
    worker exports ``TPU_VISIBLE_CHIPS`` before user code imports jax — the
    TPU analog of per-task CUDA_VISIBLE_DEVICES (_raylet.pyx:563).

Concurrency: the main thread is a pure receive loop. Normal tasks and each
actor run on their own serial executor (max_concurrency>1 widens the actor's
pool — concurrency groups, reference concurrency_group_manager.h); ``async
def`` actor methods run on a per-actor asyncio loop thread (fiber.h analog).
"""

from __future__ import annotations

import asyncio
import inspect
import os
import sys
import threading
import time
import traceback
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, List, Optional

from .. import serialization as ser
from ..utils import faults, profiler, structlog, tracing
from .object_store import StoreClient

log = structlog.get_logger(__name__)

# Actor classes preloaded by the ZYGOTE before forking (zygote.serve):
# every forked child inherits the loaded class via COW and skips its own
# cloudpickle.loads — the dominant per-child Python cost in an actor
# burst after the fork itself. Keyed by cls_id; plain dict (the zygote
# populates it pre-fork; children only read).
PRELOADED_CLASSES: Dict[bytes, Any] = {}


_m_executed = None


def _inc_executed() -> None:
    """Worker-side tasks-executed counter; lazily bound so the instrument
    registers in the WORKER's registry (its deltas merge into the head
    via the flush channel)."""
    global _m_executed
    if _m_executed is None:
        from . import metrics_defs as mdefs

        _m_executed = mdefs.worker_tasks_executed()
    _m_executed.inc()


class _ReplySender:
    """Reply writer owned by one persistent drain thread (the mirror of the
    runtime's _sender_enqueue): every enqueued reply is coalesced with
    whatever else accumulated into one ``{"type": "batch"}`` frame — one
    pickle + ONE pipe write for N completions. Each write to the driver
    pipe wakes the driver process (two context switches on a loaded host),
    so the executor thread never writes inline; it keeps executing while
    this thread drains."""

    def __init__(self, conn):
        self._conn = conn
        self._send_lock = threading.Lock()
        self._cond = threading.Condition()
        self._q: deque = deque()  # guarded-by: _cond
        self._thread: Optional[threading.Thread] = None  # guarded-by: _cond
        self._urgent = False  # an enqueued frame must not wait out the window  # guarded-by: _cond
        # adaptive flush window: after the first reply of a burst the
        # drain thread lingers briefly for stragglers, so N back-to-back
        # completions cost ONE pickle + ONE pipe write (flushing early
        # at the size cap). Workers receive explicit RMT_* env vars, not
        # the driver Config — see NodeManager.build_worker_env.
        try:
            self._window_s = float(
                os.environ.get("RMT_REPLY_FLUSH_WINDOW_S", "0.001"))
        except ValueError:
            self._window_s = 0.001
        try:
            self._flush_max = int(
                os.environ.get("RMT_REPLY_FLUSH_MAX", "32"))
        except ValueError:
            self._flush_max = 32

    def send(self, msg: dict, urgent: bool = False) -> None:
        """Enqueue one reply. ``urgent`` frames (owner round trips the
        executor parks on, the registration hello) flush the queue
        immediately instead of riding out the coalescing window."""
        with self._cond:
            self._q.append(msg)
            if urgent:
                self._urgent = True
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._drain_loop, daemon=True,
                    name="reply-sender")
                self._thread.start()
            self._cond.notify()

    def _write(self, payload: dict) -> bool:
        try:
            with self._send_lock:
                self._conn.send(payload)
            return True
        except (OSError, BrokenPipeError, ValueError):
            return False

    def send_now(self, msg: dict) -> bool:
        """Synchronous write, bypassing the drain thread — the exit-flush
        path, where os._exit follows immediately and a queued message
        would die with the process."""
        return self._write(msg)

    def flush_queued(self) -> None:
        """Synchronously deliver whatever the drain thread hasn't picked
        up yet (exit path: a done reply enqueued microseconds before
        shutdown must not lose the race with os._exit, and must reach
        the head BEFORE the final log/profile flush frame). Popping
        under _cond means each message is written exactly once whether
        this or the drain thread claims it."""
        with self._cond:
            msgs = list(self._q)
            self._q.clear()
        if msgs:
            self._write(msgs[0] if len(msgs) == 1 else
                        {"type": "batch", "msgs": msgs})

    def _drain_loop(self) -> None:
        while True:
            with self._cond:
                while not self._q:
                    self._cond.wait()
                if (self._window_s > 0 and not self._urgent
                        and len(self._q) < self._flush_max):
                    # linger for the burst's stragglers; wait() drops
                    # _cond so executor threads keep enqueueing, and an
                    # urgent send (or the size cap) ends the window early
                    deadline = time.monotonic() + self._window_s
                    while (not self._urgent
                           and len(self._q) < self._flush_max):
                        left = deadline - time.monotonic()
                        if left <= 0:
                            break
                        self._cond.wait(left)
                msgs = list(self._q)
                self._q.clear()
                self._urgent = False
            payload = msgs[0] if len(msgs) == 1 else {
                "type": "batch", "msgs": msgs}
            if not self._write(payload):
                return


class _TaskDispatcher:
    """Serial plain-task executor that grows one thread whenever the
    running task parks in an owner round trip (nested get/wait).

    Pipelined dispatch queues several tasks on this worker's pipe; if the
    executing task blocks on a dependency produced by a task queued BEHIND
    it, a fixed single thread would deadlock. The reference's semantics are
    that a worker blocked in ray.get releases its slot and other work
    proceeds; here that means: keep exactly one runnable executor thread,
    spawning a new one when the current one blocks (bounded by the
    pipelining depth, since only queued tasks trigger growth)."""

    def __init__(self):
        self._q: deque = deque()  # guarded-by: _cond
        self._cond = threading.Condition()
        self._threads = 0   # live executor threads  # guarded-by: _cond
        self._blocked = 0   # parked in an owner wait (proxy request)  # guarded-by: _cond
        self._waiting = 0   # idle, parked on the queue  # guarded-by: _cond
        self._resuming = 0  # returned from an owner wait, parked for turn  # guarded-by: _cond
        self._is_exec = threading.local()

    def _runnable(self) -> int:
        return self._threads - self._blocked - self._waiting - self._resuming

    def submit(self, fn, msg) -> None:
        with self._cond:
            self._q.append((fn, msg))
            if self._waiting:
                self._cond.notify_all()
            elif self._runnable() < 1:
                self._spawn()

    def _spawn(self) -> None:  # rmtcheck: holds=_cond
        self._threads += 1
        threading.Thread(target=self._loop, daemon=True,
                         name="task-exec").start()

    def _loop(self) -> None:
        self._is_exec.flag = True
        while True:
            with self._cond:
                self._waiting += 1
                self._cond.notify_all()  # runnable dropped: a resumer may go
                while True:
                    # claim work only while holding the sole runnable slot
                    if self._q and self._runnable() == 0:
                        break
                    if not self._q and self._waiting > 1:
                        # one parked thread is enough; surplus threads
                        # (grown while a task blocked) retire here
                        self._waiting -= 1
                        self._threads -= 1
                        return
                    self._cond.wait()
                self._waiting -= 1
                fn, msg = self._q.popleft()
            fn(msg)

    def steal(self) -> list:
        """Remove and return every not-yet-started plain-task message
        (work stealing: the owner re-dispatches these to an idle worker —
        the reference's direct-transport steal protocol). Tasks already
        executing are untouched; only queued ``exec`` frames move."""
        with self._cond:
            kept, stolen = deque(), []
            while self._q:
                fn, msg = self._q.popleft()
                if isinstance(msg, dict) and msg.get("type") == "exec":
                    stolen.append(msg)
                else:
                    kept.append((fn, msg))
            self._q = kept
        return stolen

    def enter_blocked(self) -> None:
        """The calling executor thread is about to park in an owner wait."""
        if not getattr(self._is_exec, "flag", False):
            return
        with self._cond:
            self._blocked += 1
            self._cond.notify_all()  # runnable dropped: queue may proceed
            if self._q and self._runnable() < 1 and not self._waiting:
                self._spawn()

    def exit_blocked(self) -> None:
        """Owner wait finished. Tasks execute strictly serially in a worker
        (process-wide state: cwd, env, native libs); if another executor
        thread took the runnable slot while we were blocked, park here
        until it blocks, finishes, or retires."""
        if not getattr(self._is_exec, "flag", False):
            return
        with self._cond:
            self._blocked -= 1
            # after the decrement this thread itself counts as runnable;
            # park only while some OTHER thread holds the slot too
            while self._runnable() > 1:
                self._resuming += 1
                self._cond.wait()
                self._resuming -= 1


class WorkerRuntimeProxy:
    """Driver-runtime facade available to user code running in this worker.

    Implements submit/get/put/wait by round-tripping requests to the owner
    over the worker pipe; the driver's router thread services them.
    """

    def __init__(self, worker: "Worker"):
        self._worker = worker
        self._pending: Dict[int, Any] = {}  # guarded-by: _lock
        self._events: Dict[int, threading.Event] = {}  # guarded-by: _lock
        self._req_counter = 0  # guarded-by: _lock
        self._lock = threading.Lock()
        # worker-side reference counting (the decentralization seed of
        # the reference's per-worker ReferenceCounter,
        # reference_count.h:39-61): this worker counts its OWN refs —
        # objects it put (it is the owner) and refs it deserialized
        # (borrows). Borrows still alive at task completion ship to the
        # head in the done reply's borrowed-ref table (the head converts
        # the task-duration arg pin into a worker-attributed pin);
        # zero-count transitions buffer into ``releases`` riding the
        # next done reply — no dedicated round trips in either
        # direction.
        # RLock: __del__ can fire inside any of these methods (a gc pass
        # collecting a ref cycle) and re-enter remove_local_ref
        self._ref_lock = threading.RLock()
        self._ref_counts: Dict[bytes, int] = {}  # guarded-by: _ref_lock
        self._owned: set = set()      # oids this worker put (owner)  # guarded-by: _ref_lock
        self._escaped: set = set()    # owned ids pickled OUT of this worker  # guarded-by: _ref_lock
        self._reported: set = set()   # borrows pinned head-side  # guarded-by: _ref_lock
        self._release_buf: List[bytes] = []  # guarded-by: _ref_lock
        self._owned_drop_buf: List[bytes] = []  # guarded-by: _ref_lock
        self.head_round_trips = 0  # observability: blocking owner RTs

    @property
    def inline_limit(self) -> int:
        return self._worker.inline_limit

    # -- worker-side reference counting ---------------------------------------
    def add_local_ref(self, oid: bytes) -> None:
        with self._ref_lock:
            self._ref_counts[oid] = self._ref_counts.get(oid, 0) + 1

    def mark_escaped(self, oid: bytes) -> None:
        """Called from ObjectRef.__reduce__ (serialize observer): the id
        left this process in a return/arg/put, so another process may
        hold it — the owner's release may only drop attribution, never
        free the value."""
        with self._ref_lock:
            if oid in self._owned:
                self._escaped.add(oid)

    def remove_local_ref(self, oid: bytes) -> None:
        with self._ref_lock:
            n = self._ref_counts.get(oid, 0) - 1
            if n > 0:
                self._ref_counts[oid] = n
                return
            self._ref_counts.pop(oid, None)
            owned = oid in self._owned
            reported = oid in self._reported
            self._owned.discard(oid)
            self._reported.discard(oid)
            if owned and oid in self._escaped:
                # the id is out in the world: the head only drops the
                # ownership attribution
                self._escaped.discard(oid)
                self._owned_drop_buf.append(oid)
            elif owned or reported:
                # the head holds freeable/pinned state: queue the release
                # (riding the next done reply — see ref_tables)
                self._release_buf.append(oid)

    def ref_tables(self) -> dict:
        """Borrow/release tables to piggyback on a done reply: new
        borrows (live deserialized refs not yet pinned head-side),
        buffered zero-count releases, and escaped-owned attribution
        drops. Called at completion-build time AFTER the frame's locals
        are dropped — the tables ride the reply, costing zero extra pipe
        writes."""
        out: dict = {}
        with self._ref_lock:
            borrows = [oid for oid, n in self._ref_counts.items()
                       if n > 0 and oid not in self._owned
                       and oid not in self._reported]
            if borrows:
                self._reported.update(borrows)
                out["borrows"] = borrows
            if self._release_buf:
                out["releases"] = self._release_buf
                self._release_buf = []
            if self._owned_drop_buf:
                out["owned_drops"] = self._owned_drop_buf
                self._owned_drop_buf = []
        return out

    def _request(self, msg: dict, timeout: Optional[float] = None):
        with self._lock:
            self._req_counter += 1
            req_id = self._req_counter
            ev = threading.Event()
            self._events[req_id] = ev
        msg["req_id"] = req_id
        self.head_round_trips += 1
        # urgent: this thread is about to PARK on the reply — every
        # microsecond the request sits in the coalescing window is pure
        # added round-trip latency
        self._worker.sender.send(msg, urgent=True)
        # an owner round trip can block on dependencies this worker itself
        # has queued — let the pipeline keep draining while we park
        dispatcher = self._worker.task_dispatcher
        dispatcher.enter_blocked()
        try:
            ok = ev.wait(timeout if timeout is not None else 3600.0)
        finally:
            dispatcher.exit_blocked()
        if not ok:
            raise TimeoutError(f"worker request {msg['type']} timed out")
        with self._lock:
            reply = self._pending.pop(req_id)
            self._events.pop(req_id, None)
        if reply.get("error") is not None:
            raise ser.loads(reply["error"])
        return reply

    def deliver(self, reply: dict) -> None:
        req_id = reply["req_id"]
        with self._lock:
            self._pending[req_id] = reply
            ev = self._events.get(req_id)
        if ev:
            ev.set()

    # -- API used by core.api when running inside a worker --------------------
    @staticmethod
    def _attach_trace_parent(payload: dict) -> dict:
        """A nested submit carries the EXECUTING task's trace context as
        its parent: the head minting the child spec chains its span onto
        it, which is what makes fan-out inside a task body one causal
        tree instead of a forest of fresh traces."""
        ctx = tracing.get_current()
        if ctx is not None and "trace_parent" not in payload:
            payload["trace_parent"] = ctx
        return payload

    def submit_task(self, payload: dict) -> List[bytes]:
        reply = self._request({"type": "submit_task",
                               "payload": self._attach_trace_parent(payload)})
        return reply["return_ids"]

    def submit_actor_task(self, payload: dict) -> List[bytes]:
        reply = self._request({"type": "submit_actor_task",
                               "payload": self._attach_trace_parent(payload)})
        return reply["return_ids"]

    def create_actor(self, payload: dict) -> bytes:
        reply = self._request({"type": "create_actor", "payload": payload})
        return reply["actor_id"]

    def get_objects(self, oids: List[bytes], timeout: Optional[float] = None,
                    consume: bool = False):
        """Resolve objects: local store first, else ask the owner (which
        transfers/restores/replies inline for memory-store values).
        ``consume=True`` TAKES device entries pinned in this process (the
        last-reader donation path) instead of reading them zero-copy."""
        out: Dict[bytes, Any] = {}
        missing: List[bytes] = []
        for oid in set(oids):
            if consume:
                arr = self._worker.device_store.take(oid)
                if arr is not None:
                    # one-way: the head drops its device routing for the
                    # oid (the buffer is being donated; no copy survives)
                    self._worker.sender.send(
                        {"type": "device_consumed", "object_id": oid})
                    out[oid] = arr
                    continue
            # device objects pinned in THIS process come back zero-copy
            arr = self._worker.device_store.get(oid)
            if arr is not None:
                out[oid] = arr
                continue
            view = self._worker.store.get(oid)
            if view is not None:
                out[oid] = self._maybe_repromote(
                    oid, self._worker.decode_value(view, pin=oid))
            else:
                missing.append(oid)
        attempt = 0
        while missing:
            req = {"type": "get_objects", "oids": missing}
            if attempt >= 3:
                # the owner's residency promise keeps getting reclaimed
                # under store pressure: ask for the bytes inline instead of
                # racing the spill tier again
                req["inline"] = True
            reply = self._request(req, timeout=timeout)
            still: List[bytes] = []
            for oid, enc in zip(missing, reply["values"]):
                if enc[0] == "v":
                    out[oid] = ser.loads(enc[1])
                else:  # now present in the local store
                    view = self._worker.store.get(oid)
                    if view is None:
                        # the owner's residency pin can be reclaimed under
                        # store pressure before our read lands — re-request
                        # (the owner restores again) instead of failing
                        still.append(oid)
                        continue
                    out[oid] = self._worker.decode_value(view, pin=oid)
            missing = still
            if missing:
                attempt += 1
                if attempt >= 8:
                    raise RuntimeError(
                        f"owner reported {missing[0].hex()} local but the "
                        f"store read kept missing after {attempt} attempts"
                    )
                time.sleep(0.05 * attempt)
        return [out[oid] for oid in oids]

    def _maybe_repromote(self, oid: bytes, value: Any):
        """Re-promotion on next device read: an object THIS worker
        demoted under budget pressure comes back as a live jax array
        (the demotion envelope rehydrates in decode) — re-pin it so
        subsequent local reads are zero-copy again. Movement back into
        HBM carries the device.materialize fault site; an injected
        error skips the re-pin (the host copy still serves the read)."""
        from ..config import global_config
        from .device_store import is_device_array

        worker = self._worker
        if oid not in worker._demoted_device:
            return value
        if not global_config().device_promote_on_read \
                or not is_device_array(value):
            worker._demoted_device.discard(oid)
            return value
        act = faults.fire("device.materialize")
        if act is not None:
            if act.mode == "stall":
                act.sleep()
            else:
                return value  # injected error/drop: serve the host copy
        worker._demoted_device.discard(oid)
        worker.device_store.put(oid, value)
        return value

    def _direct_store_put(self, data, own: bool) -> bytes:
        """Shared body of the decentralized put paths: mint the id in
        THIS worker, write straight into the node's shm store (asking
        the head to make room once on pressure), and register via a
        ONE-WAY ``owned_put`` frame — zero blocking round trips
        (previously two: reserve_put + put_sealed). Pipe FIFO + the
        head's inline handling guarantee the registration lands before
        any later message referencing the id. Small values and
        full-store degradation go through ``put_inline`` (owner memory);
        with ``own`` those also register in the owned table so the
        owner-release protocol applies uniformly."""
        from ..ids import ObjectID
        from ..native import ShmStoreFullError

        if data.total_size <= self._worker.inline_limit:
            reply = self._request(
                {"type": "put_inline", "data": data.to_bytes(),
                 "own": own})
            oid = reply["object_id"]
            if own:
                with self._ref_lock:
                    self._owned.add(oid)
            return oid
        oid = ObjectID.for_put().binary()
        stored = False
        for attempt in range(2):
            try:
                self._worker.store.put_serialized(oid, data)
                stored = True
                break
            except ShmStoreFullError:
                if attempt == 0:
                    try:
                        self._request({"type": "make_room",
                                       "bytes": data.total_size},
                                      timeout=60)
                    except Exception:  # noqa: BLE001 — fall through
                        break
        if not stored:
            # node store full past spilling: owner-memory inline put is
            # the last resort (same degradation as oversized returns)
            reply = self._request(
                {"type": "put_inline", "data": data.to_bytes(),
                 "own": own})
            oid = reply["object_id"]
            if own:
                with self._ref_lock:
                    self._owned.add(oid)
            return oid
        if own:
            with self._ref_lock:
                self._owned.add(oid)
        self._worker.sender.send({"type": "owned_put", "object_id": oid,
                                  "own": own, "size": data.total_size})
        return oid

    def put_object(self, value: Any) -> bytes:
        """Store a value with THIS WORKER as the owner — the
        ownership-decentralization seed (reference_count.h:39 'the
        worker that creates the ObjectRef owns it')."""
        return self._direct_store_put(ser.serialize(value), own=True)

    def put_device_object(self, value: Any) -> bytes:
        """Pin a jax.Array in this worker's device store; two-phase with
        the owner (reserve, store locally, seal) so a get racing the put
        waits for the seal instead of missing the object."""
        from .device_store import is_device_array

        if not is_device_array(value):
            raise TypeError(
                "put(..., device=True) requires a jax.Array; got "
                f"{type(value).__name__}")
        from . import transfer as xfer

        reply = self._request({"type": "device_put"})
        oid = reply["object_id"]
        try:
            nbytes = int(value.nbytes)
        except Exception:  # noqa: BLE001
            nbytes = 0
        self._worker.device_store.put(oid, value)
        # the seal carries size (locality scoring sees HBM bytes) and the
        # producer's mesh fingerprint (the head's ICI-vs-host route input)
        self._request({"type": "device_put_sealed", "object_id": oid,
                       "size": nbytes, "mesh": xfer.mesh_fingerprint()})
        return oid

    def put_serialized_arg(self, data) -> bytes:
        """Big nested-task args: same zero-round-trip direct store write
        as put_object, but with ``own: False`` — no ObjectRef ever wraps
        these ids (the task spec holds them), so the head keeps plain
        location state without owner attribution."""
        return self._direct_store_put(data, own=False)

    def wait(self, oids: List[bytes], num_returns: int, timeout, fetch_local):
        reply = self._request({
            "type": "wait", "oids": oids, "num_returns": num_returns,
            "timeout": timeout,
        }, timeout=None if timeout is None else timeout + 5)
        return reply["ready"], reply["not_ready"]

    def kill_actor(self, actor_id: bytes, no_restart: bool) -> None:
        self._request({"type": "kill_actor", "actor_id": actor_id,
                       "no_restart": no_restart})

    def cancel_task(self, oid: bytes, force: bool) -> None:
        self._request({"type": "cancel_task", "object_id": oid,
                       "force": force})

    def actor_method_spec(self, actor_id: bytes):
        reply = self._request({"type": "actor_info", "actor_id": actor_id})
        return reply

    def get_named_actor(self, name: str) -> bytes:
        reply = self._request({"type": "get_named_actor", "name": name})
        return reply["actor_id"]

    # placement groups proxy to the driver-side manager so nested libraries
    # (a Trainer running inside a Tune trial actor) can gang-schedule — the
    # reference supports the same nesting through its GCS PG manager
    def create_placement_group(self, bundles, strategy, name="") -> bytes:
        reply = self._request({"type": "create_pg", "bundles": bundles,
                               "strategy": strategy, "name": name})
        return reply["pg_id"]

    def placement_group_state(self, pg_id: bytes):
        return self._request({"type": "pg_state", "pg_id": pg_id})["state"]

    def wait_placement_group(self, pg_id: bytes, timeout: float) -> bool:
        # blocks server-side on the request pool (like nested get/wait) —
        # one round-trip instead of a poll loop
        reply = self._request({"type": "wait_pg", "pg_id": pg_id,
                               "timeout": timeout}, timeout=timeout + 30)
        return reply["created"]

    def remove_placement_group(self, pg_id: bytes) -> None:
        self._request({"type": "remove_pg", "pg_id": pg_id})


class _ActorState:
    def __init__(self, instance, max_concurrency: int):
        self.instance = instance
        self.max_concurrency = max_concurrency
        self.executor = ThreadPoolExecutor(
            max_workers=max_concurrency, thread_name_prefix="actor"
        )
        self.loop: Optional[asyncio.AbstractEventLoop] = None
        self.loop_thread: Optional[threading.Thread] = None
        # Bounds concurrent coroutines to max_concurrency (the reference
        # caps async actors the same way; threads bound only sync methods).
        self.async_sem: Optional[asyncio.Semaphore] = None
        self._loop_lock = threading.Lock()

    def ensure_loop(self) -> asyncio.AbstractEventLoop:
        # called from executor threads concurrently; exactly one loop/actor
        with self._loop_lock:
            if self.loop is None:
                self.loop = asyncio.new_event_loop()
                self.async_sem = asyncio.Semaphore(self.max_concurrency)
                self.loop_thread = threading.Thread(
                    target=self.loop.run_forever, daemon=True,
                    name="actor-asyncio"
                )
                self.loop_thread.start()
            return self.loop


class Worker:
    def __init__(self, conn, worker_id: bytes, node_id: bytes,
                 store_name: str, inline_limit: int):
        from ..config import global_config
        from .device_store import DeviceObjectStore, resolve_capacity

        self.conn = conn
        self.worker_id = worker_id
        self.node_id = node_id
        self.store = StoreClient(store_name)
        # workers see the env-driven config (RMT_* vars travel through the
        # pool spawn), so capacity/precision knobs apply per-process
        self.device_store = DeviceObjectStore(
            capacity_bytes=resolve_capacity(global_config()),
            on_demote=self._demote_device_object)
        # oids this process demoted (re-promotion candidates on read);
        # benign races only — a miss just skips one re-pin
        self._demoted_device: set = set()
        self.inline_limit = inline_limit
        self.sender = _ReplySender(conn)
        self.proxy = WorkerRuntimeProxy(self)
        self.functions: Dict[bytes, Any] = {}
        self.classes: Dict[bytes, Any] = {}
        self.actors: Dict[bytes, _ActorState] = {}
        self.task_dispatcher = _TaskDispatcher()
        self._shutdown = threading.Event()

    # -- value encoding -------------------------------------------------------
    def decode_value(self, view: memoryview, pin: Optional[bytes] = None):
        """Deserialize from a store view. The view stays referenced by any
        zero-copy numpy arrays; we release our store ref only after the task
        completes (args are pinned for the task's duration, as the raylet pins
        task args — local_task_manager.cc:388)."""
        return ser.deserialize(view)

    def decode_args(self, args, kwargs):
        pinned: List[bytes] = []

        def decode(enc):
            kind, payload = enc
            if kind == "v":
                return ser.loads(payload)
            view = self.store.get(payload)
            if view is None:
                # Not local (spilled elsewhere / other node): owner will fix.
                value = self.proxy.get_objects([payload])[0]
                return value
            pinned.append(payload)
            return ser.deserialize(view)

        pos = [decode(a) for a in args]
        kw = {k: decode(v) for k, v in kwargs.items()}
        return pos, kw, pinned

    def encode_returns(self, values: List[Any], return_ids: List[bytes]):
        """Small returns inline in the reply (owner memory store); big ones go
        straight to shm (core_worker.cc:892 PutInLocalPlasmaStore analog).

        A full store is the owner's problem, not a task failure: the worker
        asks the owner to make room (the owner spills the node's store — a
        plasma create triggering raylet spilling, create_request_queue.h:32)
        and retries; if the store STILL cannot take it, the value ships
        inline in the reply as the last resort."""
        from ..native import ShmStoreFullError

        encoded = []
        for value, oid in zip(values, return_ids):
            data = ser.serialize(value)
            if data.total_size <= self.inline_limit:
                encoded.append((oid, "v", data.to_bytes()))
                continue
            stored = False
            for attempt in range(2):
                try:
                    self.store.put_serialized(oid, data)
                    stored = True
                    break
                except ShmStoreFullError:
                    if attempt == 0:
                        try:
                            self.proxy._request(
                                {"type": "make_room",
                                 "bytes": data.total_size}, timeout=60)
                        except Exception:  # noqa: BLE001 — fall through
                            break
            if stored:
                encoded.append((oid, "store", data.total_size))
            else:
                # visible degradation: the value bypasses the object store
                # and lands in owner memory — if this repeats, the store is
                # undersized for the workload
                from ..utils import events

                events.emit(
                    "RETURN_INLINED",
                    f"store full even after spilling; shipping a "
                    f"{data.total_size}-byte return inline",
                    severity=events.WARNING, source="core_worker")
                log.warning("node store full; return of %s bytes "
                            "shipped inline", data.total_size)
                encoded.append((oid, "v", data.to_bytes()))
        return encoded

    # -- execution ------------------------------------------------------------
    @staticmethod
    def _apply_chip_lease(msg: dict) -> None:
        """Export the leased chips before user code imports jax — the TPU
        analog of per-task CUDA_VISIBLE_DEVICES (_raylet.pyx:563). The pool
        pins workers to JAX_PLATFORMS=cpu by default; a chip lease lifts that
        so jax can claim the TPU."""
        chips = msg.get("visible_chips")
        if chips is not None:
            os.environ["TPU_VISIBLE_CHIPS"] = chips
            if os.environ.get("JAX_PLATFORMS") == "cpu":
                del os.environ["JAX_PLATFORMS"]

    def _resolve_function(self, msg) -> Any:
        fn_id = msg["fn_id"]
        fn = self.functions.get(fn_id)
        if fn is None:
            blob = msg.get("fn_blob")
            if blob is None:
                raise RuntimeError(f"function {fn_id.hex()} not registered")
            import cloudpickle

            fn = cloudpickle.loads(blob)
            self.functions[fn_id] = fn
        return fn

    def exec_task(self, msg: dict) -> None:
        task_id = msg["task_id"]
        pinned: List[bytes] = []
        args = kwargs = result = returns = None
        t0 = time.time()
        # install the task's trace context for the duration of the call:
        # the exec span lands on the submitting trace, and any nested
        # .remote() inside the task body chains onto it (the proxy reads
        # the current context when it attaches trace_parent)
        trace_ctx = tracing.from_wire(msg.get("trace_ctx"))
        trace_tok = tracing.set_current(trace_ctx)
        # log records emitted by the task body (print, logging, package
        # logger) attribute to this task via the same ContextVar pattern
        log_tok = structlog.set_task_context(task_id.hex())
        # the stack sampler reads task identity through a per-thread-ident
        # map (ContextVars are invisible across threads); register it at
        # the same boundary, and bracket execution with rusage snapshots
        prof_tok = profiler.set_task_context(
            task_id.hex(), trace_ctx[0] if trace_ctx else None)
        ru0 = profiler.task_rusage_begin(self.device_store)
        try:
            self._apply_chip_lease(msg)
            fn = self._resolve_function(msg)
            # fault site: an injected error rides the normal app-error
            # path, so recovery is the task-retry machinery itself
            act = faults.fire("worker.exec")
            if act is not None:
                if act.mode == "stall":
                    act.sleep()
                else:
                    act.raise_()
            args, kwargs, pinned = self.decode_args(msg["args"], msg["kwargs"])
            env = msg.get("runtime_env")
            if env:
                from ..runtime_env import applied as _env_applied

                with _env_applied(env):
                    result = fn(*args, **kwargs)
            else:
                result = fn(*args, **kwargs)
            returns = self._split_returns(result, msg["return_ids"])
            reply = {
                "type": "done", "task_id": task_id,
                "returns": self.encode_returns(returns, msg["return_ids"]),
                "error": None,
            }
        except BaseException as e:  # noqa: BLE001 — errors travel to the owner
            reply = {
                "type": "done", "task_id": task_id, "returns": [],
                "error": self._encode_error(msg.get("name", "task"), e),
            }
        finally:
            tracing.reset(trace_tok)
            structlog.reset_task_context(log_tok)
            profiler.reset_task_context(prof_tok)
            # resource deltas ride the reply like tstamps; computed here,
            # before the frame's refs drop, so peak_rss sees the task's
            # working set
            reply["rusage"] = profiler.task_rusage_end(
                ru0, self.device_store)
            for oid in pinned:
                self.store.release(oid)
        # drop the frame's refs BEFORE computing the borrow table: only
        # refs the USER retained (actor/global state) count as borrows —
        # args/result dying with the call must not ping-pong pin/release
        # through the head every task
        args = kwargs = result = returns = None  # noqa: F841
        reply["profile"] = self._profile_batch(
            f"task::{msg.get('name', 'task')}", t0,
            trace=trace_ctx, task_id=task_id)
        # the task's buffered log records ride ITS done reply: the head
        # ingests them before resolving the completion future, so a
        # task's last line is queryable the moment get() returns
        lgs = structlog.drain_records()
        if lgs:
            reply["logs"] = lgs
        # same contract for stack samples: the head ingests them before
        # resolving the future, so the burner's frames are queryable
        # through get_profile the moment get() returns
        smp = profiler.drain_samples()
        if smp:
            reply["samples"] = smp
        # worker-side lifecycle stamps ride the reply; the owner merges
        # them into the task's transition record (task_events analog)
        reply["tstamps"] = {"RUNNING": t0, "WORKER_DONE": time.time()}
        _inc_executed()
        # borrowed-ref table + buffered releases ride the done reply
        # (reference_count.h:139-156: the borrowed-ref table ships back
        # on task completion) — zero extra pipe writes
        reply.update(self.proxy.ref_tables())
        self.sender.send(reply)

    def _profile_batch(self, span_name: str, t0: float,
                       trace=None, task_id=None) -> List[dict]:
        """Record this task's execution span and flush buffered user
        profile() events — the worker→GCS ProfileEvent batch path
        (src/ray/core_worker/profiling.h:30) riding the done reply.
        ``trace`` carries the task's (trace_id, span_id, parent) so the
        exec slice joins the head-side lifecycle slices' flow group."""
        from ..utils import timeline

        timeline.record_event(
            span_name, "task", t0, time.time(),
            pid=f"worker:{self.worker_id.hex()[:8]}",
            extra={"task_id": task_id.hex()} if task_id else None,
            trace=trace,
        )
        # amortized: most replies carry no profile; every ~64th (or 1s)
        # carries the batch — stragglers ship via _profile_flush_loop
        return timeline.drain_events_if_due()

    @staticmethod
    def _split_returns(result, return_ids):
        n = len(return_ids)
        if n == 1:
            return [result]
        if not isinstance(result, (tuple, list)) or len(result) != n:
            raise ValueError(
                f"task declared num_returns={n} but returned {type(result)}"
            )
        return list(result)

    @staticmethod
    def _encode_error(name: str, e: BaseException) -> bytes:
        from ..exceptions import TaskError

        if isinstance(e, TaskError):  # propagate the original site
            return ser.dumps(e)
        tb = "".join(traceback.format_exception(e))
        try:
            return ser.dumps(TaskError(name, e, tb))
        except Exception:
            return ser.dumps(TaskError(name, None, tb))

    def materialize_device(self, msg: dict) -> None:
        """Owner-side device→host copy on demand: serialize the pinned
        array into this node's shm store so remote readers ride the
        normal object plane (device_store.py design)."""
        oid = msg["object_id"]
        try:
            act = faults.fire("device.materialize")
            if act is not None:
                if act.mode == "stall":
                    act.sleep()
                else:
                    act.raise_()
            arr = self.device_store.get(oid)
            if arr is None:
                raise KeyError(
                    f"device object {oid.hex()} not pinned in this worker")
            self.store.put_serialized(oid, ser.serialize(arr))
            reply = {"type": "device_materialized", "object_id": oid,
                     "error": None}
        except BaseException as e:  # noqa: BLE001
            reply = {"type": "device_materialized", "object_id": oid,
                     "error": self._encode_error("materialize_device", e)}
        self.sender.send(reply)

    def _demote_device_object(self, oid: bytes, arr: Any) -> bool:
        """Budget-pressure demotion callback (device_store.on_demote):
        HBM → this node's shm tier, optionally bf16-downcast. Runs on
        whichever thread overfilled the store; a full shm store defers
        the eviction (return False — the entry stays device-resident)."""
        from ..config import global_config
        from ..native import ShmStoreFullError
        from ..serialization import serialize_device_demotion

        data = serialize_device_demotion(
            arr, global_config().device_demote_precision)
        try:
            self.store.put_serialized(oid, data)
        except ShmStoreFullError:
            return False
        self._demoted_device.add(oid)
        # one-way notice: the head flips the directory tier to shm and
        # stops routing device reads here (pipe FIFO orders it before any
        # later frame referencing the oid)
        self.sender.send({"type": "device_demoted", "object_id": oid,
                          "size": data.total_size})
        return True

    def create_actor(self, msg: dict) -> None:
        actor_id = msg["actor_id"]
        try:
            self._apply_chip_lease(msg)
            cls_id = msg["cls_id"]
            cls = self.classes.get(cls_id) or PRELOADED_CLASSES.get(cls_id)
            if cls is None:
                blob = msg.get("cls_blob")
                if blob is None:  # stripped blob + no preload: a bug
                    raise RuntimeError(
                        f"class {cls_id.hex()} neither preloaded nor "
                        "shipped with the create")
                import cloudpickle

                cls = cloudpickle.loads(blob)
            self.classes[cls_id] = cls
            args, kwargs, pinned = self.decode_args(msg["args"], msg["kwargs"])
            # actors own their dedicated worker process: the env applies
            # for the process lifetime (async + concurrent methods see it
            # with no per-call save/restore races)
            from ..runtime_env import apply_permanent

            apply_permanent(msg.get("runtime_env"))
            instance = cls(*args, **kwargs)
            for oid in pinned:
                self.store.release(oid)
            state = _ActorState(instance, msg.get("max_concurrency", 1))
            self.actors[actor_id] = state
            reply = {"type": "actor_created", "actor_id": actor_id,
                     "error": None}
        except BaseException as e:  # noqa: BLE001
            reply = {"type": "actor_created", "actor_id": actor_id,
                     "error": self._encode_error(msg.get("name", "actor"), e)}
        self.sender.send(reply)

    def exec_actor_task(self, msg: dict) -> None:
        task_id = msg["task_id"]
        state = self.actors.get(msg["actor_id"])
        if state is None:
            self.sender.send({
                "type": "done", "task_id": task_id, "returns": [],
                "error": self._encode_error(
                    msg.get("name", "actor-task"),
                    RuntimeError("actor not found on worker"),
                ),
            })
            return
        method = getattr(state.instance, msg["method"], None)
        if method is None:
            self.sender.send({
                "type": "done", "task_id": task_id, "returns": [],
                "error": self._encode_error(
                    msg["method"], AttributeError(msg["method"])),
            })
            return
        pinned: List[bytes] = []
        t0 = time.time()
        trace_ctx = tracing.from_wire(msg.get("trace_ctx"))
        trace_tok = tracing.set_current(trace_ctx)
        log_tok = structlog.set_task_context(task_id.hex(),
                                            msg["actor_id"].hex())
        prof_tok = profiler.set_task_context(
            task_id.hex(), trace_ctx[0] if trace_ctx else None)
        ru0 = profiler.task_rusage_begin(self.device_store)
        try:
            args, kwargs, pinned = self.decode_args(msg["args"], msg["kwargs"])
            if inspect.iscoroutinefunction(method):
                # Async methods run as coroutines on the actor's loop and do
                # NOT hold this executor thread while awaiting (fiber.h
                # semantics: max_concurrency bounds threads for sync methods,
                # while any number of coroutines may be parked on awaits —
                # e.g. many blocked queue getters). The done callback (on the
                # loop thread) sends the reply and releases pinned args.
                loop = state.ensure_loop()

                async def _bounded(m=method, a=args, kw=kwargs, s=state,
                                   tc=trace_ctx, tid=task_id,
                                   aid=msg["actor_id"]):
                    # run_coroutine_threadsafe does NOT inherit this
                    # dispatcher thread's contextvars — the trace context
                    # (and the log plane's task context) must be installed
                    # INSIDE the coroutine for nested submits awaited by
                    # the method body to chain
                    tok = tracing.set_current(tc)
                    ltok = structlog.set_task_context(tid.hex(), aid.hex())
                    # the loop thread runs this coroutine — register the
                    # task identity there so samples taken mid-await
                    # attribute correctly (per-thread map, see exec_task)
                    ptok = profiler.set_task_context(
                        tid.hex(), tc[0] if tc else None)
                    try:
                        async with s.async_sem:
                            return await m(*a, **kw)
                    finally:
                        tracing.reset(tok)
                        structlog.reset_task_context(ltok)
                        profiler.reset_task_context(ptok)

                fut = asyncio.run_coroutine_threadsafe(_bounded(), loop)
                fut.add_done_callback(
                    lambda f, p=pinned: self._finish_actor_task(
                        msg, t0, p, f, ru0)
                )
                return
            result = method(*args, **kwargs)
            returns = self._split_returns(result, msg["return_ids"])
            reply = {
                "type": "done", "task_id": task_id,
                "returns": self.encode_returns(returns, msg["return_ids"]),
                "error": None,
            }
        except BaseException as e:  # noqa: BLE001
            reply = {"type": "done", "task_id": task_id, "returns": [],
                     "error": self._encode_error(msg["method"], e)}
        finally:
            tracing.reset(trace_tok)
            structlog.reset_task_context(log_tok)
            profiler.reset_task_context(prof_tok)
        reply["rusage"] = profiler.task_rusage_end(ru0, self.device_store)
        for oid in pinned:
            self.store.release(oid)
        # only refs retained in actor/user state survive this drop and
        # count as borrows (see exec_task)
        args = kwargs = result = returns = None  # noqa: F841
        reply["profile"] = self._profile_batch(
            f"actor::{msg.get('name', msg['method'])}", t0,
            trace=trace_ctx, task_id=task_id)
        lgs = structlog.drain_records()
        if lgs:
            reply["logs"] = lgs
        smp = profiler.drain_samples()
        if smp:
            reply["samples"] = smp
        reply["tstamps"] = {"RUNNING": t0, "WORKER_DONE": time.time()}
        _inc_executed()
        reply.update(self.proxy.ref_tables())  # borrows/releases ride along
        self.sender.send(reply)

    def _finish_actor_task(self, msg: dict, t0: float, pinned: List[bytes],
                           fut, ru0: Optional[dict] = None) -> None:
        """Completion callback for async actor methods (runs on the actor's
        loop thread when the coroutine finishes)."""
        task_id = msg["task_id"]
        try:
            result = fut.result()
            returns = self._split_returns(result, msg["return_ids"])
            reply = {
                "type": "done", "task_id": task_id,
                "returns": self.encode_returns(returns, msg["return_ids"]),
                "error": None,
            }
        except BaseException as e:  # noqa: BLE001
            reply = {"type": "done", "task_id": task_id, "returns": [],
                     "error": self._encode_error(msg["method"], e)}
        finally:
            for oid in pinned:
                self.store.release(oid)
        # drop before the borrow table — including the Future's stored
        # result, which would otherwise keep returned refs alive and
        # falsely report them as borrows (released only at the NEXT
        # done, or never on an idle actor)
        result = returns = None  # noqa: F841
        try:
            fut._result = None
        except AttributeError:
            pass
        fut = None  # noqa: F841
        reply["profile"] = self._profile_batch(
            f"actor::{msg.get('name', msg['method'])}", t0,
            trace=tracing.from_wire(msg.get("trace_ctx")), task_id=task_id)
        lgs = structlog.drain_records()
        if lgs:
            reply["logs"] = lgs
        smp = profiler.drain_samples()
        if smp:
            reply["samples"] = smp
        reply["tstamps"] = {"RUNNING": t0, "WORKER_DONE": time.time()}
        if ru0 is not None:
            # begin was snapped on the dispatcher thread, end runs here on
            # the loop thread — task_rusage_end detects the mismatch and
            # falls back to the process CPU clock
            reply["rusage"] = profiler.task_rusage_end(
                ru0, self.device_store)
        _inc_executed()
        reply.update(self.proxy.ref_tables())  # borrows/releases ride along
        self.sender.send(reply)

    # -- log streaming --------------------------------------------------------
    def start_output_capture(self) -> None:
        """Redirect this process's stdout/stderr (fd level, so native writes
        are caught too) into an in-band pipe whose drain thread ships chunks
        to the owner as ``log`` frames. The driver prints them prefixed with
        the worker identity — the log-monitor-tails-to-driver behavior of
        the reference (services.py:1126), collapsed onto the worker pipe
        (which also carries them through the node-agent tunnel, so REMOTE
        workers' prints reach the driver the same way)."""
        import sys

        r, w = os.pipe()
        os.dup2(w, 1)
        os.dup2(w, 2)
        os.close(w)
        # line buffering so a task's print() ships before the task blocks
        sys.stdout = os.fdopen(1, "w", buffering=1, closefd=False)
        sys.stderr = os.fdopen(2, "w", buffering=1, closefd=False)

        def drain() -> None:
            while True:
                try:
                    chunk = os.read(r, 65536)
                except OSError:
                    return
                if not chunk:
                    return
                self.sender.send({"type": "log", "data": chunk})

        threading.Thread(target=drain, daemon=True,
                         name="log-capture").start()

    # -- main loop ------------------------------------------------------------
    def _flush_frame(self, spans: List[dict]) -> Optional[dict]:
        """Build one combined flush frame: straggler timeline spans plus
        this process's buffered events, log records and metric-series
        deltas (the agent→head aggregation ride-along). None when
        nothing moved."""
        from ..utils import events as _events
        from ..utils import metrics as _metrics

        evs = _events.drain_events()
        lgs = structlog.drain_records()
        try:
            series = _metrics.snapshot_deltas()
        except Exception:  # noqa: BLE001 — never block the flush on stats
            series = []
        try:
            smp = profiler.drain_samples()
        except Exception:  # noqa: BLE001 — never block the flush on stats
            smp = []
        if not (spans or evs or lgs or series or smp):
            return None
        frame: dict = {"type": "profile", "profile": spans or []}
        if evs:
            frame["events"] = evs
        if lgs:
            frame["logs"] = lgs
        if series:
            frame["series"] = series
        if smp:
            frame["samples"] = smp
        return frame

    def _profile_flush_loop(self) -> None:
        """Straggler profile spans: the done-reply path batches spans
        (drain_events_if_due), so an idle worker could sit on a tail of
        undelivered spans forever — this 1 s ticker ships them as a
        standalone frame (with piggybacked events + metric deltas).
        No-op (no send, no wakeups) while empty."""
        from ..utils import timeline

        while not self._shutdown.is_set():
            self._shutdown.wait(1.0)
            evs = timeline.drain_events_if_due(min_batch=1,
                                               max_age_s=1.0)
            frame = self._flush_frame(evs)
            if frame:
                self.sender.send(frame)

    def _final_flush(self) -> None:
        """Unconditional exit flush: spans/events/metric deltas buffered
        since the last ticker tick would die with os._exit — drain
        everything and write SYNCHRONOUSLY (the sender's drain thread may
        never be scheduled again). Failures are moot: if the pipe is
        already closed the head has moved on."""
        try:
            # queued done replies first: their attached log batches must
            # land before (and never lose the os._exit race to) the
            # trailing flush frame
            self.sender.flush_queued()
        except Exception:  # noqa: BLE001 — exiting anyway
            pass
        try:
            from ..utils import timeline

            spans = timeline.drain_events_if_due(min_batch=1, max_age_s=0.0)
            frame = self._flush_frame(spans)
            if frame:
                self.sender.send_now(frame)
        except Exception:  # noqa: BLE001 — exiting anyway
            pass

    def run(self) -> None:
        from .. import _worker_context

        _worker_context.set_proxy(self.proxy)
        if os.environ.get("RMT_LOG_TO_DRIVER") == "1":
            self.start_output_capture()
        # structured capture layers OVER the raw fd capture: the tee
        # writes through to the pipe (driver live tail unchanged) while
        # minting attributed records for the head LogStore
        structlog.configure(node_id=self.node_id.hex(), role="worker")
        structlog.install_worker_capture()
        # continuous low-hz stack sampling for the profiling plane; the
        # drained samples ride the same flush frames as spans/logs
        profiler.configure(node_id=self.node_id.hex(), role="worker")
        profiler.start_sampler()
        threading.Thread(target=self._profile_flush_loop, daemon=True,
                         name="profile-flush").start()
        # registration doubles as the ready signal (exec-then-connect
        # handshake; the runtime binds this connection to our WorkerHandle)
        self.sender.send({"type": "ready", "worker_id": self.worker_id,
                          "node_id": self.node_id, "pid": os.getpid()},
                         urgent=True)
        # a bootstrap message (the reference's dedicated-worker startup
        # token carrying the assigned actor, worker_pool.h:446) was handed
        # to us AT SPAWN — process it without waiting for the owner's
        # registration round trip. Ordering is safe: the owner sends actor
        # tasks only after our actor_ready reply.
        boot = getattr(self, "bootstrap_msg", None)
        if boot is not None:
            self.bootstrap_msg = None
            self._dispatch(boot)
        while not self._shutdown.is_set():
            try:
                msg = self.conn.recv()
            except (EOFError, OSError):
                break
            # batch frames come from the runtime's sender thread, which
            # coalesces back-to-back dispatches into one pickle+write
            msgs = msg["msgs"] if msg["type"] == "batch" else (msg,)
            for m in msgs:
                self._dispatch(m)
        self._final_flush()
        os._exit(0)  # skip atexit: the store mapping may hold live views

    def _dispatch(self, msg: dict) -> None:
        mtype = msg["type"]
        if mtype == "exec":
            self.task_dispatcher.submit(self.exec_task, msg)
        elif mtype == "exec_actor":
            state = self.actors.get(msg["actor_id"])
            if state is not None:
                state.executor.submit(self.exec_actor_task, msg)
            else:
                self.task_dispatcher.submit(self.exec_actor_task, msg)
        elif mtype == "create_actor":
            self.task_dispatcher.submit(self.create_actor, msg)
        elif mtype == "reply":
            self.proxy.deliver(msg)
        elif mtype == "materialize_device":
            # own thread: queuing behind a long task on task_executor
            # would stall remote readers of a live pinned object
            threading.Thread(
                target=self.materialize_device, args=(msg,),
                daemon=True, name="materialize-device").start()
        elif mtype == "steal":
            stolen = self.task_dispatcher.steal()
            # urgent: an idle worker elsewhere is waiting on this handback
            self.sender.send({
                "type": "stolen",
                "task_ids": [m["task_id"] for m in stolen],
            }, urgent=True)
        elif mtype == "free_device":
            self.device_store.delete(msg["object_id"])
            self._demoted_device.discard(msg["object_id"])
        elif mtype == "ping":
            self.sender.send({"type": "pong"})
        elif mtype == "shutdown":
            self._shutdown.set()


def worker_entry(conn, worker_id: bytes, node_id: bytes, store_name: str,
                 inline_limit: int, env: Optional[dict] = None) -> None:
    """Entry point run in the spawned worker process (worker_pool starts us —
    the WorkerPool::StartWorkerProcess analog, worker_pool.h:427)."""
    if env:
        os.environ.update(env)
    Worker(conn, worker_id, node_id, store_name, inline_limit).run()
