"""Canonical core-runtime instrument set (the reference's
src/ray/stats/metric_defs.cc analog).

Every metric the runtime emits is declared here once — name, type, help
text, tag keys, bucket boundaries — and call sites fetch instruments via
the accessor functions. Accessors re-register on demand so the set
survives ``metrics.clear_registry()`` in tests: construction either
registers a fresh instrument or aliases the storage of an
already-registered one (utils/metrics.py _adopt_prior).

Naming follows the Prometheus conventions the reference exporter uses:
``rmt_`` prefix, ``_total`` suffix on counters, base units in names
(seconds / bytes).
"""

from __future__ import annotations

from typing import Dict

from ..utils.metrics import Counter, Gauge, Histogram, Metric

# latency buckets: 500us .. 60s, roughly log-spaced — covers scheduler
# hops (sub-ms) through long collective/transfer ops
LATENCY_BOUNDARIES = [0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                      0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0]
# size buckets: 1KiB .. 4GiB
BYTES_BOUNDARIES = [float(1 << s) for s in (10, 14, 17, 20, 23, 26, 29, 32)]

# name -> (cls, kwargs); pure data so tests can assert the full set
DEFS: Dict[str, tuple] = {
    # task lifecycle (task_events analog)
    "rmt_tasks_submitted_total": (Counter, dict(
        description="Tasks submitted to the runtime (incl. actor tasks).")),
    "rmt_tasks_finished_total": (Counter, dict(
        description="Tasks that reached FINISHED.")),
    "rmt_tasks_failed_total": (Counter, dict(
        description="Tasks that reached FAILED (after retries).")),
    "rmt_tasks_retried_total": (Counter, dict(
        description="Task retry attempts (app error or worker death).")),
    "rmt_task_stage_seconds": (Histogram, dict(
        description="Per-task time spent in each lifecycle stage.",
        boundaries=LATENCY_BOUNDARIES, tag_keys=("stage",))),
    # scheduler
    "rmt_scheduler_placements_total": (Counter, dict(
        description="Successful pick_node placements.")),
    "rmt_scheduler_queue_depth": (Gauge, dict(
        description="Dispatch-queue depth (queued + inflight) per node.",
        tag_keys=("node_id",))),
    "rmt_scheduler_pending_args": (Gauge, dict(
        description="Tasks waiting on argument dependencies.")),
    "rmt_scheduler_locality_hits_total": (Counter, dict(
        description="Placements (with locality scoring engaged) that "
                    "landed on a node already holding >= locality_min_"
                    "bytes of the task's argument bytes.")),
    "rmt_scheduler_locality_misses_total": (Counter, dict(
        description="Placements where some node held >= locality_min_"
                    "bytes of the task's args but placement landed "
                    "elsewhere (hard affinity, saturation spillback, or "
                    "the weighted score preferring an idle node).")),
    "rmt_scheduler_locality_bytes_avoided_total": (Counter, dict(
        description="Argument bytes already resident on the chosen node "
                    "at placement time — bytes the data plane never has "
                    "to move because the scheduler went to the data.")),
    "rmt_prefetch_started_total": (Counter, dict(
        description="Argument prestage pulls launched for tasks placed "
                    "on a non-holder (transfer overlaps dispatch-queue "
                    "wait instead of serializing before execution).")),
    "rmt_prefetch_completed_total": (Counter, dict(
        description="Argument prestage pulls that landed (task's args "
                    "were store-resident before a worker asked).")),
    "rmt_sched_local_placed_total": (Counter, dict(
        description="Leaf tasks placed through the agent-local lease "
                    "fast path (bulk-granted credits; no cluster-"
                    "scheduler pass, no per-task head routing).")),
    "rmt_sched_local_spillback_total": (Counter, dict(
        description="Leaf tasks spilled back to the head router: no "
                    "node had lease credits, or a saturated/dead agent "
                    "returned the lease (the two-level raylet spillback "
                    "hop, raylet_client.h:398).")),
    # object / device stores
    "rmt_object_store_bytes": (Gauge, dict(
        description="Shared-memory object store bytes in use per node.",
        tag_keys=("node_id",))),
    "rmt_device_store_bytes": (Gauge, dict(
        description="Accelerator-resident object bytes (device store).")),
    "rmt_device_objects_pinned": (Gauge, dict(
        description="Objects currently resident in this process's "
                    "device (HBM) tier.")),
    "rmt_device_bytes_pinned": (Gauge, dict(
        description="Bytes currently resident in this process's device "
                    "(HBM) tier.")),
    "rmt_device_evictions_total": (Counter, dict(
        description="Device objects demoted out of the HBM tier under "
                    "capacity pressure, by destination tier (shm = the "
                    "host store's create/seal path; the spill plane "
                    "takes over below it).",
        tag_keys=("to_tier",))),
    "rmt_device_zero_copy_hits_total": (Counter, dict(
        description="Device-object reads served zero-copy from the "
                    "live pinned jax.Array (no serialization, no host "
                    "copy).")),
    "rmt_device_ici_transfers_total": (Counter, dict(
        description="Device objects moved device-to-device over the "
                    "jitted same-mesh transfer path instead of the "
                    "host wire.")),
    "rmt_objects_spilled_total": (Counter, dict(
        description="Objects spilled to external storage.")),
    "rmt_objects_spilled_bytes_total": (Counter, dict(
        description="Bytes spilled to external storage.")),
    "rmt_objects_restored_total": (Counter, dict(
        description="Objects restored from external storage.")),
    "rmt_objects_restored_bytes_total": (Counter, dict(
        description="Bytes restored from external storage.")),
    # transfer plane
    "rmt_transfer_bytes": (Histogram, dict(
        description="Object payload size per transfer.",
        boundaries=BYTES_BOUNDARIES, tag_keys=("direction",))),
    "rmt_transfer_latency_seconds": (Histogram, dict(
        description="Wall time per object transfer.",
        boundaries=LATENCY_BOUNDARIES, tag_keys=("direction",))),
    "rmt_transfer_stripe_requests_total": (Counter, dict(
        description="Range (partial-object) requests served — each stripe "
                    "of a striped pull is one.")),
    "rmt_transfer_striped_fetches_total": (Counter, dict(
        description="Pulls that used the striped multi-connection path.")),
    "rmt_transfer_pool_hits_total": (Counter, dict(
        description="Transfer connections reused from the pool "
                    "(handshake amortized).")),
    "rmt_transfer_pool_misses_total": (Counter, dict(
        description="Transfer connections freshly dialed (pool empty "
                    "for the peer, or pooling disabled).")),
    "rmt_transfer_broadcast_waits_total": (Counter, dict(
        description="Multi-destination pulls that waited at the broadcast "
                    "gate for an earlier copy to land (then pulled from a "
                    "new holder instead of the original source).")),
    # fault plane / recovery (the robustness PR's instrument set: every
    # injected fault, retry, failover and degradation is countable, so a
    # recovery regression shows in /metrics, not just tail latency)
    "rmt_faults_injected_total": (Counter, dict(
        description="Faults injected by the deterministic fault plane "
                    "(utils/faults.py), by site and mode.",
        tag_keys=("site", "mode"))),
    "rmt_retry_attempts_total": (Counter, dict(
        description="Retries taken under the unified RetryPolicy, by "
                    "plane (transfer, transfer.dial, push, spill, ...).",
        tag_keys=("plane",))),
    "rmt_retry_exhausted_total": (Counter, dict(
        description="RetryPolicy budgets spent without success, by plane.",
        tag_keys=("plane",))),
    "rmt_transfer_failovers_total": (Counter, dict(
        description="Mid-pull holder failovers: stripe ranges re-pulled "
                    "from an alternate holder after the original stalled "
                    "or died (no lineage re-execution).")),
    "rmt_transfer_checksum_mismatch_total": (Counter, dict(
        description="Payload CRC32 mismatches detected at a "
                    "materialization boundary (stripe completion, "
                    "restore) — treated as object loss, never returned.")),
    "rmt_transfer_auth_failures_total": (Counter, dict(
        description="Transfer dials refused at the authentication "
                    "handshake (non-retryable, distinct from peer death).")),
    # compressed movement plane (wire codecs + quantized collectives):
    # bytes_out/bytes_in is the achieved ratio per codec; the seconds
    # histogram splits encode vs decode so a slow decompressor shows up
    # on the right side of the wire.
    "rmt_transfer_compress_bytes_in_total": (Counter, dict(
        description="Logical (uncompressed) bytes entering a wire codec "
                    "on encode, by codec.",
        tag_keys=("codec",))),
    "rmt_transfer_compress_bytes_out_total": (Counter, dict(
        description="Compressed bytes leaving a wire codec for the wire "
                    "on encode, by codec (out/in = achieved ratio).",
        tag_keys=("codec",))),
    "rmt_transfer_compress_seconds": (Histogram, dict(
        description="Wire-codec CPU time per chunk, by codec and op "
                    "(encode|decode).",
        boundaries=LATENCY_BOUNDARIES, tag_keys=("codec", "op"))),
    "rmt_transfer_compress_skipped_total": (Counter, dict(
        description="Payloads that bypassed wire compression, by reason "
                    "(below_threshold, incompressible probe verdict, "
                    "no_codec negotiated).",
        tag_keys=("reason",))),
    "rmt_collective_quantized_ops_total": (Counter, dict(
        description="Collective ops that quantized shards below f32 "
                    "before the wire (dequantize+accumulate stays f32), "
                    "by op and precision.",
        tag_keys=("op", "precision"))),
    "rmt_spill_errors_total": (Counter, dict(
        description="Spill-storage IO errors (before retry), by op.",
        tag_keys=("op",))),
    "rmt_spill_degraded_total": (Counter, dict(
        description="Times the store entered spill-degraded mode "
                    "(persistent storage failure; objects stay in memory "
                    "under backpressure until a probe succeeds).")),
    "rmt_stale_creates_aborted_total": (Counter, dict(
        description="Unsealed creates swept and aborted after exceeding "
                    "unsealed_create_deadline_s (leaked by a dead "
                    "fetcher).")),
    "rmt_object_directory_prunes_total": (Counter, dict(
        description="Stale GCS object-directory locations pruned after a "
                    "holder reported the object missing.")),
    # pod-scale control plane (hot/cold directory + delta heartbeats):
    # the memory bound and the O(changes) ingress claim are measurable,
    # not just asserted by the pod bench
    "rmt_gcs_directory_hot_rows": (Gauge, dict(
        description="RAM-resident GCS object-directory rows across "
                    "shards (bounded by gcs_directory_hot_max_rows).")),
    "rmt_gcs_directory_cold_rows": (Gauge, dict(
        description="Directory rows spilled to the gcs_storage blob "
                    "surface; only their per-oid index entry stays in "
                    "head RAM.")),
    "rmt_gcs_directory_faults_total": (Counter, dict(
        description="Cold directory batches faulted back into the hot "
                    "tables on a locate/mutation of a spilled row.")),
    "rmt_gcs_directory_spills_total": (Counter, dict(
        description="Directory LRU-tail batches spilled to the "
                    "gcs_storage blob surface by the hot-row cap.")),
    "rmt_heartbeat_resyncs_total": (Counter, dict(
        description="Full-state heartbeat resyncs requested after a "
                    "delta-pong sequence gap or reconnect.")),
    "rmt_leaf_lease_batches_total": (Counter, dict(
        description="lease_batch frames flushed (leaf grants coalesced "
                    "per node per scheduling pass instead of one frame "
                    "per task).")),
    # elastic train plane (checkpoint/restore/resize — the preemption-
    # tolerance instrument set: a training run's durability overhead and
    # recovery behavior are countable, not just visible in wall-clock)
    "rmt_train_checkpoint_saves_total": (Counter, dict(
        description="Durable training checkpoints written (atomic "
                    "tmp+rename with CRC32 manifest), by result.",
        tag_keys=("result",))),
    "rmt_train_checkpoint_restores_total": (Counter, dict(
        description="Training checkpoints loaded for resume, by source "
                    "(latest, fallback after a corrupt/partial newest, "
                    "uri).",
        tag_keys=("source",))),
    "rmt_train_checkpoint_save_seconds": (Histogram, dict(
        description="Training checkpoint save time split by phase: "
                    "'blocking' is the step-blocking slice the trainer "
                    "waits on (enqueue/snapshot), 'drain' is the "
                    "background writer's full durable-write time.",
        boundaries=LATENCY_BOUNDARIES, tag_keys=("phase",))),
    "rmt_train_elastic_resizes_total": (Counter, dict(
        description="Elastic worker-group resizes (rebuild at a new "
                    "world size), by direction (down after node loss, "
                    "up when capacity returned).",
        tag_keys=("direction",))),
    # collectives
    "rmt_collective_latency_seconds": (Histogram, dict(
        description="Wall time per collective op.",
        boundaries=LATENCY_BOUNDARIES, tag_keys=("op",))),
    # liveness
    "rmt_worker_heartbeat_age_seconds": (Gauge, dict(
        description="Seconds since each node's last heartbeat.",
        tag_keys=("node_id",))),
    # worker-process-side (merged into the head registry via the
    # done-reply/flush piggyback channel)
    "rmt_worker_tasks_executed_total": (Counter, dict(
        description="Tasks executed, counted worker-side.")),
    # observability plane itself
    "rmt_timeline_events_dropped_total": (Counter, dict(
        description="Timeline spans evicted from the bounded event ring "
                    "(oldest-first) before they could be dumped; counted "
                    "in whichever process dropped them and merged into "
                    "the head registry via the flush channel.")),
    # log plane (utils/structlog.py)
    "rmt_logs_records_total": (Counter, dict(
        description="Structured log records captured, by source stream "
                    "(logging bridge vs the stdout/stderr tee); counted "
                    "at emit time in whichever process captured them.",
        tag_keys=("stream",))),
    "rmt_logs_bytes_total": (Counter, dict(
        description="Structured log message bytes captured (payload "
                    "text only, excluding the record envelope).")),
    "rmt_logs_dropped_total": (Counter, dict(
        description="Log records dropped oldest-first: buffer_full is "
                    "the worker-side bounded queue overflowing under "
                    "backpressure, retention is head-side LogStore ring "
                    "eviction.",
        tag_keys=("reason",))),
    "rmt_logs_flush_seconds": (Histogram, dict(
        description="Worker-side log batch drain time per flush frame "
                    "(done reply, ticker, or exit flush).",
        boundaries=LATENCY_BOUNDARIES)),
    # serve data plane (serve/: router, replica, proxy, paged KV engine)
    "rmt_serve_requests_total": (Counter, dict(
        description="Requests executed by serve replicas, by deployment "
                    "and result (ok | error).",
        tag_keys=("deployment", "result"))),
    "rmt_serve_request_seconds": (Histogram, dict(
        description="Replica-side service time per request (queue wait "
                    "inside the replica included, routing excluded).",
        boundaries=LATENCY_BOUNDARIES, tag_keys=("deployment",))),
    "rmt_serve_shed_total": (Counter, dict(
        description="Requests shed instead of queued, by reason: "
                    "backpressure_timeout (router deadline expired), "
                    "no_replicas (routing table stayed empty), "
                    "queue_full (proxy 429 on queue depth past "
                    "serve_shed_queue_factor x capacity).",
        tag_keys=("reason",))),
    "rmt_serve_queue_depth": (Gauge, dict(
        description="Cluster-wide ongoing-request depth per deployment, "
                    "from the replica queue-depth snapshots piggybacked "
                    "on the controller's routing table.",
        tag_keys=("deployment",))),
    "rmt_serve_autoscale_errors_total": (Counter, dict(
        description="Replica metrics fetches that failed during an "
                    "autoscale pass (previously swallowed silently).")),
    "rmt_serve_autoscale_decisions_total": (Counter, dict(
        description="Autoscaling decisions that changed a deployment's "
                    "target replica count, by direction (up | down).",
        tag_keys=("direction",))),
    "rmt_serve_kv_pages_in_use": (Gauge, dict(
        description="KV-cache pages currently allocated from the serve "
                    "engine's device page pool (live-token footprint in "
                    "kv_page_tokens units).")),
    "rmt_serve_kv_backpressure_total": (Counter, dict(
        description="Admissions deferred because the KV page pool was "
                    "exhausted (the request stays queued and admits "
                    "when a retiring slot frees pages — backpressure, "
                    "never an allocation failure).")),
    "rmt_serve_cold_start_seconds": (Histogram, dict(
        description="Replica model cold-start time, by weight source "
                    "(init = fresh parameter init, shipped = quantized "
                    "weights from the movement plane).",
        boundaries=LATENCY_BOUNDARIES, tag_keys=("source",))),
    "rmt_serve_replica_placements_total": (Counter, dict(
        description="Replica actor placements, by mode (tier_affine = "
                    "soft node affinity toward a holder of the "
                    "deployment's weights object from the tier-tagged "
                    "locality directory, default = no hint).",
        tag_keys=("mode",))),
    # multi-tenant job plane (core/job_plane.py: quotas, sweeps,
    # preemption — the tenancy instrument set: a leaked job shows up as
    # a non-zero post-sweep gauge, not just missing HBM bytes)
    "rmt_jobs_active": (Gauge, dict(
        description="Jobs with a live ledger (driver + connected "
                    "clients + job_submission drivers).")),
    "rmt_job_sweeps_total": (Counter, dict(
        description="Job-death sweeps completed, by trigger "
                    "(disconnect = client conn closed, watchdog = "
                    "dropped-detach recovery, stop = explicit job stop, "
                    "retry = re-run after an injected sweep error).",
        tag_keys=("trigger",))),
    "rmt_job_preemptions_total": (Counter, dict(
        description="Leaf-lease preemptions: a higher-priority job "
                    "evicted a lower-priority job's leaf task (the "
                    "victim re-queues on a free retry).")),
    "rmt_job_quota_rejections_total": (Counter, dict(
        description="Admissions rejected by a job quota, by resource "
                    "(object_bytes | device_bytes).",
        tag_keys=("resource",))),
    "rmt_job_sweep_seconds": (Histogram, dict(
        description="Wall time per job-death sweep (walk the job's "
                    "directory/refcount rows, free objects, kill "
                    "actors, cancel leases).",
        boundaries=LATENCY_BOUNDARIES)),
    # profiling plane (utils/profiler.py)
    "rmt_proc_cpu_seconds_total": (Counter, dict(
        description="Process CPU seconds (user+system) accumulated, by "
                    "process role; fed by the continuous sampler's "
                    "per-tick delta and by per-task rusage attribution.",
        tag_keys=("role",))),
    "rmt_proc_rss_bytes": (Gauge, dict(
        description="Process resident set size in bytes, sampled by the "
                    "profiling plane (/proc/self/statm; getrusage peak "
                    "where /proc is absent).")),
    "rmt_profile_samples_total": (Counter, dict(
        description="Stack samples captured (one per thread per sampler "
                    "tick or burst tick), counted in whichever process "
                    "captured them.")),
    "rmt_profile_bytes_total": (Counter, dict(
        description="Folded-stack payload bytes drained onto flush "
                    "frames / pongs (the profiling plane's wire cost).")),
    "rmt_profile_dropped_total": (Counter, dict(
        description="Stack samples dropped: agg_full is the bounded "
                    "per-process aggregation map refusing a new distinct "
                    "stack, retention is head-side ProfileStore ring "
                    "eviction.",
        tag_keys=("reason",))),
    # health plane (utils/tsdb.py + core/health.py)
    "rmt_metrics_series_overflow_total": (Counter, dict(
        description="Metric writes folded into the all-__other__ "
                    "overflow series by the registry cardinality guard "
                    "(a NEW distinct tag combo past metrics_max_series_"
                    "per_name), by metric name.",
        tag_keys=("metric",))),
    "rmt_tsdb_dropped_total": (Counter, dict(
        description="Time-series samples the head tsdb refused into a "
                    "dedicated ring: cardinality is a tag combo past "
                    "tsdb_max_series_per_name (the sample folds into "
                    "the per-name __other__ bucket instead).",
        tag_keys=("reason",))),
    "rmt_workers_exited_total": (Counter, dict(
        description="Worker processes that exited (clean or crashed) "
                    "and were reaped by the head's death path; the "
                    "health plane's worker-churn rate signal.")),
    "rmt_health_alerts_total": (Counter, dict(
        description="Health-rule alert transitions (firing + resolved), "
                    "by rule and severity.",
        tag_keys=("rule", "severity"))),
}


def get(name: str) -> Metric:
    """Fetch (constructing if needed) a canonical instrument by name.

    Construction is idempotent: utils.metrics aliases storage when the
    name is already registered, so this is cheap enough for emit sites to
    call per event — but hot paths should still hoist the result."""
    cls, kw = DEFS[name]
    return cls(name, **kw)


def tasks_submitted() -> Counter:
    return get("rmt_tasks_submitted_total")


def tasks_finished() -> Counter:
    return get("rmt_tasks_finished_total")


def tasks_failed() -> Counter:
    return get("rmt_tasks_failed_total")


def tasks_retried() -> Counter:
    return get("rmt_tasks_retried_total")


def task_stage_seconds() -> Histogram:
    return get("rmt_task_stage_seconds")


def scheduler_placements() -> Counter:
    return get("rmt_scheduler_placements_total")


def scheduler_queue_depth() -> Gauge:
    return get("rmt_scheduler_queue_depth")


def scheduler_pending_args() -> Gauge:
    return get("rmt_scheduler_pending_args")


def scheduler_locality_hits() -> Counter:
    return get("rmt_scheduler_locality_hits_total")


def scheduler_locality_misses() -> Counter:
    return get("rmt_scheduler_locality_misses_total")


def scheduler_locality_bytes_avoided() -> Counter:
    return get("rmt_scheduler_locality_bytes_avoided_total")


def prefetch_started() -> Counter:
    return get("rmt_prefetch_started_total")


def prefetch_completed() -> Counter:
    return get("rmt_prefetch_completed_total")


def object_store_bytes() -> Gauge:
    return get("rmt_object_store_bytes")


def device_store_bytes() -> Gauge:
    return get("rmt_device_store_bytes")


def device_objects_pinned() -> Gauge:
    return get("rmt_device_objects_pinned")


def device_bytes_pinned() -> Gauge:
    return get("rmt_device_bytes_pinned")


def device_evictions() -> Counter:
    return get("rmt_device_evictions_total")


def device_zero_copy_hits() -> Counter:
    return get("rmt_device_zero_copy_hits_total")


def device_ici_transfers() -> Counter:
    return get("rmt_device_ici_transfers_total")


def objects_spilled() -> Counter:
    return get("rmt_objects_spilled_total")


def objects_spilled_bytes() -> Counter:
    return get("rmt_objects_spilled_bytes_total")


def objects_restored() -> Counter:
    return get("rmt_objects_restored_total")


def objects_restored_bytes() -> Counter:
    return get("rmt_objects_restored_bytes_total")


def transfer_bytes() -> Histogram:
    return get("rmt_transfer_bytes")


def transfer_latency_seconds() -> Histogram:
    return get("rmt_transfer_latency_seconds")


def transfer_stripe_requests() -> Counter:
    return get("rmt_transfer_stripe_requests_total")


def transfer_striped_fetches() -> Counter:
    return get("rmt_transfer_striped_fetches_total")


def transfer_pool_hits() -> Counter:
    return get("rmt_transfer_pool_hits_total")


def transfer_pool_misses() -> Counter:
    return get("rmt_transfer_pool_misses_total")


def transfer_broadcast_waits() -> Counter:
    return get("rmt_transfer_broadcast_waits_total")


def faults_injected() -> Counter:
    return get("rmt_faults_injected_total")


def retry_attempts() -> Counter:
    return get("rmt_retry_attempts_total")


def retry_exhausted() -> Counter:
    return get("rmt_retry_exhausted_total")


def transfer_failovers() -> Counter:
    return get("rmt_transfer_failovers_total")


def transfer_checksum_mismatch() -> Counter:
    return get("rmt_transfer_checksum_mismatch_total")


def transfer_auth_failures() -> Counter:
    return get("rmt_transfer_auth_failures_total")


def transfer_compress_bytes_in() -> Counter:
    return get("rmt_transfer_compress_bytes_in_total")


def transfer_compress_bytes_out() -> Counter:
    return get("rmt_transfer_compress_bytes_out_total")


def transfer_compress_seconds() -> Histogram:
    return get("rmt_transfer_compress_seconds")


def transfer_compress_skipped() -> Counter:
    return get("rmt_transfer_compress_skipped_total")


def collective_quantized_ops() -> Counter:
    return get("rmt_collective_quantized_ops_total")


def spill_errors() -> Counter:
    return get("rmt_spill_errors_total")


def spill_degraded() -> Counter:
    return get("rmt_spill_degraded_total")


def stale_creates_aborted() -> Counter:
    return get("rmt_stale_creates_aborted_total")


def object_directory_prunes() -> Counter:
    return get("rmt_object_directory_prunes_total")


def gcs_directory_hot_rows() -> Gauge:
    return get("rmt_gcs_directory_hot_rows")


def gcs_directory_cold_rows() -> Gauge:
    return get("rmt_gcs_directory_cold_rows")


def gcs_directory_faults() -> Counter:
    return get("rmt_gcs_directory_faults_total")


def gcs_directory_spills() -> Counter:
    return get("rmt_gcs_directory_spills_total")


def heartbeat_resyncs() -> Counter:
    return get("rmt_heartbeat_resyncs_total")


def leaf_lease_batches() -> Counter:
    return get("rmt_leaf_lease_batches_total")


def sched_local_placed() -> Counter:
    return get("rmt_sched_local_placed_total")


def sched_local_spillback() -> Counter:
    return get("rmt_sched_local_spillback_total")


def train_checkpoint_saves() -> Counter:
    return get("rmt_train_checkpoint_saves_total")


def train_checkpoint_restores() -> Counter:
    return get("rmt_train_checkpoint_restores_total")


def train_checkpoint_save_seconds() -> Histogram:
    return get("rmt_train_checkpoint_save_seconds")


def train_elastic_resizes() -> Counter:
    return get("rmt_train_elastic_resizes_total")


def collective_latency_seconds() -> Histogram:
    return get("rmt_collective_latency_seconds")


def worker_heartbeat_age_seconds() -> Gauge:
    return get("rmt_worker_heartbeat_age_seconds")


def worker_tasks_executed() -> Counter:
    return get("rmt_worker_tasks_executed_total")


def timeline_events_dropped() -> Counter:
    return get("rmt_timeline_events_dropped_total")


def logs_records() -> Counter:
    return get("rmt_logs_records_total")


def logs_bytes() -> Counter:
    return get("rmt_logs_bytes_total")


def logs_dropped() -> Counter:
    return get("rmt_logs_dropped_total")


def logs_flush_seconds() -> Histogram:
    return get("rmt_logs_flush_seconds")


def proc_cpu_seconds() -> Counter:
    return get("rmt_proc_cpu_seconds_total")


def proc_rss_bytes() -> Gauge:
    return get("rmt_proc_rss_bytes")


def serve_requests() -> Counter:
    return get("rmt_serve_requests_total")


def serve_request_seconds() -> Histogram:
    return get("rmt_serve_request_seconds")


def serve_shed() -> Counter:
    return get("rmt_serve_shed_total")


def serve_queue_depth() -> Gauge:
    return get("rmt_serve_queue_depth")


def serve_autoscale_errors() -> Counter:
    return get("rmt_serve_autoscale_errors_total")


def serve_autoscale_decisions() -> Counter:
    return get("rmt_serve_autoscale_decisions_total")


def serve_kv_pages_in_use() -> Gauge:
    return get("rmt_serve_kv_pages_in_use")


def serve_kv_backpressure() -> Counter:
    return get("rmt_serve_kv_backpressure_total")


def serve_cold_start_seconds() -> Histogram:
    return get("rmt_serve_cold_start_seconds")


def serve_replica_placements() -> Counter:
    return get("rmt_serve_replica_placements_total")


def jobs_active() -> Gauge:
    return get("rmt_jobs_active")


def job_sweeps() -> Counter:
    return get("rmt_job_sweeps_total")


def job_preemptions() -> Counter:
    return get("rmt_job_preemptions_total")


def job_quota_rejections() -> Counter:
    return get("rmt_job_quota_rejections_total")


def job_sweep_seconds() -> Histogram:
    return get("rmt_job_sweep_seconds")


def profile_samples() -> Counter:
    return get("rmt_profile_samples_total")


def profile_bytes() -> Counter:
    return get("rmt_profile_bytes_total")


def profile_dropped() -> Counter:
    return get("rmt_profile_dropped_total")


def metrics_series_overflow() -> Counter:
    return get("rmt_metrics_series_overflow_total")


def tsdb_dropped() -> Counter:
    return get("rmt_tsdb_dropped_total")


def workers_exited() -> Counter:
    return get("rmt_workers_exited_total")


def health_alerts() -> Counter:
    return get("rmt_health_alerts_total")
