"""Node manager: per-node worker pool, dispatch queue, and chip accounting.

The raylet analog (src/ray/raylet/node_manager.h:143) restricted to what a
single-host TPU node needs:
  - WorkerPool semantics from worker_pool.h:104,349,427 — prestart, pooled
    idle workers, dedicated (non-returning) workers for actors;
  - LocalTaskManager dispatch (local_task_manager.cc:99,256): leased tasks
    queue here until an idle worker and node resources are available;
  - TPU chip assignment: the node tracks free chip indices and passes a
    ``TPU_VISIBLE_CHIPS`` value with each lease — the accelerator-isolation
    analog of CUDA_VISIBLE_DEVICES assignment (_private/utils.py:349-362).

Runs inside the driver process; worker processes are real OS processes
spawned via multiprocessing (spawn context, so children never inherit the
driver's TPU/jax state).
"""

from __future__ import annotations

import os
import subprocess
import sys
import threading
from collections import deque
from typing import Callable, Dict, List, Optional, Set

from ..config import Config
from ..ids import NodeID, WorkerID
from .object_store import NodeObjectStore
from .resources import NodeResources, Resources, TPU
from .task_spec import TaskSpec


class WorkerHandle:
    __slots__ = ("worker_id", "proc", "conn", "node_id", "ready", "idle",
                 "known_fns", "known_classes", "actor_id", "inflight",
                 "lease_resources", "visible_chips", "pending_msgs",
                 "_alive_checked_at")

    def __init__(self, worker_id: WorkerID, proc, node_id: NodeID):
        self.worker_id = worker_id
        self.proc = proc  # subprocess.Popen
        self.conn = None  # set when the worker dials back in
        self.node_id = node_id
        self.ready = False
        self.idle = False
        self.known_fns: Set[bytes] = set()
        self.known_classes: Set[bytes] = set()
        self.actor_id: Optional[bytes] = None  # dedicated actor worker
        self.inflight: Dict[bytes, TaskSpec] = {}  # task_id -> spec
        self.lease_resources: Optional[Resources] = None
        self.visible_chips: Optional[List[int]] = None
        self.pending_msgs: List[dict] = []  # queued until registration
        self._alive_checked_at = 0.0

    def alive(self) -> bool:
        # proc.poll() is a waitpid syscall; on the dispatch hot path it
        # dominated task throughput. Death is ALSO detected by the router
        # seeing the pipe EOF, so a short-TTL cache here only delays this
        # secondary check, never correctness.
        if self.proc.returncode is not None:
            return False
        import time

        now = time.monotonic()
        if now - self._alive_checked_at < 0.2:
            return True
        self._alive_checked_at = now
        return self.proc.poll() is None


class NodeManager:
    def __init__(
        self,
        node_id: NodeID,
        resources: NodeResources,
        store_name: str,
        config: Config,
        on_worker_started: Callable[[WorkerHandle], None],
        socket_path: str = "",
        authkey_hex: str = "",
    ):
        self.socket_path = socket_path
        self.authkey_hex = authkey_hex
        self.node_id = node_id
        self.resources = resources
        self.config = config
        self.store = NodeObjectStore(store_name, config, create=True)
        self.store_name = store_name
        self.workers: Dict[WorkerID, WorkerHandle] = {}
        self.idle_workers: deque = deque()
        self.queue: deque = deque()  # TaskSpec leased to this node
        self.starting = 0
        self.alive = True
        self._on_worker_started = on_worker_started
        self._lock = threading.RLock()
        total_chips = int(resources.total.get(TPU))
        self.free_chips: List[int] = list(range(total_chips))

    # -- worker pool ----------------------------------------------------------
    def start_worker(self, dedicated: bool = False) -> WorkerHandle:
        """Spawn one worker process (WorkerPool::StartWorkerProcess analog,
        worker_pool.h:427): a fresh interpreter launched with `-m ...worker_main`
        that dials back into the runtime's Unix socket — the same
        exec-then-connect handshake the raylet uses with its workers
        (raylet_client.h:236 registration over the raylet socket)."""
        worker_id = WorkerID.from_random()
        env = dict(os.environ)
        env.update({
            "RMT_WORKER_ID": worker_id.hex(),
            "RMT_NODE_ID": self.node_id.hex(),
            "RMT_STORE_NAME": self.store_name,
            "RMT_SOCKET": self.socket_path,
            "RMT_AUTHKEY": self.authkey_hex,
            "RMT_INLINE_LIMIT": str(self.config.max_direct_call_object_size),
            # Workers default to CPU jax — they never see the driver's TPU
            # (the driver's JAX_PLATFORMS is deliberately NOT inherited).
            # Set RMT_WORKER_JAX_PLATFORMS=tpu on the driver to spawn
            # TPU-capable workers for tasks/actors leased chips.
            "JAX_PLATFORMS": env.get("RMT_WORKER_JAX_PLATFORMS", "cpu"),
        })
        if env["JAX_PLATFORMS"] == "cpu":
            # CPU workers skip the TPU plugin bootstrap some images run from
            # sitecustomize at interpreter start (it imports jax + registers a
            # PJRT backend, ~2s); dropping the trigger env vars cuts worker
            # spawn from ~2s to ~0.2s. TPU-platform workers keep them.
            for var in self.config.cpu_worker_env_drop.split(","):
                if var:
                    env.pop(var.strip(), None)
        proc = subprocess.Popen(
            [sys.executable, "-m",
             "ray_memory_management_tpu.core.worker_main"],
            env=env, close_fds=True,
        )
        handle = WorkerHandle(worker_id, proc, self.node_id)
        if dedicated:
            # claimed for an actor before registration: never enters the
            # idle pool (dedicated workers, worker_pool.h:446)
            handle.actor_id = b"__pending__"
        with self._lock:
            self.workers[worker_id] = handle
            if not dedicated:
                self.starting += 1
        self._on_worker_started(handle)
        return handle

    def prestart(self, count: Optional[int] = None) -> None:
        n = self.config.worker_prestart_count if count is None else count
        for _ in range(n):
            if len(self.workers) < self.config.max_workers_per_node:
                self.start_worker()

    def on_worker_ready(self, handle: WorkerHandle) -> None:
        with self._lock:
            handle.ready = True
            self.starting = max(0, self.starting - 1)
            if handle.actor_id is None:
                handle.idle = True
                self.idle_workers.append(handle)

    def remove_worker(self, handle: WorkerHandle) -> None:
        with self._lock:
            self.workers.pop(handle.worker_id, None)
            try:
                self.idle_workers.remove(handle)
            except ValueError:
                pass
            if not handle.ready:
                self.starting = max(0, self.starting - 1)
            if handle.lease_resources is not None:
                self.resources.free(handle.lease_resources)
                handle.lease_resources = None
            if handle.visible_chips:
                self.free_chips.extend(handle.visible_chips)
                handle.visible_chips = None

    # -- dispatch -------------------------------------------------------------
    def submit(self, spec: TaskSpec) -> None:
        with self._lock:
            self.queue.append(spec)

    def try_dispatch(
        self, send: Callable[[WorkerHandle, TaskSpec], None]
    ) -> None:
        """Match queued tasks to idle workers + resources; start workers on
        demand (DispatchScheduledTasksToWorkers, local_task_manager.cc:99)."""
        with self._lock:
            if not self.alive:
                return
            made_progress = True
            while made_progress and self.queue:
                made_progress = False
                spec = self.queue[0]
                # PG tasks draw from their bundle's reservation, which the
                # scheduler already deducted from this node's pool
                req = Resources(
                    {} if spec.placement is not None else spec.resources
                )
                if not req.fits_in(self.resources.available):
                    break  # head-of-line: wait for running tasks to finish
                handle = None
                while self.idle_workers:
                    cand = self.idle_workers.popleft()
                    if cand.alive() and cand.ready:
                        handle = cand
                        break
                if handle is None:
                    can_start = (
                        len(self.workers) < self.config.max_workers_per_node
                    )
                    if can_start and self.starting == 0:
                        self.start_worker()
                    break
                self.queue.popleft()
                handle.idle = False
                handle.inflight[spec.task_id] = spec
                self.resources.allocate(req)
                handle.lease_resources = req
                n_chips = int(req.get(TPU))
                if n_chips > 0:
                    handle.visible_chips = [
                        self.free_chips.pop() for _ in range(n_chips)
                    ]
                made_progress = True
                send(handle, spec)

    def finish_task(self, handle: WorkerHandle, task_id: bytes) -> None:
        """Free the lease and return the worker to the pool."""
        with self._lock:
            handle.inflight.pop(task_id, None)
            if handle.lease_resources is not None:
                self.resources.free(handle.lease_resources)
                handle.lease_resources = None
            if handle.visible_chips:
                self.free_chips.extend(handle.visible_chips)
                handle.visible_chips = None
            if handle.actor_id is None and handle.alive():
                handle.idle = True
                # LIFO: reuse the hottest worker — on small tasks this keeps
                # one process warm (caches, branch state) and lets dispatch
                # batches coalesce on its pipe instead of round-robining
                # wakeups across the whole pool
                self.idle_workers.appendleft(handle)

    def dedicate_to_actor(self, handle: WorkerHandle, actor_id: bytes,
                          req: Resources, chips: Optional[List[int]]) -> None:
        """Convert a pooled worker into a dedicated actor worker; the lease
        lasts for the actor's lifetime (dedicated workers, worker_pool.h:446)."""
        with self._lock:
            handle.actor_id = actor_id
            handle.idle = False
            try:
                self.idle_workers.remove(handle)
            except ValueError:
                pass
            self.resources.allocate(req)
            handle.lease_resources = req
            handle.visible_chips = chips

    def take_chips(self, n: int) -> Optional[List[int]]:
        with self._lock:
            if len(self.free_chips) < n:
                return None
            return [self.free_chips.pop() for _ in range(n)]

    def shutdown(self, unlink_store: bool = True) -> None:
        with self._lock:
            self.alive = False
            workers = list(self.workers.values())
        for h in workers:
            if h.conn is not None:
                try:
                    h.conn.send({"type": "shutdown"})
                except (OSError, BrokenPipeError):
                    pass
        for h in workers:
            try:
                h.proc.wait(timeout=1.0)
            except subprocess.TimeoutExpired:
                h.proc.terminate()
            if h.conn is not None:
                try:
                    h.conn.close()
                except OSError:
                    pass
        self.store.close(unlink=unlink_store)
