"""Node manager: per-node worker pool, dispatch queue, and chip accounting.

The raylet analog (src/ray/raylet/node_manager.h:143) restricted to what a
single-host TPU node needs:
  - WorkerPool semantics from worker_pool.h:104,349,427 — prestart, pooled
    idle workers, dedicated (non-returning) workers for actors;
  - LocalTaskManager dispatch (local_task_manager.cc:99,256): leased tasks
    queue here until an idle worker and node resources are available;
  - TPU chip assignment: the node tracks free chip indices and passes a
    ``TPU_VISIBLE_CHIPS`` value with each lease — the accelerator-isolation
    analog of CUDA_VISIBLE_DEVICES assignment (_private/utils.py:349-362).

Runs inside the driver process; worker processes are real OS processes
spawned via multiprocessing (spawn context, so children never inherit the
driver's TPU/jax state).
"""

from __future__ import annotations

import os
import subprocess
import sys
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Set

from ..config import Config
from ..ids import NodeID, WorkerID
from .object_store import NodeObjectStore
from .resources import CPU, NodeResources, Resources, TPU
from .task_spec import TaskSpec

# shared zero request for placement-group tasks (their resources were
# already deducted at bundle reservation); Resources is immutable-by-
# convention so one instance serves every dispatch round
_EMPTY_REQ = Resources({})


class WorkerHandle:
    __slots__ = ("worker_id", "proc", "conn", "node_id", "ready", "idle",
                 "known_fns", "known_classes", "actor_id", "inflight",
                 "lease_resources", "visible_chips", "pending_msgs",
                 "death_processed", "send_lock", "steal_pending",
                 "re_inflight", "conda_key", "spawned_at",
                 "_alive_checked_at", "device_mesh")

    def __init__(self, worker_id: WorkerID, proc, node_id: NodeID):
        self.worker_id = worker_id
        self.proc = proc  # subprocess.Popen
        self.conn = None  # set when the worker dials back in
        self.node_id = node_id
        self.ready = False
        self.idle = False
        self.death_processed = False
        self.steal_pending = False  # a steal request is in flight
        # serializes task-msg build+enqueue per worker: the fn_blob
        # carried-once decision (known_fns) must stay atomic with the
        # enqueue order now that dispatch sends outside the node lock
        self.send_lock = threading.Lock()
        self.known_fns: Set[bytes] = set()
        self.known_classes: Set[bytes] = set()
        self.actor_id: Optional[bytes] = None  # dedicated actor worker
        # set when this worker's process IS a conda env's python: it only
        # serves tasks carrying the same env key (worker_pool.h:446
        # dedicated runtime-env workers)
        self.conda_key: Optional[str] = None
        self.inflight: Dict[bytes, TaskSpec] = {}  # task_id -> spec
        self.re_inflight = 0  # inflight tasks carrying a runtime_env
        self.lease_resources: Optional[Resources] = None
        self.visible_chips: Optional[List[int]] = None
        self.pending_msgs: List[dict] = []  # queued until registration
        self.spawned_at = 0.0  # set at spawn; boot latency at ready
        self._alive_checked_at = 0.0
        # mesh fingerprint the worker reported with its first device
        # seal: the ICI-route decision compares it with the consumer's
        self.device_mesh: Optional[tuple] = None

    def alive(self) -> bool:
        # proc.poll() is a waitpid syscall; on the dispatch hot path it
        # dominated task throughput. Death is ALSO detected by the router
        # seeing the pipe EOF, so a short-TTL cache here only delays this
        # secondary check, never correctness.
        if self.proc.returncode is not None:
            return False
        import time

        now = time.monotonic()
        if now - self._alive_checked_at < 0.2:
            return True
        self._alive_checked_at = now
        return self.proc.poll() is None


class _PendingProc:
    """Placeholder process for a WorkerHandle registered before its OS
    process exists (start_worker registers first so a fast bootstrapped
    fork can never answer before the bookkeeping is visible)."""

    returncode = None

    def poll(self):
        return None

    def terminate(self) -> None:
        pass

    def kill(self) -> None:
        pass

    def wait(self, timeout=None) -> int:
        return 0


def package_env() -> Dict[str, str]:
    """A copy of this process's environment with PYTHONPATH arranged so
    spawned processes can import this package from any cwd (the checkout is
    the install; there is no pip-installed copy to fall back on)."""
    env = dict(os.environ)
    pkg_parent = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    parts = [p for p in env.get("PYTHONPATH", "").split(os.pathsep) if p]
    if pkg_parent not in parts:
        env["PYTHONPATH"] = os.pathsep.join([pkg_parent] + parts)
    return env


def build_worker_env(worker_id_hex: str, node_id_hex: str, store_name: str,
                     socket_path: str, authkey_hex: str,
                     config: Config) -> Dict[str, str]:
    """Environment for a spawned worker process — shared by the local
    worker pool and the remote node agent so the two can never diverge.

    Workers default to CPU jax — they never see the driver's TPU (the
    driver's JAX_PLATFORMS is deliberately NOT inherited). Set
    RMT_WORKER_JAX_PLATFORMS=tpu on the driver to spawn TPU-capable
    workers for tasks/actors leased chips."""
    env = package_env()
    env.update({
        "RMT_WORKER_ID": worker_id_hex,
        "RMT_NODE_ID": node_id_hex,
        "RMT_STORE_NAME": store_name,
        "RMT_SOCKET": socket_path,
        "RMT_AUTHKEY": authkey_hex,
        "RMT_INLINE_LIMIT": str(config.max_direct_call_object_size),
        "RMT_LOG_TO_DRIVER": "1" if config.log_to_driver else "0",
        # pipelined done-reply batching (worker _ReplySender adaptive
        # flush window); explicit so local pool and agent spawn agree
        "RMT_REPLY_FLUSH_WINDOW_S": str(config.reply_flush_window_s),
        "RMT_REPLY_FLUSH_MAX": str(config.reply_flush_max),
        "JAX_PLATFORMS": env.get("RMT_WORKER_JAX_PLATFORMS", "cpu"),
    })
    if env["JAX_PLATFORMS"] == "cpu":
        # CPU workers skip the TPU plugin bootstrap some images run from
        # sitecustomize at interpreter start (it imports jax + registers a
        # PJRT backend, ~2s); dropping the trigger env vars cuts worker
        # spawn from ~2s to ~0.2s. TPU-platform workers keep them.
        for var in config.cpu_worker_env_drop.split(","):
            if var:
                env.pop(var.strip(), None)
    return env


def spawn_worker_process(env: Dict[str, str], config: Config,
                         bootstrap: Optional[dict] = None,
                         on_cold_bootstrap=None,
                         python_exe: Optional[str] = None):
    """Start one worker process: forked from the warm zygote when the
    worker is CPU-platform (ms instead of a cold interpreter), else — and
    whenever the zygote is unavailable — a fresh ``subprocess.Popen``.
    TPU-platform workers always cold-spawn: the PJRT plugin must register
    at interpreter startup, which a fork of the (deliberately
    TPU-ignorant) zygote cannot provide.

    ``bootstrap`` is a message the worker should process immediately at
    startup (the dedicated-worker startup token, worker_pool.h:446). The
    fork path hands it to the child in memory; the cold path cannot, so
    ``on_cold_bootstrap`` is invoked BEFORE the process is created — the
    caller queues the message for delivery at registration, race-free
    because the worker cannot register before it exists."""
    if python_exe is None and config.worker_fork_server \
            and env.get("JAX_PLATFORMS") == "cpu":
        from . import zygote

        z = zygote.get_global()
        if z is not None:
            proc = z.spawn(env, bootstrap)
            if proc is not None:
                return proc
    if bootstrap is not None and on_cold_bootstrap is not None:
        on_cold_bootstrap()
    # python_exe: a conda env's interpreter — always a cold spawn (the
    # zygote is the WRONG interpreter); package_env's PYTHONPATH makes
    # this package importable from the foreign python
    return subprocess.Popen(
        [python_exe or sys.executable, "-m",
         "ray_memory_management_tpu.core.worker_main"],
        env=env, close_fds=True,
    )


class NodeManager:
    def __init__(
        self,
        node_id: NodeID,
        resources: NodeResources,
        store_name: str,
        config: Config,
        on_worker_started: Callable[[WorkerHandle], None],
        socket_path: str = "",
        authkey_hex: str = "",
    ):
        self.socket_path = socket_path
        self.authkey_hex = authkey_hex
        self.node_id = node_id
        self.resources = resources
        self.config = config
        self.store = NodeObjectStore(store_name, config, create=True)
        self.store_name = store_name
        self._on_worker_started = on_worker_started
        total_chips = int(resources.total.get(TPU))
        self.free_chips: List[int] = list(range(total_chips))
        self._init_pool_state()

    def _init_pool_state(self) -> None:
        """Worker-pool bookkeeping shared with RemoteNodeManager, which
        bypasses ``__init__`` (it has no local store to create). Every
        pool field MUST live here, not in ``__init__``: a field added
        there surfaces as an AttributeError the first time an inherited
        pool method runs against a remote node."""
        self.workers: Dict[WorkerID, WorkerHandle] = {}
        self.idle_workers: deque = deque()
        # pool workers currently holding a lease; pipelining candidates
        # (max_tasks_in_flight_per_worker, the reference's small-task
        # pipelining knob on the direct task transport)
        self.busy_pool: Set[WorkerHandle] = set()
        self.queue: deque = deque()  # TaskSpec leased to this node
        self.starting = 0
        self.alive = True
        self._lock = threading.RLock()
        # dedicated conda-env workers, one warm pool per env key: their
        # process is the env's python, so they never mix with the main
        # pool (worker_pool.h:446 dedicated runtime-env workers)
        self.conda_idle: Dict[str, deque] = {}
        self._conda_starting: Set[str] = set()
        # phase accounting (scale bench): spawn-return -> worker-ready
        self.boot_seconds = 0.0
        self.boot_count = 0
        # leaf-lease pool (decentralized control plane): a bulk credit
        # grant that lets the router place constraint-free leaf tasks on
        # this node WITHOUT the full pick_node/locality pass, and lets a
        # remote node's agent pick the worker itself (the two-level
        # lease protocol the ClusterScheduler docstring reserves;
        # raylet_client.h:398). Credits resolve once per node: the flag,
        # or 2x the node's CPU count; negative disables leaf leasing.
        slots = self.config.leaf_lease_slots
        if slots == 0:
            slots = max(2, int(self.resources.total.get(CPU)) * 2)
        # construction runs outside __init__ (RemoteNodeManager path),
        # so take the lock to honor the annotations lexically
        with self._lock:
            self.leaf_credits = max(0, slots)  # guarded-by: _lock
            # local-mode markers: leaf tasks riding the ordinary
            # dispatch queue, so finish_task knows to return the credit
            self.leaf_local: Set[bytes] = set()  # guarded-by: _lock
            # remote-mode inflight: specs handed to the node's AGENT for
            # agent-local worker placement (lease_exec); drained by the
            # node-death handler exactly like the dispatch queue
            self.leaf_inflight: Dict[bytes, TaskSpec] = {}  # guarded-by: _lock
            # fn ids whose blob already rode a lease_exec to this
            # node's agent (the agent caches blobs; per-node ships-once)
            self.lease_known_fns: Set[bytes] = set()  # guarded-by: _lock

    # -- worker pool ----------------------------------------------------------
    def start_conda_worker(self, conda_spec, conda_key: str) -> None:
        """Spawn one dedicated worker whose process is the conda env's
        python. Env resolution/creation can take minutes (conda env
        create), so it runs on a daemon thread — never on the dispatch
        path; the worker joins ``conda_idle[key]`` at registration and
        the next dispatch round matches it."""
        with self._lock:
            if conda_key in self._conda_starting:
                return
            self._conda_starting.add(conda_key)

        def resolve_and_spawn():
            # _conda_starting holds the key until the worker REGISTERS
            # (cleared in on_worker_ready/remove_worker) so one worker at
            # a time starts per env; on any failure here the key clears
            # and the failure is loud
            handle = None
            try:
                from .. import runtime_env as re_mod

                python_exe = re_mod.conda_python(conda_spec)
                worker_id = WorkerID.from_random()
                env = build_worker_env(
                    worker_id.hex(), self.node_id.hex(), self.store_name,
                    self.socket_path, self.authkey_hex, self.config)
                handle = WorkerHandle(worker_id, _PendingProc(),
                                      self.node_id)
                handle.conda_key = conda_key
                with self._lock:
                    self.workers[worker_id] = handle
                    self.starting += 1
                self._on_worker_started(handle)
                handle.proc = spawn_worker_process(env, self.config,
                                                   python_exe=python_exe)
            except Exception as e:  # noqa: BLE001
                from ..utils import events

                events.emit(
                    "CONDA_ENV_FAILED",
                    f"conda env {conda_spec!r} unavailable: {e!r}; "
                    "tasks requiring it will wait",
                    severity=events.ERROR, source="worker_pool")
                with self._lock:
                    self._conda_starting.discard(conda_key)
                if handle is not None:
                    self.remove_worker(handle)
                return
            if not self.alive:
                try:
                    handle.proc.terminate()
                except Exception:  # noqa: BLE001
                    pass

        threading.Thread(target=resolve_and_spawn, daemon=True,
                         name=f"conda-spawn-{conda_key[:6]}").start()

    def start_worker(self, dedicated: bool = False,
                     bootstrap: Optional[dict] = None,
                     on_handle=None,
                     conda_spec=None) -> WorkerHandle:
        """Spawn one worker process (WorkerPool::StartWorkerProcess analog,
        worker_pool.h:427): a worker that dials back into the runtime's
        Unix socket — the same exec-then-connect handshake the raylet uses
        with its workers (raylet_client.h:236 registration over the raylet
        socket). A ``bootstrap`` message rides the spawn itself when the
        fork path is available (startup token, worker_pool.h:446), else it
        is queued for delivery at registration. ``conda_spec`` makes the
        worker a dedicated conda-env process (cold spawn under the env's
        python; resolution/creation may block the caller — actor creation
        tolerates this the way it tolerates pip installs).

        The handle is registered — and ``on_handle`` (caller bookkeeping
        that must be visible before any reply from the worker) runs —
        BEFORE the process exists: a bootstrapped fork can answer within
        milliseconds, racing any bookkeeping done after this returns."""
        python_exe = None
        if conda_spec is not None:
            from .. import runtime_env as re_mod

            python_exe = re_mod.conda_python(conda_spec)
        worker_id = WorkerID.from_random()
        env = build_worker_env(worker_id.hex(), self.node_id.hex(),
                               self.store_name, self.socket_path,
                               self.authkey_hex, self.config)
        handle = WorkerHandle(worker_id, _PendingProc(), self.node_id)
        if dedicated:
            # claimed for an actor before registration: never enters the
            # idle pool (dedicated workers, worker_pool.h:446)
            handle.actor_id = b"__pending__"
        with self._lock:
            self.workers[worker_id] = handle
            if not dedicated:
                self.starting += 1
        self._on_worker_started(handle)
        if on_handle is not None:
            on_handle(handle)

        def queue_bootstrap():
            # cold spawn: deliver through registration (pending_msgs are
            # flushed when the worker dials in). Runs before the process
            # exists, so the flush cannot have happened yet.
            handle.pending_msgs.append(bootstrap)

        # BEFORE the spawn: a bootstrapped fork can register before this
        # returns, and on_worker_ready skips the boot sample at 0
        handle.spawned_at = time.monotonic()
        handle.proc = spawn_worker_process(env, self.config, bootstrap,
                                           queue_bootstrap,
                                           python_exe=python_exe)
        if not self.alive:
            # remove_node ran while we were spawning: its terminate loop
            # saw only the _PendingProc placeholder, so the real process
            # would outlive its node — kill it; the runtime's unborn-worker
            # sweep then reports the death
            try:
                handle.proc.terminate()
            except Exception:  # noqa: BLE001
                pass
        return handle

    def prestart(self, count: Optional[int] = None) -> None:
        n = self.config.worker_prestart_count if count is None else count
        for _ in range(n):
            if len(self.workers) < self.config.max_workers_per_node:
                self.start_worker()

    def on_worker_ready(self, handle: WorkerHandle) -> None:
        with self._lock:
            handle.ready = True
            if handle.spawned_at:
                self.boot_seconds += time.monotonic() - handle.spawned_at
                self.boot_count += 1
            self.starting = max(0, self.starting - 1)
            if handle.conda_key is not None:
                self._conda_starting.discard(handle.conda_key)
            if handle.actor_id is None:
                handle.idle = True
                if handle.conda_key is not None:
                    self.conda_idle.setdefault(
                        handle.conda_key, deque()).append(handle)
                else:
                    self.idle_workers.append(handle)

    def remove_worker(self, handle: WorkerHandle) -> None:
        with self._lock:
            self.workers.pop(handle.worker_id, None)
            self.busy_pool.discard(handle)
            try:
                self.idle_workers.remove(handle)
            except ValueError:
                pass
            if handle.conda_key is not None:
                self._conda_starting.discard(handle.conda_key)
                try:
                    self.conda_idle.get(handle.conda_key,
                                        deque()).remove(handle)
                except ValueError:
                    pass
            if not handle.ready:
                self.starting = max(0, self.starting - 1)
            if handle.lease_resources is not None:
                self.resources.free(handle.lease_resources)
                handle.lease_resources = None
            if handle.visible_chips:
                self.free_chips.extend(handle.visible_chips)
                handle.visible_chips = None

    # -- dispatch -------------------------------------------------------------
    def submit(self, spec: TaskSpec) -> None:
        # fault plane, control side: an injected dispatch failure models
        # a dropped/late control frame; the runtime's dispatch RetryPolicy
        # (_submit_to_node) is what recovers it
        from ..utils import faults

        act = faults.fire("control.dispatch")
        if act is not None:
            if act.mode == "stall":
                act.sleep()
            else:
                act.raise_()
        with self._lock:
            if not self.alive:
                # a dead node's queue is drained exactly once by its
                # death handler; accepting a spec here would wedge it
                # forever ("not retryable" on THIS node — the dispatcher
                # re-places it on a live one)
                from ..exceptions import NodeDeadError

                raise NodeDeadError(
                    f"node {self.node_id.hex()[:12]} is dead "
                    "(not retryable)")
            self.queue.append(spec)

    def backlog(self) -> int:
        """Tasks leased to this node but not yet executing: the dispatch
        queue plus everything pipelined behind a running task on a worker
        pipe. This — not ``len(queue)`` — is the node's pending-demand
        signal (autoscaler scale-up, scheduler least-queued balancing);
        pipelining would otherwise drain the queue and blind both."""
        with self._lock:
            return len(self.queue) + sum(
                len(h.inflight) - 1
                for h in self.busy_pool if len(h.inflight) > 1
            )

    # -- leaf leases ----------------------------------------------------------
    def submit_leaf(self, spec: TaskSpec, build_msg=None) -> bool:
        """Admit one leaf task against this node's lease-credit pool.

        Local nodes just ride the ordinary dispatch queue (the win is
        skipping the router's pick_node/locality pass, not the queue);
        the credit is returned by finish_task via the leaf_local marker.
        Returns False when the pool is saturated (the caller counts a
        spillback and falls through to the full scheduling path) or the
        node is dead. ``build_msg`` is only used by the remote override.
        """
        with self._lock:
            if not self.alive or self.leaf_credits <= 0:
                return False
            self.leaf_credits -= 1
            self.leaf_local.add(spec.task_id)
            self.queue.append(spec)
        return True

    def flush_leases(self) -> list:
        """Local nodes dispatch leaf tasks straight onto their own queue
        in submit_leaf — there is no grant buffer to flush and nothing
        can fail, so the router's per-pass flush is a no-op here. The
        remote override ships the buffered lease_batch frames and
        returns any specs a dead channel bounced."""
        return []

    def finish_leaf(self, task_id: bytes) -> Optional[TaskSpec]:
        """Settle an agent-placed leaf task (done reply, spillback, or
        worker death): return its credit and hand back the spec. Local
        leaf tasks live in handle.inflight instead, so this returns None
        for them — finish_task settles their credit."""
        with self._lock:
            spec = self.leaf_inflight.pop(task_id, None)
            if spec is not None:
                self.leaf_credits += 1
            return spec

    def cancel_leaf(self, task_id: bytes) -> None:
        """Job sweep: nothing to do locally — a local leaf task is
        either in the dispatch queue (the sweep drops it there) or in a
        worker handle's inflight map (the sweep's victim scan terminates
        that worker). The remote override asks the agent to kill the
        pool worker only IT can name."""

    def release_leaf(self, task_id: bytes) -> None:
        """Return the credit of a LOCAL leaf task whose worker died
        before finish_task could run (the death handler cleared the
        handle's inflight map wholesale)."""
        with self._lock:
            if task_id in self.leaf_local:
                self.leaf_local.discard(task_id)
                self.leaf_credits += 1

    def take_leaf_inflight(self) -> Dict[bytes, TaskSpec]:
        """Node death: drain every agent-placed leaf task for retry
        elsewhere (the lease-revocation half of the dead-flag-then-drain
        ordering — the dead flag is already set, so no new lease_exec
        can land behind this drain)."""
        with self._lock:
            out = dict(self.leaf_inflight)
            self.leaf_inflight.clear()
            self.leaf_credits += len(out)
            return out

    def preempt_leaf(self, victim_ok):
        """Priority preemption over this node's LOCAL leaf pool: evict
        one leaf task for which ``victim_ok(task_id)`` is True (the
        runtime passes a lower-priority-job predicate; it must not block
        — it runs under the node lock).

        Prefers a QUEUED victim — removed from the dispatch queue with
        its credit returned synchronously, zero wasted work; falls back
        to a RUNNING victim whose worker holds nothing else (the caller
        terminates the worker and the ordinary death path returns the
        credit and re-queues the task). Returns ``("queued", spec)``,
        ``("running", (task_id, handle))``, or None."""
        with self._lock:
            if not self.alive:
                return None
            for i, spec in enumerate(self.queue):
                if spec.task_id in self.leaf_local \
                        and victim_ok(spec.task_id):
                    del self.queue[i]
                    self.leaf_local.discard(spec.task_id)
                    self.leaf_credits += 1
                    return ("queued", spec)
            for h in self.workers.values():
                if h.actor_id is not None or len(h.inflight) != 1:
                    continue
                tid = next(iter(h.inflight))
                if tid in self.leaf_local and victim_ok(tid):
                    return ("running", (tid, h))
            return None

    def try_dispatch(
        self, send: Callable[[WorkerHandle, TaskSpec], None]
    ) -> None:
        """Match queued tasks to idle workers + resources; start workers on
        demand (DispatchScheduledTasksToWorkers, local_task_manager.cc:99).

        Two dispatch modes:
          - lease: an idle worker takes the task and its resource request is
            allocated from the node pool;
          - pipeline: when no idle worker/resources are left, a task whose
            request exactly matches a busy pool worker's held lease rides
            that lease, queued on the worker's pipe behind its current task
            (the reference pipelines small tasks onto held leases the same
            way — max_tasks_in_flight_per_worker on the direct transport).
            The worker still executes serially; pipelining only hides the
            owner↔worker turnaround latency.
        """
        to_send: List[tuple] = []
        with self._lock:
            if not self.alive:
                return
            while self.queue:
                spec = self.queue[0]
                # PG tasks draw from their bundle's reservation, which the
                # scheduler already deducted from this node's pool
                req = (_EMPTY_REQ if spec.placement is not None
                       else spec.req)
                handle = None
                lease = False
                conda_spec = (spec.runtime_env or {}).get("conda") \
                    if spec.runtime_env else None
                if conda_spec is not None:
                    # conda tasks only run on dedicated workers whose
                    # process IS the env's python — never the main pool
                    ckey = spec._conda_key
                    if ckey is None:
                        from .. import runtime_env as re_mod

                        ckey = re_mod.conda_env_key(conda_spec)
                        spec._conda_key = ckey
                    if req.fits_in(self.resources.available):
                        pool = self.conda_idle.get(ckey)
                        while pool:
                            cand = pool.popleft()
                            if cand.alive() and cand.ready:
                                handle = cand
                                lease = True
                                break
                    if handle is None:
                        # spawn ONLY when no warm worker exists for this
                        # env (a resource wait with a warm worker must
                        # not breed processes); resolution/creation runs
                        # off-thread and the worker joins conda_idle at
                        # registration (one in flight per key — the
                        # _conda_starting guard clears at ready/death)
                        if not self.conda_idle.get(ckey):
                            self.start_conda_worker(conda_spec, ckey)
                        break  # head-of-line: wait for the env worker
                elif req.fits_in(self.resources.available):
                    while self.idle_workers:
                        cand = self.idle_workers.popleft()
                        if cand.alive() and cand.ready:
                            handle = cand
                            lease = True
                            break
                    if handle is None:
                        self._start_workers_for_backlog(req)
                if handle is None:
                    handle = self._pick_pipeline_worker(spec, req)
                    if handle is None:
                        break  # head-of-line: wait for a lease to free
                self.queue.popleft()
                handle.idle = False
                handle.inflight[spec.task_id] = spec
                if spec.runtime_env:
                    handle.re_inflight += 1
                if lease:
                    self.resources.allocate(req)
                    handle.lease_resources = req
                    n_chips = int(req.get(TPU))
                    if n_chips > 0:
                        handle.visible_chips = [
                            self.free_chips.pop() for _ in range(n_chips)
                        ]
                    if handle.actor_id is None:
                        self.busy_pool.add(handle)
                to_send.append((handle, spec))
        # sends happen outside the node lock: a slow pipe write must not
        # block completions (finish_task) or other dispatchers
        for handle, spec in to_send:
            send(handle, spec)

    def pick_steal_victim(self) -> Optional[WorkerHandle]:
        """When a worker sits idle with an empty queue while another's pipe
        carries pipelined backlog, steal it back (the reference's direct-
        transport work stealing): the victim returns its not-yet-started
        tasks and the owner re-dispatches them to the idle capacity.
        Returns the most-backlogged eligible worker, marking it
        steal_pending (cleared when its 'stolen' reply lands)."""
        with self._lock:
            if self.queue or not any(
                    h.idle and h.ready for h in self.idle_workers):
                return None
            best = None
            for cand in self.busy_pool:
                # the lease-fits check keeps stealing productive: a stolen
                # task can only land on the idle worker if a lease of the
                # same shape is available — otherwise it would just
                # re-pipeline onto a busy worker (steal/re-pipeline churn)
                if (len(cand.inflight) > 1 and not cand.steal_pending
                        and cand.alive()
                        and cand.lease_resources is not None
                        and cand.lease_resources.fits_in(
                            self.resources.available)):
                    if best is None or len(cand.inflight) > \
                            len(best.inflight):
                        best = cand
            if best is not None:
                best.steal_pending = True
            return best

    def return_stolen(self, handle: WorkerHandle, task_ids) -> list:
        """Take stolen tasks back from ``handle``: re-queue their specs at
        the FRONT (they were dispatched first) and release the worker's
        lease if its pipeline drained. Returns the requeued specs."""
        specs = []
        with self._lock:
            handle.steal_pending = False
            for tid in task_ids:
                spec = handle.inflight.pop(tid, None)
                if spec is not None:
                    specs.append(spec)
                    if spec.runtime_env:
                        handle.re_inflight -= 1
                    # the blob-carrying dispatch may itself be stolen, so
                    # this worker can no longer be assumed to know the fn
                    handle.known_fns.discard(spec.fn_id)
            for spec in reversed(specs):
                self.queue.appendleft(spec)
            if not handle.inflight and handle.lease_resources is not None:
                self.resources.free(handle.lease_resources)
                handle.lease_resources = None
                if handle.visible_chips:
                    self.free_chips.extend(handle.visible_chips)
                    handle.visible_chips = None
                self.busy_pool.discard(handle)
                if handle.actor_id is None and handle.alive():
                    handle.idle = True
                    self.idle_workers.appendleft(handle)
        return specs

    def _start_workers_for_backlog(self, req: Resources) -> None:
        """Start enough workers to cover the queued backlog, bounded by the
        resource slots the node could actually lease (the reference
        prestarts workers per dispatch round the same way,
        worker_pool.h:349 PrestartWorkers)."""
        can_start = self.config.max_workers_per_node - len(self.workers)
        if can_start <= self.starting:
            return
        # how many copies of `req` fit in what's still available (pure
        # arithmetic: this runs on every dispatch round with an empty idle
        # pool, so no trial-allocation loop)
        slots = 64
        avail = self.resources.available
        for name, amount in req.to_dict().items():
            if amount > 0:
                slots = min(slots, int(avail.get(name) / amount))
        want = min(len(self.queue), slots, can_start) - self.starting
        for _ in range(max(0, want)):
            self.start_worker()

    def _pick_pipeline_worker(
        self, spec: TaskSpec, req: Resources
    ) -> Optional[WorkerHandle]:
        """A busy pool worker whose held lease matches ``req`` exactly and
        whose pipe backlog is under the pipelining depth.

        runtime_env tasks never pipeline (in either direction): applying an
        env mutates process-wide state (os.environ, cwd, sys.path), which is
        only safe while the worker executes strictly serially — and a
        blocked task can grow a second executor thread (_TaskDispatcher)."""
        depth = self.config.max_tasks_in_flight_per_worker
        # only small tasks pipeline (the reference's pipelining likewise
        # targets the high-rate small-task path): a request over 1 CPU
        # signals heavy work, where serializing behind a busy worker loses
        # more than the owner round trip costs — those wait for a lease
        # (or for the autoscaler, which sees them via backlog())
        if (depth <= 1 or spec.placement is not None or req.get(TPU) > 0
                or req.get(CPU) > 1.0 or spec.runtime_env):
            return None
        best = None
        best_depth = depth
        for cand in self.busy_pool:
            # steal_pending workers are off-limits: a dispatch racing the
            # in-flight steal could omit a fn_blob the steal is about to
            # take back (known_fns is only reconciled at the stolen reply)
            if (len(cand.inflight) < best_depth
                    and cand.lease_resources == req
                    and cand.ready and cand.alive()
                    and not cand.steal_pending
                    and cand.re_inflight == 0):
                best = cand
                best_depth = len(cand.inflight)
        return best

    def finish_task(self, handle: WorkerHandle, task_id: bytes) -> None:
        """Release the task; free the lease and return the worker to the
        pool once its pipeline drains."""
        with self._lock:
            spec = handle.inflight.pop(task_id, None)
            if spec is not None and spec.runtime_env:
                handle.re_inflight -= 1
            if task_id in self.leaf_local:
                # local-mode leaf task: its lease credit frees with it
                self.leaf_local.discard(task_id)
                self.leaf_credits += 1
            if handle.inflight:
                return  # pipelined tasks still riding this lease
            if handle.lease_resources is not None:
                self.resources.free(handle.lease_resources)
                handle.lease_resources = None
            if handle.visible_chips:
                self.free_chips.extend(handle.visible_chips)
                handle.visible_chips = None
            self.busy_pool.discard(handle)
            if handle.actor_id is None and handle.alive():
                handle.idle = True
                if handle.conda_key is not None:
                    # back to its env's warm dedicated pool
                    self.conda_idle.setdefault(
                        handle.conda_key, deque()).appendleft(handle)
                    return
                # LIFO: reuse the hottest worker — on small tasks this keeps
                # one process warm (caches, branch state) and lets dispatch
                # batches coalesce on its pipe instead of round-robining
                # wakeups across the whole pool
                self.idle_workers.appendleft(handle)

    def dedicate_to_actor(self, handle: WorkerHandle, actor_id: bytes,
                          req: Resources, chips: Optional[List[int]]) -> None:
        """Convert a pooled worker into a dedicated actor worker; the lease
        lasts for the actor's lifetime (dedicated workers, worker_pool.h:446)."""
        with self._lock:
            handle.actor_id = actor_id
            handle.idle = False
            self.busy_pool.discard(handle)
            try:
                self.idle_workers.remove(handle)
            except ValueError:
                pass
            self.resources.allocate(req)
            handle.lease_resources = req
            handle.visible_chips = chips

    def take_chips(self, n: int) -> Optional[List[int]]:
        with self._lock:
            if len(self.free_chips) < n:
                return None
            return [self.free_chips.pop() for _ in range(n)]

    def shutdown(self, unlink_store: bool = True) -> None:
        with self._lock:
            self.alive = False
            workers = list(self.workers.values())
        for h in workers:
            if h.conn is not None:
                try:
                    h.conn.send({"type": "shutdown"})
                except (OSError, BrokenPipeError):
                    pass
        for h in workers:
            try:
                h.proc.wait(timeout=1.0)
            except subprocess.TimeoutExpired:
                h.proc.terminate()
            if h.conn is not None:
                try:
                    h.conn.close()
                except OSError:
                    pass
        self.store.close(unlink=unlink_store)
