"""Device object store: a first-class HBM tier of the object plane.

The north-star capability (BASELINE.json: "ObjectRefs pinned in TPU
HBM"): the reference's plasma store is host-shm only (SURVEY.md — no GPU
object store in the snapshot), so this is net-new, designed per
SURVEY.md §7:

  - XLA owns HBM: a device object IS a live ``jax.Array`` pinned by the
    process that produced it (the per-host arena of XLA buffers). There
    is no HBM mmap analog, so device objects are process-local by
    construction; the host-process-per-TPU-host model makes that the
    natural ownership unit.
  - Same-process consumers get the buffer back zero-copy (actor-to-actor
    handoff without leaving HBM); a ``consume=True`` last-reader get
    TAKES the entry so the caller can donate the buffer into its pjit
    computation — transformer-block-sized handoffs allocate nothing.
  - The tier has a budget (``device_store_capacity_bytes``): putting
    past it demotes least-recently-used UNPINNED entries to the host
    shm tier through a caller-supplied demote callback (the existing
    NodeObjectStore create/seal path, optionally bf16-downcast via the
    PR 7 codec envelopes); the spill plane takes over below shm.
    HBM → host shm → spill, each tier evicting into the next.
  - Cross-process consumers trigger on-demand materialization: the
    owning process copies device→host and writes the serialized value
    into its node's shm store, after which the normal object plane
    (shm / DCN push-pull) takes over. The device copy stays pinned for
    local readers until budget pressure or the ref count drops it.
  - A dead owner process loses its device objects; recovery is lineage
    re-execution, same as any lost object.

Observability: every resident/pinned-bytes change lands in the
``rmt_device_objects_pinned`` / ``rmt_device_bytes_pinned`` gauges,
zero-copy reads bump ``rmt_device_zero_copy_hits_total``, demotions
bump ``rmt_device_evictions_total{to_tier}``; the demotion path carries
the injectable ``device.evict`` fault site (an injected error DEFERS
the eviction — the object stays resident and readable; pressure causes
slowness, never loss).
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..utils import events, faults


def is_device_array(value: Any) -> bool:
    """True for a jax.Array (CPU-backed arrays also benefit from
    zero-copy process-local pinning). One shared detector with the
    serializer so the put and serialize paths always agree."""
    from ..serialization import _is_jax_array

    return _is_jax_array(value)


def resolve_capacity(config) -> int:
    """Device-tier budget in bytes for this process. Explicit flag wins;
    0 = auto from the backend's device memory stats (60% of the first
    local device's reported limit — the rest belongs to the program's
    own compute), falling back to 1 GiB when the backend reports
    nothing (CPU-backed jax arrays in tier-1). Negative disables
    eviction (unbounded pinning)."""
    cap = int(getattr(config, "device_store_capacity_bytes", 0) or 0)
    if cap:
        return cap
    try:
        import jax

        stats = jax.local_devices()[0].memory_stats() or {}
        limit = int(stats.get("bytes_limit")
                    or stats.get("bytes_reservable_limit") or 0)
        if limit > 0:
            return int(limit * 0.6)
    except Exception:  # noqa: BLE001 — stats are a hint, not a contract
        pass
    return 1 << 30


class _Entry:
    __slots__ = ("array", "nbytes", "pins")

    def __init__(self, array: Any, nbytes: int):
        self.array = array
        self.nbytes = nbytes
        self.pins = 0


def _entry_nbytes(array: Any) -> int:
    try:
        return int(array.nbytes)
    except Exception:  # noqa: BLE001
        return 0


class DeviceObjectStore:
    """Process-local refcounted HBM pin table with LRU demotion.

    ``on_demote(oid, array) -> bool`` writes the host copy (node-store
    create/seal) and returns True on success; it runs OUTSIDE the store
    lock (serialization + shm writes must never convoy readers). A
    failed or faulted demotion re-inserts the entry at the cold end —
    eviction is deferred, never lossy.
    """

    def __init__(self, capacity_bytes: int = -1,
                 on_demote: Optional[Callable[[bytes, Any], bool]] = None):
        self._lock = threading.Lock()
        # MRU at the end; OrderedDict gives O(1) LRU via move_to_end
        self._objects: "OrderedDict[bytes, _Entry]" = OrderedDict()  # guarded-by: _lock
        self._total = 0  # guarded-by: _lock
        self._bytes_avoided = 0  # guarded-by: _lock
        self.capacity_bytes = int(capacity_bytes)
        self._on_demote = on_demote
        self._victim_rank: Optional[Callable[[bytes], int]] = None

    # -- configuration --------------------------------------------------------
    def set_demoter(self, on_demote: Callable[[bytes, Any], bool],
                    capacity_bytes: Optional[int] = None) -> None:
        self._on_demote = on_demote
        if capacity_bytes is not None:
            self.capacity_bytes = int(capacity_bytes)

    def set_victim_rank(self,
                        rank: Optional[Callable[[bytes], int]]) -> None:
        """Optional job-aware demotion order: ``rank(oid)`` returns a
        sort key and LOWER demotes first (the runtime passes the owning
        job's priority, so a low-priority tenant's cold pins leave HBM
        before a high-priority tenant's, with plain LRU breaking ties
        within one rank). None restores pure LRU."""
        self._victim_rank = rank

    # -- core tier operations -------------------------------------------------
    def put(self, object_id: bytes, array: Any) -> List[bytes]:
        """Pin an array; returns the oids demoted to make room (empty
        when under budget, eviction is disabled, or nothing was
        evictable)."""
        n = _entry_nbytes(array)
        with self._lock:
            prev = self._objects.pop(object_id, None)
            if prev is not None:
                self._total -= prev.nbytes
            self._objects[object_id] = _Entry(array, n)
            self._total += n
        demoted = self._evict_over_budget(keep=object_id)
        self._publish_gauges()
        return demoted

    def get(self, object_id: bytes) -> Optional[Any]:
        """Zero-copy read of the live array; bumps LRU recency and the
        zero-copy counters."""
        with self._lock:
            entry = self._objects.get(object_id)
            if entry is None:
                return None
            self._objects.move_to_end(object_id)
            self._bytes_avoided += entry.nbytes
            array = entry.array
        try:
            from . import metrics_defs as mdefs

            mdefs.device_zero_copy_hits().inc()
        except Exception:  # noqa: BLE001 — metrics never fail a read
            pass
        return array

    def take(self, object_id: bytes) -> Optional[Any]:
        """Consume: remove the entry and hand the caller the live array
        (the last-reader donation path — the store drops its reference
        so the consuming pjit computation can donate the buffer). The
        object is no longer readable through this store afterwards."""
        with self._lock:
            entry = self._objects.pop(object_id, None)
            if entry is None:
                return None
            self._total -= entry.nbytes
            array = entry.array
            entry.array = None
        self._publish_gauges()
        return array

    # -- refcount pinning ------------------------------------------------------
    def pin(self, object_id: bytes) -> bool:
        """Make an entry ineligible for demotion (a reader holding the
        live buffer across a demotion would see it vanish mid-use)."""
        with self._lock:
            entry = self._objects.get(object_id)
            if entry is None:
                return False
            entry.pins += 1
            return True

    def unpin(self, object_id: bytes) -> None:
        with self._lock:
            entry = self._objects.get(object_id)
            if entry is not None and entry.pins > 0:
                entry.pins -= 1

    def pin_count(self, object_id: bytes) -> int:
        with self._lock:
            entry = self._objects.get(object_id)
            return entry.pins if entry is not None else 0

    # -- eviction --------------------------------------------------------------
    def _evict_over_budget(self, keep: Optional[bytes] = None) -> List[bytes]:
        """Demote LRU unpinned entries until the tier fits its budget.
        Victims are chosen and unlinked under the lock, but demotion IO
        (serialize + host-store write) runs outside it."""
        if self.capacity_bytes < 0 or self._on_demote is None:
            return []
        rank = self._victim_rank
        order: Optional[Dict[bytes, int]] = None
        if rank is not None:
            with self._lock:
                cands = [oid for oid, e in self._objects.items()
                         if e.pins == 0 and oid != keep]
            # ranks resolve OUTSIDE the store lock: the callback reads
            # runtime/GCS state, and nesting those locks under this one
            # would invert the runtime -> store lock order
            order = {}
            for oid in cands:
                try:
                    order[oid] = rank(oid)
                except Exception:  # noqa: BLE001 — rank is advisory
                    order[oid] = 1 << 62
        victims: List[Tuple[bytes, _Entry]] = []
        with self._lock:
            if self._total <= self.capacity_bytes:
                return []
            walk = list(self._objects)
            if order is not None:
                # stable sort: LRU order survives within one rank tier;
                # entries added since the snapshot demote last
                walk.sort(key=lambda o: order.get(o, 1 << 62))
            for oid in walk:
                if self._total <= self.capacity_bytes:
                    break
                entry = self._objects[oid]
                if entry.pins > 0 or oid == keep:
                    continue
                del self._objects[oid]
                self._total -= entry.nbytes
                victims.append((oid, entry))
        demoted: List[bytes] = []
        for oid, entry in victims:
            if self._demote_one(oid, entry):
                demoted.append(oid)
            else:
                # deferred, not lost: back in at the cold end so the
                # next put retries it first
                with self._lock:
                    self._objects[oid] = entry
                    self._objects.move_to_end(oid, last=False)
                    self._total += entry.nbytes
        return demoted

    def _demote_one(self, oid: bytes, entry: _Entry) -> bool:
        act = faults.fire("device.evict")
        if act is not None:
            if act.mode == "stall":
                act.sleep()
            elif act.mode in ("error", "drop"):
                events.emit(
                    "DEVICE_EVICT_DEFERRED",
                    f"demotion of {oid.hex()[:12]} deferred by injected "
                    f"{act.mode}", severity=events.WARNING,
                    source="device_store")
                return False
        try:
            ok = bool(self._on_demote(oid, entry.array))
        except Exception as e:  # noqa: BLE001 — demotion IO must not lose data
            events.emit(
                "DEVICE_EVICT_DEFERRED",
                f"demotion of {oid.hex()[:12]} failed ({e!r}); object "
                "stays device-resident", severity=events.WARNING,
                source="device_store")
            return False
        if ok:
            try:
                from . import metrics_defs as mdefs

                mdefs.device_evictions().inc(tags={"to_tier": "shm"})
            except Exception:  # noqa: BLE001
                pass
        return ok

    # -- introspection ---------------------------------------------------------
    def contains(self, object_id: bytes) -> bool:
        with self._lock:
            return object_id in self._objects

    def delete(self, object_id: bytes) -> None:
        with self._lock:
            entry = self._objects.pop(object_id, None)
            if entry is not None:
                self._total -= entry.nbytes
        self._publish_gauges()

    def ids(self) -> List[bytes]:
        with self._lock:
            return list(self._objects)

    def nbytes(self, object_id: bytes) -> Optional[int]:
        with self._lock:
            entry = self._objects.get(object_id)
            return entry.nbytes if entry is not None else None

    def total_bytes(self) -> int:
        with self._lock:
            return self._total

    def count(self) -> int:
        with self._lock:
            return len(self._objects)

    def bytes_avoided(self) -> int:
        """Serialization/copy bytes the zero-copy path never paid (one
        full payload per zero-copy read)."""
        with self._lock:
            return self._bytes_avoided

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "objects": len(self._objects),
                "bytes": self._total,
                "pinned": sum(1 for e in self._objects.values() if e.pins),
                "capacity_bytes": self.capacity_bytes,
                "bytes_avoided": self._bytes_avoided,
            }

    def clear(self) -> None:
        with self._lock:
            self._objects.clear()
            self._total = 0
        self._publish_gauges()

    def _publish_gauges(self) -> None:
        try:
            from . import metrics_defs as mdefs

            with self._lock:
                count, total = len(self._objects), self._total
            mdefs.device_objects_pinned().set(float(count))
            mdefs.device_bytes_pinned().set(float(total))
        except Exception:  # noqa: BLE001 — gauges never fail the data path
            pass
