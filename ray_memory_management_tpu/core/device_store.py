"""Device object store: ObjectRefs pinning accelerator-resident arrays.

The north-star capability (BASELINE.json: "ObjectRefs pinned in TPU
HBM"): the reference's plasma store is host-shm only (SURVEY.md — no GPU
object store in the snapshot), so this is net-new, designed per
SURVEY.md §7:

  - XLA owns HBM: a device object IS a live ``jax.Array`` pinned by the
    process that produced it (the per-host arena of XLA buffers). There
    is no HBM mmap analog, so device objects are process-local by
    construction; the host-process-per-TPU-host model makes that the
    natural ownership unit.
  - Same-process consumers get the buffer back zero-copy (actor-to-actor
    handoff without leaving HBM).
  - Cross-process consumers trigger on-demand materialization: the
    owning process copies device→host and writes the serialized value
    into its node's shm store (the spill tier), after which the normal
    object plane (shm / DCN push-pull) takes over. The device copy stays
    pinned for local readers until the ref count drops.
  - A dead owner process loses its device objects; recovery is lineage
    re-execution, same as any lost object.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional


def is_device_array(value: Any) -> bool:
    """True for a jax.Array (CPU-backed arrays also benefit from
    zero-copy process-local pinning). One shared detector with the
    serializer so the put and serialize paths always agree."""
    from ..serialization import _is_jax_array

    return _is_jax_array(value)


class DeviceObjectStore:
    """Process-local pin table: object id -> live jax.Array."""

    def __init__(self):
        self._lock = threading.Lock()
        self._objects: Dict[bytes, Any] = {}

    def put(self, object_id: bytes, array: Any) -> None:
        with self._lock:
            self._objects[object_id] = array

    def get(self, object_id: bytes) -> Optional[Any]:
        with self._lock:
            return self._objects.get(object_id)

    def contains(self, object_id: bytes) -> bool:
        with self._lock:
            return object_id in self._objects

    def delete(self, object_id: bytes) -> None:
        with self._lock:
            self._objects.pop(object_id, None)

    def ids(self) -> List[bytes]:
        with self._lock:
            return list(self._objects)

    def nbytes(self, object_id: bytes) -> Optional[int]:
        with self._lock:
            arr = self._objects.get(object_id)
        if arr is None:
            return None
        try:
            return int(arr.nbytes)
        except Exception:
            return None

    def clear(self) -> None:
        with self._lock:
            self._objects.clear()
