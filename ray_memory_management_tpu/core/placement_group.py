"""Placement groups: gang reservation of resource bundles across nodes.

Mirrors the reference's PG stack — public API python/ray/util/placement_group.py:129,
GCS state machine gcs_placement_group_manager.h:173, bundle policies PACK/
SPREAD/STRICT_PACK/STRICT_SPREAD (bundle_scheduling_policy.h:82-109), and
bundle resource commit/return (placement_group_resource_manager.h). Tasks and
actors scheduled with a PG strategy draw from the bundle's reserved resources
rather than the node's free pool.

TPU note (net-new vs the reference): bundles requesting TPU chips are placed
with the same policies, and STRICT_PACK maps naturally to "one ICI domain" —
the topology-aware extension point the reference lacks (SURVEY.md §7).
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import Future
from typing import Dict, List, Optional, Tuple

from ..exceptions import PlacementGroupError
from ..ids import NodeID, ObjectID, PlacementGroupID
from .resources import Resources

PENDING = "PENDING"
CREATED = "CREATED"
REMOVED = "REMOVED"


class _Bundle:
    __slots__ = ("index", "total", "available", "node_id")

    def __init__(self, index: int, total: Resources):
        self.index = index
        self.total = total
        self.available = Resources.from_fixed(total.fixed())
        self.node_id: Optional[NodeID] = None


class PlacementGroup:
    """User-facing handle (util/placement_group.py PlacementGroup)."""

    def __init__(self, pg_id: bytes, bundles: List[Dict[str, float]],
                 strategy: str, name: str = ""):
        self.id = pg_id
        self.bundle_specs = bundles
        self.strategy = strategy
        self.name = name

    def ready(self):
        """ObjectRef that resolves when all bundles are reserved — used as
        ``get(pg.ready())`` like the reference."""
        from .. import _worker_context
        from .object_ref import ObjectRef

        rt = _worker_context.get_runtime()
        if rt is None:
            raise PlacementGroupError(
                "pg.ready() is driver-side; use pg.wait() inside workers")
        mgr = _manager(rt)
        return ObjectRef(mgr.ready_object(self.id), rt)

    def wait(self, timeout_seconds: float = 30.0) -> bool:
        from .. import _worker_context

        rt = _worker_context.get_runtime()
        if rt is not None:
            return _manager(rt).wait_created(self.id, timeout_seconds)
        proxy = _worker_context.get_proxy()
        if proxy is None:
            raise PlacementGroupError("not initialized")
        return proxy.wait_placement_group(self.id, timeout_seconds)

    @property
    def bundle_count(self) -> int:
        return len(self.bundle_specs)

    def __reduce__(self):
        return (PlacementGroup,
                (self.id, self.bundle_specs, self.strategy, self.name))


class _PGState:
    __slots__ = ("pg", "bundles", "state", "created_event", "ready_oid")

    def __init__(self, pg: PlacementGroup):
        self.pg = pg
        self.bundles = [
            _Bundle(i, Resources(spec)) for i, spec in
            enumerate(pg.bundle_specs)
        ]
        self.state = PENDING
        self.created_event = threading.Event()
        self.ready_oid: Optional[bytes] = None


class PlacementGroupManager:
    def __init__(self, runtime):
        self.runtime = runtime
        self._lock = threading.RLock()
        self._groups: Dict[bytes, _PGState] = {}
        self._pending: List[bytes] = []
        # key (task/actor id) -> (pg_id, bundle_index, Resources)
        self._allocations: Dict[bytes, Tuple[bytes, int, Resources]] = {}

    # -- creation -------------------------------------------------------------
    def create(self, bundles: List[Dict[str, float]], strategy: str,
               name: str = "") -> PlacementGroup:
        for b in bundles:
            if not b or all(v == 0 for v in b.values()):
                raise PlacementGroupError(f"empty bundle in {bundles}")
        pg_id = PlacementGroupID.from_random().binary()
        pg = PlacementGroup(pg_id, bundles, strategy, name)
        state = _PGState(pg)
        with self._lock:
            self._groups[pg_id] = state
            self._pending.append(pg_id)
        self.runtime.gcs.placement_groups[pg_id] = {
            "name": name, "strategy": strategy, "bundles": bundles,
            "state": PENDING,
        }
        self.retry_pending()
        return pg

    def retry_pending(self) -> None:
        """Try to place all pending groups (two-phase prepare/commit — the
        GCS PG scheduler loop, gcs_placement_group_scheduler.h)."""
        with self._lock:
            pending = list(self._pending)
        for pg_id in pending:
            self._try_place(pg_id)

    def _try_place(self, pg_id: bytes) -> None:
        with self._lock:
            state = self._groups.get(pg_id)
            if state is None or state.state != PENDING:
                return
            reqs = [b.total for b in state.bundles]
            placement = self.runtime.scheduler.place_bundles(
                reqs, state.pg.strategy
            )
            if placement is None:
                return
            # commit: deduct each bundle from its node's free pool
            for bundle, node_id in zip(state.bundles, placement):
                self.runtime.scheduler.allocate(node_id, bundle.total)
                bundle.node_id = node_id
            state.state = CREATED
            self._pending.remove(pg_id)
            self.runtime.gcs.placement_groups[pg_id]["state"] = CREATED
            state.created_event.set()
            if state.ready_oid is not None:
                self._resolve_ready(state)

    def _resolve_ready(self, state: _PGState) -> None:
        rt = self.runtime
        with rt._lock:
            rt.memory_store[state.ready_oid] = _READY_PAYLOAD
            fut = rt.futures.get(state.ready_oid)
            if fut is None:
                rt.futures[state.ready_oid] = fut = Future()
        if not fut.done():
            fut.set_result(True)

    def ready_object(self, pg_id: bytes) -> bytes:
        from .. import serialization as ser

        global _READY_PAYLOAD
        _READY_PAYLOAD = ser.dumps(True)
        rt = self.runtime
        with self._lock:
            state = self._groups[pg_id]
            if state.ready_oid is None:
                state.ready_oid = ObjectID.for_put().binary()
                with rt._lock:
                    rt.futures[state.ready_oid] = Future()
                if state.state == CREATED:
                    self._resolve_ready(state)
        return state.ready_oid

    def wait_created(self, pg_id: bytes, timeout: float) -> bool:
        with self._lock:
            state = self._groups.get(pg_id)
        if state is None:
            raise PlacementGroupError("unknown placement group")
        if state.state == REMOVED:
            return False  # removed groups will never be created
        return state.created_event.wait(timeout)

    def state(self, pg_id: bytes) -> Optional[str]:
        with self._lock:
            st = self._groups.get(pg_id)
            return st.state if st is not None else None

    # -- scheduling integration ----------------------------------------------
    def acquire(self, pg_id: bytes, bundle_index: int, req: Resources,
                key: bytes) -> Optional[Tuple[NodeID, int]]:
        """Reserve ``req`` out of a bundle for ``key`` (a task or actor id);
        idempotent per key (an actor restart re-resolves without
        double-counting). Returns (node, bundle_index) or None if the PG is
        still pending / bundle exhausted."""
        with self._lock:
            held = self._allocations.get(key)
            if held is not None:
                held_pg, idx, _req = held
                return self._groups[held_pg].bundles[idx].node_id, idx
            state = self._groups.get(pg_id)
            if state is None:
                raise PlacementGroupError("unknown placement group")
            if state.state != CREATED:
                return None
            candidates = (
                state.bundles if bundle_index == -1
                else [state.bundles[bundle_index]]
            )
            for bundle in candidates:
                if req.fits_in(bundle.available):
                    bundle.available = bundle.available - req
                    self._allocations[key] = (pg_id, bundle.index, req)
                    return bundle.node_id, bundle.index
            return None

    def release_key(self, key: bytes) -> None:
        with self._lock:
            held = self._allocations.pop(key, None)
            if held is None:
                return
            pg_id, idx, req = held
            state = self._groups.get(pg_id)
            if state is None or state.state == REMOVED:
                return
            bundle = state.bundles[idx]
            bundle.available = bundle.available + req

    def remove(self, pg_id: bytes) -> None:
        """Return bundle resources to the nodes (bundle return phase)."""
        with self._lock:
            state = self._groups.get(pg_id)
            if state is None or state.state == REMOVED:
                return
            if state.state == CREATED:
                for bundle in state.bundles:
                    if bundle.node_id is not None:
                        self.runtime.scheduler.free(bundle.node_id, bundle.total)
            else:
                if pg_id in self._pending:
                    self._pending.remove(pg_id)
            state.state = REMOVED
            self.runtime.gcs.placement_groups[pg_id]["state"] = REMOVED

    def table(self) -> Dict[bytes, dict]:
        return dict(self.runtime.gcs.placement_groups)


_READY_PAYLOAD = b""


def _manager(runtime) -> PlacementGroupManager:
    if runtime.pg_manager is None:
        runtime.pg_manager = PlacementGroupManager(runtime)
    return runtime.pg_manager


# -- runtime hooks -----------------------------------------------------------
def resolve_pg_node(runtime, spec) -> Optional[NodeID]:
    """Resolve a task's PG strategy to a node, drawing from the bundle.
    Called by Runtime._schedule; returns None to park the task until the PG
    is created or the bundle frees up."""
    strategy = spec.strategy
    if isinstance(strategy, object) and hasattr(strategy, "placement_group"):
        pg = strategy.placement_group
        pg_id = pg.id if isinstance(pg, PlacementGroup) else pg
        bundle_index = strategy.placement_group_bundle_index
    else:
        pg_id, bundle_index = spec.placement[:2]
    mgr = _manager(runtime)
    req = Resources(spec.resources)
    got = mgr.acquire(pg_id, bundle_index, req, key=spec.task_id)
    if got is None:
        return None
    node_id, idx = got
    # the bundle already reserved node resources; node dispatch must not
    # double-count them (placement set => zero node-level request)
    spec.placement = (pg_id, idx)
    return node_id


def resolve_pg_node_for_actor(runtime, spec) -> Optional[NodeID]:
    pg_id, bundle_index = spec.placement[:2]
    mgr = _manager(runtime)
    req = Resources(spec.resources)
    deadline = time.monotonic() + runtime.config.worker_lease_timeout_s
    while time.monotonic() < deadline:
        got = mgr.acquire(pg_id, bundle_index, req, key=spec.actor_id)
        if got is not None:
            node_id, idx = got
            spec.placement = (pg_id, idx)
            return node_id
        time.sleep(0.02)
    return None


def placement_group(bundles: List[Dict[str, float]], strategy: str = "PACK",
                    name: str = "") -> PlacementGroup:
    """Create a placement group (util/placement_group.py:129)."""
    from .. import _worker_context

    rt = _worker_context.get_runtime()
    if rt is not None:
        return _manager(rt).create(bundles, strategy, name)
    proxy = _worker_context.get_proxy()
    if proxy is None:
        raise PlacementGroupError("not initialized")
    pg_id = proxy.create_placement_group(bundles, strategy, name)
    return PlacementGroup(pg_id, bundles, strategy, name)


def remove_placement_group(pg: PlacementGroup) -> None:
    from .. import _worker_context

    pg_id = pg.id if isinstance(pg, PlacementGroup) else pg
    rt = _worker_context.get_runtime()
    if rt is not None:
        _manager(rt).remove(pg_id)
        return
    proxy = _worker_context.get_proxy()
    if proxy is None:
        raise PlacementGroupError("not initialized")
    proxy.remove_placement_group(pg_id)


def placement_group_table() -> Dict[str, dict]:
    from .. import _worker_context

    rt = _worker_context.get_runtime()
    if rt is None or rt.pg_manager is None:
        return {}
    return {k.hex(): v for k, v in rt.pg_manager.table().items()}
