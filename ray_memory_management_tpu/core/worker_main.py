"""Worker process entry point: ``python -m ray_memory_management_tpu.core.worker_main``.

Launched by the node manager's worker pool; connects back to the driver
runtime over its Unix socket (the reference's worker registers with the raylet
over its socket at startup, raylet_client.h:236) and enters the task loop.
Configuration arrives via RMT_* environment variables so no argv parsing or
pickling of startup state is needed.
"""

from __future__ import annotations

import os
from multiprocessing.connection import Client

# Set by the zygote in a forked child before calling main(): a message
# (e.g. create_actor) the worker processes immediately after registering,
# without waiting for the owner to deliver it (startup-token analog).
_bootstrap = None


def main() -> None:
    worker_id = bytes.fromhex(os.environ["RMT_WORKER_ID"])
    node_id = bytes.fromhex(os.environ["RMT_NODE_ID"])
    store_name = os.environ["RMT_STORE_NAME"]
    socket_path = os.environ["RMT_SOCKET"]
    # empty RMT_AUTHKEY = permission-trusted local socket (no HMAC
    # challenge; the socket file is 0600, same trust boundary)
    authkey = bytes.fromhex(os.environ["RMT_AUTHKEY"]) or None
    inline_limit = int(os.environ["RMT_INLINE_LIMIT"])

    import time

    conn = None
    for attempt in range(3):
        try:
            conn = Client(socket_path, family="AF_UNIX", authkey=authkey)
            break
        except (FileNotFoundError, ConnectionRefusedError,
                ConnectionResetError, EOFError, OSError):
            # runtime already shut down (or not yet listening, or tearing
            # down mid-handshake): exit quietly — we are a pooled worker
            # nobody will miss
            time.sleep(0.1 * (attempt + 1))
    if conn is None:
        return
    # identity first, so records emitted during the remaining imports
    # (or a bootstrap actor's __init__) already carry node/role stamps;
    # the RMT_LOGS gate itself is read at structlog import from the
    # inherited environment, same contract as RMT_TIMELINE
    from ..utils import structlog

    structlog.configure(node_id=node_id.hex(), role="worker")
    from .worker import Worker

    w = Worker(conn, worker_id, node_id, store_name, inline_limit)
    # refs deserialized in this process register with THIS worker's
    # reference counter (borrowed-ref protocol, reference_count.h:39-61);
    # refs serialized OUT mark their ids escaped (blocks the
    # free-on-owner-release fast path for ids other processes may hold)
    from .object_ref import set_deserialize_owner, set_serialize_observer

    set_deserialize_owner(w.proxy)
    set_serialize_observer(w.proxy.mark_escaped)
    if _bootstrap is not None:
        w.bootstrap_msg = _bootstrap
    if os.environ.get("RMT_WORKER_PROFILE"):
        # deprecation alias for the retired cProfile hook: a burst
        # capture from the sampling profiler, dumping folded stacks to
        # the old per-pid path (plus shipping them over the wire like
        # any other samples)
        import warnings

        from ..utils import profiler

        # FutureWarning: visible under the default filters (plain
        # DeprecationWarning is silenced outside __main__, and this
        # must reach the operator who set the env var)
        warnings.warn(
            "RMT_WORKER_PROFILE is deprecated: the cProfile hook was "
            "replaced by the sampling profiler (rmt profile / "
            "state.get_profile); this run takes a 2s burst capture "
            "instead", FutureWarning, stacklevel=1)
        path = os.environ["RMT_WORKER_PROFILE"] + f".{os.getpid()}"
        profiler.start_burst(2.0, path=path)
    w.run()


if __name__ == "__main__":
    main()
