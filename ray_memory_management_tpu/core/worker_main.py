"""Worker process entry point: ``python -m ray_memory_management_tpu.core.worker_main``.

Launched by the node manager's worker pool; connects back to the driver
runtime over its Unix socket (the reference's worker registers with the raylet
over its socket at startup, raylet_client.h:236) and enters the task loop.
Configuration arrives via RMT_* environment variables so no argv parsing or
pickling of startup state is needed.
"""

from __future__ import annotations

import os
from multiprocessing.connection import Client


def main() -> None:
    worker_id = bytes.fromhex(os.environ["RMT_WORKER_ID"])
    node_id = bytes.fromhex(os.environ["RMT_NODE_ID"])
    store_name = os.environ["RMT_STORE_NAME"]
    socket_path = os.environ["RMT_SOCKET"]
    authkey = bytes.fromhex(os.environ["RMT_AUTHKEY"])
    inline_limit = int(os.environ["RMT_INLINE_LIMIT"])

    import time

    conn = None
    for attempt in range(3):
        try:
            conn = Client(socket_path, family="AF_UNIX", authkey=authkey)
            break
        except (FileNotFoundError, ConnectionRefusedError,
                ConnectionResetError, EOFError, OSError):
            # runtime already shut down (or not yet listening, or tearing
            # down mid-handshake): exit quietly — we are a pooled worker
            # nobody will miss
            time.sleep(0.1 * (attempt + 1))
    if conn is None:
        return
    from .worker import Worker

    Worker(conn, worker_id, node_id, store_name, inline_limit).run()


if __name__ == "__main__":
    main()
