"""Per-job tenancy substrate: quotas, usage ledgers, fair-share state.

The runtime multiplexes many drivers (the in-process driver plus every
thin client and every ``job_submission`` subprocess); this module holds
the per-job half of that multiplexing:

  - ``JobQuota`` — admission limits enforced at the submit / put /
    device-pin edges (the analog of the reference's per-job resource
    isolation, which Ray itself never shipped beyond placement groups);
  - ``JobLedger`` — one per live job: usage counters, the owned-object
    tables a job-death sweep walks, the cpu-slot throttle queue, and the
    stride-scheduling virtual time the router's fair-share pass keys on.

Quota semantics (documented in README "Multi-tenant job plane"):

  - ``object_bytes`` / ``device_bytes`` are HARD admission limits — an
    over-quota put or device-pin raises ``QuotaExceededError`` at the
    call site and touches nothing. They never trigger eviction of
    another job's state: quota rejection is strictly local to the
    requesting job.
  - ``cpu_slots`` is BACKPRESSURE, not rejection: at most ``cpu_slots``
    of the job's tasks are in flight (scheduled-to-finished); excess
    submissions queue in the ledger and release as tasks finish. A task
    submitted at exactly the quota boundary runs; the one after waits.
  - ``priority`` orders jobs for the router's weighted-fair drain
    (stride scheduling: a job advances its virtual time by
    ``1/priority`` per dispatched task, lowest time goes first) and
    gates leaf-lease preemption: a strictly-higher-priority job may
    evict a lower-priority job's leaf tasks when the credit pool is dry.
  - Demotion interplay: when the device tier demotes an HBM object to
    host shm, its bytes MOVE from ``device_bytes`` to ``object_bytes``
    accounting — demoted bytes stop counting against the device quota.

A quota field of 0 means unlimited (the default job runs unconstrained,
exactly as the single-tenant runtime did).

Ledger locks are LEAF locks: no ledger method calls back into the
runtime or takes any other lock, so callers may hold the runtime lock.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Deque, Dict, List, Optional, Set

from ..exceptions import QuotaExceededError


class JobQuota:
    """Admission limits for one job. 0 = unlimited."""

    __slots__ = ("cpu_slots", "object_bytes", "device_bytes", "priority")

    def __init__(self, cpu_slots: int = 0, object_bytes: int = 0,
                 device_bytes: int = 0, priority: int = 1):
        self.cpu_slots = max(0, int(cpu_slots))
        self.object_bytes = max(0, int(object_bytes))
        self.device_bytes = max(0, int(device_bytes))
        self.priority = max(1, int(priority))

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "JobQuota":
        d = d or {}
        return cls(
            cpu_slots=d.get("cpu_slots", 0),
            object_bytes=d.get("object_bytes", 0),
            device_bytes=d.get("device_bytes", 0),
            priority=d.get("priority", 1),
        )

    def to_dict(self) -> dict:
        return {
            "cpu_slots": self.cpu_slots,
            "object_bytes": self.object_bytes,
            "device_bytes": self.device_bytes,
            "priority": self.priority,
        }

    def __repr__(self):
        return (f"JobQuota(cpu_slots={self.cpu_slots}, "
                f"object_bytes={self.object_bytes}, "
                f"device_bytes={self.device_bytes}, "
                f"priority={self.priority})")


class JobLedger:
    """Usage accounting + fair-share state for one live job.

    The ledger is the sweep's manifest: ``owned_object_ids()`` is every
    object the job created by put (host or device) that the runtime must
    release when the job dies, and ``actors`` is every actor it created.
    Task-created state (return objects, refcounts) is found through the
    task table instead — task ids carry the job id on their spec.
    """

    __slots__ = (
        "job_id", "quota", "lock",
        "object_sizes", "object_bytes",
        "device_sizes", "device_bytes",
        "actors", "slots", "throttled",
        "stride_pass", "tasks_submitted", "tasks_finished",
        "preempted_total", "rejections_total", "swept",
    )

    def __init__(self, job_id: bytes, quota: Optional[JobQuota] = None):
        self.job_id = job_id
        self.quota = quota or JobQuota()
        self.lock = threading.Lock()
        # host-tier objects this job created by put: oid -> bytes.
        # Demoted device objects migrate here (see note_demoted).
        self.object_sizes: Dict[bytes, int] = {}  # guarded-by: lock
        self.object_bytes = 0  # guarded-by: lock
        # device-tier (HBM) objects this job pinned: oid -> bytes
        self.device_sizes: Dict[bytes, int] = {}  # guarded-by: lock
        self.device_bytes = 0  # guarded-by: lock
        self.actors: Set[bytes] = set()  # guarded-by: lock
        # cpu_slots throttle: task ids currently holding a slot, plus the
        # specs waiting for one (drained FIFO as slots free)
        self.slots: Set[bytes] = set()  # guarded-by: lock
        self.throttled: Deque = deque()  # guarded-by: lock
        # stride-scheduling virtual time: advanced 1/priority per
        # dispatched task; the router drains the lowest-pass job first
        self.stride_pass = 0.0  # guarded-by: lock
        self.tasks_submitted = 0
        self.tasks_finished = 0
        self.preempted_total = 0
        self.rejections_total = 0
        self.swept = False

    # -- byte quotas (hard admission) ------------------------------------
    def admit_object(self, oid: bytes, nbytes: int) -> None:
        """Charge a host-tier put against object_bytes or raise."""
        with self.lock:
            limit = self.quota.object_bytes
            if limit and self.object_bytes + nbytes > limit \
                    and oid not in self.object_sizes:
                self.rejections_total += 1
                raise QuotaExceededError(
                    self.job_id.hex(), "object_bytes",
                    nbytes, limit, self.object_bytes)
            prev = self.object_sizes.get(oid)
            self.object_sizes[oid] = nbytes
            self.object_bytes += nbytes - (prev or 0)

    def admit_device(self, oid: bytes, nbytes: int) -> None:
        """Charge a device pin against device_bytes or raise."""
        with self.lock:
            limit = self.quota.device_bytes
            if limit and self.device_bytes + nbytes > limit \
                    and oid not in self.device_sizes:
                self.rejections_total += 1
                raise QuotaExceededError(
                    self.job_id.hex(), "device_bytes",
                    nbytes, limit, self.device_bytes)
            prev = self.device_sizes.get(oid)
            self.device_sizes[oid] = nbytes
            self.device_bytes += nbytes - (prev or 0)

    def release_object(self, oid: bytes) -> int:
        with self.lock:
            n = self.object_sizes.pop(oid, 0)
            self.object_bytes -= n
            return n

    def release_device(self, oid: bytes) -> int:
        with self.lock:
            n = self.device_sizes.pop(oid, 0)
            self.device_bytes -= n
            return n

    def release_many(self, oids) -> None:
        """Batch uncharge (free_objects path): cheap no-op for oids this
        job never charged."""
        with self.lock:
            for oid in oids:
                n = self.object_sizes.pop(oid, 0)
                if n:
                    self.object_bytes -= n
                n = self.device_sizes.pop(oid, 0)
                if n:
                    self.device_bytes -= n

    def note_demoted(self, oid: bytes) -> None:
        """HBM -> host demotion: the bytes stop counting against
        ``device_bytes`` and start counting against ``object_bytes``
        (never rejected — demotion is a system action, not a request)."""
        with self.lock:
            n = self.device_sizes.pop(oid, 0)
            if n:
                self.device_bytes -= n
                prev = self.object_sizes.get(oid, 0)
                self.object_sizes[oid] = n
                self.object_bytes += n - prev

    def owned_object_ids(self) -> List[bytes]:
        with self.lock:
            return list(self.object_sizes.keys()) \
                + list(self.device_sizes.keys())

    # -- cpu_slots throttle (backpressure) -------------------------------
    def try_take_slot(self, task_id: bytes) -> bool:
        """Claim an in-flight slot; False means the caller must park the
        spec via park(). Unlimited quota always succeeds. Idempotent per
        task id (a retry re-enters scheduling with its slot held)."""
        with self.lock:
            limit = self.quota.cpu_slots
            if task_id in self.slots:
                return True
            if limit and len(self.slots) >= limit:
                return False
            self.slots.add(task_id)
            return True

    def park(self, spec) -> None:
        with self.lock:
            self.throttled.append(spec)

    def release_slot(self, task_id: bytes):
        """Return a finished/failed task's slot; hands back the next
        parked spec (if any) for the caller to re-enter scheduling.
        Idempotent: releasing an unheld slot unparks nothing."""
        with self.lock:
            if task_id not in self.slots:
                return None
            self.slots.discard(task_id)
            if self.throttled:
                spec = self.throttled.popleft()
                self.slots.add(spec.task_id)
                return spec
            return None

    def drain_parked(self) -> list:
        """Sweep path: every spec still waiting for a slot."""
        with self.lock:
            out = list(self.throttled)
            self.throttled.clear()
            self.slots.clear()
            return out

    # -- fair share ------------------------------------------------------
    def peek_pass(self) -> float:
        with self.lock:
            return self.stride_pass

    def advance_pass(self) -> float:
        """One dispatch charged against this job's virtual time; higher
        priority advances slower and therefore drains more often."""
        with self.lock:
            self.stride_pass += 1.0 / self.quota.priority
            return self.stride_pass

    def usage(self) -> dict:
        with self.lock:
            return {
                "object_bytes": self.object_bytes,
                "object_count": len(self.object_sizes),
                "device_bytes": self.device_bytes,
                "device_count": len(self.device_sizes),
                "tasks_inflight": len(self.slots),
                "tasks_parked": len(self.throttled),
                "tasks_submitted": self.tasks_submitted,
                "tasks_finished": self.tasks_finished,
                "actors": len(self.actors),
                "preempted": self.preempted_total,
                "rejections": self.rejections_total,
                "priority": self.quota.priority,
                "quota": self.quota.to_dict(),
            }


def fair_order(specs, ledger_of) -> list:
    """Stride-scheduling interleave of one drained submit batch.

    ``ledger_of(spec)`` maps a spec to its job's ledger. Within a job,
    FIFO order is preserved; across jobs, the next spec always comes
    from the job with the lowest virtual time, which converges to
    priority-weighted shares. Single-job batches return unchanged (the
    common case pays one dict insert, no sort).
    """
    import heapq

    by_job: Dict[bytes, Deque] = {}
    order: List[bytes] = []
    for spec in specs:
        led = ledger_of(spec)
        key = led.job_id
        q = by_job.get(key)
        if q is None:
            q = by_job[key] = deque()
            order.append(key)
        q.append((spec, led))
    if len(by_job) <= 1:
        return list(specs)
    heap = []
    for i, key in enumerate(order):
        _, led = by_job[key][0]
        heapq.heappush(heap, (led.peek_pass(), i, key))
    out: List = []
    while heap:
        _, i, key = heapq.heappop(heap)
        q = by_job[key]
        spec, led = q.popleft()
        out.append(spec)
        new_pass = led.advance_pass()
        if q:
            heapq.heappush(heap, (new_pass, i, key))
    return out
