"""Task and actor specifications shipped from owner to worker.

The analog of the reference's TaskSpecification (src/ray/common/task/task_spec.h:159)
— but as a plain Python object sent over the worker pipe rather than a protobuf,
since the worker boundary here is a same-host process. Arguments are encoded as
either inline serialized bytes or object references, mirroring the reference's
inlining rules (task_rpc_inlined_bytes_limit, ray_config_def.h:424).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

# Argument encodings: ("v", <serialized bytes>) inline value,
#                     ("ref", <object id bytes>) store reference.
Arg = Tuple[str, bytes]


class TaskSpec:
    __slots__ = (
        "task_id", "name", "fn_id", "args", "kwargs", "num_returns",
        "return_ids", "resources", "strategy", "max_retries",
        "retry_exceptions", "actor_id", "method", "seq",
        "runtime_env", "placement", "depth", "trace_ctx", "job_id",
        "_ref_deps_cache", "_conda_key", "_req_cache",
    )

    def __init__(
        self,
        task_id: bytes,
        name: str,
        fn_id: bytes,
        args: List[Arg],
        kwargs: Dict[str, Arg],
        num_returns: int,
        return_ids: List[bytes],
        resources: Dict[str, float],
        strategy: Any = None,
        max_retries: int = 0,
        retry_exceptions: bool = False,
        actor_id: Optional[bytes] = None,
        method: Optional[str] = None,
        seq: int = 0,
        runtime_env: Optional[dict] = None,
        placement: Optional[tuple] = None,  # (pg_id_bytes, bundle_index)
        depth: int = 0,
        trace_ctx: Optional[tuple] = None,  # (trace_id, span_id, parent)
        job_id: Optional[bytes] = None,
    ):
        self.task_id = task_id
        self.name = name
        self.fn_id = fn_id
        self.args = args
        self.kwargs = kwargs
        self.num_returns = num_returns
        self.return_ids = return_ids
        self.resources = resources
        self.strategy = strategy
        self.max_retries = max_retries
        self.retry_exceptions = retry_exceptions
        self.actor_id = actor_id
        self.method = method
        self.seq = seq
        self.runtime_env = runtime_env
        self.placement = placement
        self.depth = depth
        self.trace_ctx = trace_ctx
        # owning job: the 16-byte id of the job that submitted this task
        # (the task id's 4-byte prefix is derived from it; the full id
        # disambiguates prefix collisions for sweeps and state filters)
        self.job_id = job_id
        self._ref_deps_cache: Optional[List[bytes]] = None
        # memoized conda-env key: computed once at first dispatch, not
        # re-hashed under the node lock every dispatch round
        self._conda_key: Optional[str] = None
        self._req_cache = None

    @property
    def req(self):
        """The task's resource request as a ``Resources``, built once:
        scheduling + every dispatch round rebuilt it from the dict, which
        showed in the task hot path. Read-only by convention — dispatch
        stores it as a worker's lease and compares leases by value."""
        r = self._req_cache
        if r is None:
            from .resources import Resources

            r = self._req_cache = Resources(self.resources)
        return r

    @property
    def ref_deps(self) -> List[bytes]:
        """Object ids this task's args reference. Computed once: the owner
        walks a task's deps on submit, dep-resolve, arg-pin, finish, GC
        and recovery paths — rebuilding the list each time showed up in
        the submit hot path. Args are immutable after construction."""
        deps = self._ref_deps_cache
        if deps is None:
            deps = [payload for kind, payload in self.args if kind == "ref"]
            for kind, payload in self.kwargs.values():
                if kind == "ref":
                    deps.append(payload)
            self._ref_deps_cache = deps
        return deps

    @property
    def is_actor_task(self) -> bool:
        return self.actor_id is not None and self.method is not None

    def __repr__(self):
        return f"TaskSpec({self.name}, id={self.task_id.hex()[:8]})"


class ActorCreationSpec:
    __slots__ = (
        "actor_id", "name", "cls_id", "args", "kwargs", "resources",
        "strategy", "max_restarts", "max_task_retries", "max_concurrency",
        "runtime_env", "placement", "detached", "registered_name",
    )

    def __init__(
        self,
        actor_id: bytes,
        name: str,
        cls_id: bytes,
        args: List[Arg],
        kwargs: Dict[str, Arg],
        resources: Dict[str, float],
        strategy: Any = None,
        max_restarts: int = 0,
        max_task_retries: int = 0,
        max_concurrency: int = 1,
        runtime_env: Optional[dict] = None,
        placement: Optional[tuple] = None,
        detached: bool = False,
        registered_name: Optional[str] = None,
    ):
        self.actor_id = actor_id
        self.name = name
        self.cls_id = cls_id
        self.args = args
        self.kwargs = kwargs
        self.resources = resources
        self.strategy = strategy
        self.max_restarts = max_restarts
        self.max_task_retries = max_task_retries
        self.max_concurrency = max_concurrency
        self.runtime_env = runtime_env
        self.placement = placement
        self.detached = detached
        self.registered_name = registered_name
