"""Fixed-point resource accounting.

Mirrors the reference's raylet resource math (src/ray/raylet/scheduling/fixed_point.h
and cluster_resource_data.h:416 NodeResources): resource quantities are stored
as integers in units of 1/10000 so that fractional resources (e.g. num_cpus=0.5)
never drift under repeated add/subtract.

Resource names follow the reference's convention: "CPU", "memory",
"object_store_memory", custom strings — plus "TPU", the first-class accelerator
resource this framework adds (the analog of "GPU" in _private/resource_spec.py:88-101).
"""

from __future__ import annotations

from typing import Dict, Iterable, Optional

PRECISION = 10_000

CPU = "CPU"
TPU = "TPU"
MEMORY = "memory"
OBJECT_STORE_MEMORY = "object_store_memory"


def _to_fixed(v: float) -> int:
    return round(v * PRECISION)


class Resources:
    """An immutable-ish bag of named fixed-point resource quantities."""

    __slots__ = ("_amounts",)

    def __init__(self, amounts: Optional[Dict[str, float]] = None, _fixed=None):
        if _fixed is not None:
            self._amounts: Dict[str, int] = _fixed
        else:
            self._amounts = {
                k: _to_fixed(v) for k, v in (amounts or {}).items() if v
            }

    @classmethod
    def from_fixed(cls, fixed: Dict[str, int]) -> "Resources":
        return cls(_fixed=dict(fixed))

    def get(self, name: str) -> float:
        return self._amounts.get(name, 0) / PRECISION

    def fixed(self) -> Dict[str, int]:
        return dict(self._amounts)

    def is_empty(self) -> bool:
        return not any(self._amounts.values())

    def names(self) -> Iterable[str]:
        return self._amounts.keys()

    def fits_in(self, other: "Resources") -> bool:
        return all(
            amt <= other._amounts.get(name, 0)
            for name, amt in self._amounts.items()
        )

    def __add__(self, other: "Resources") -> "Resources":
        out = dict(self._amounts)
        for k, v in other._amounts.items():
            out[k] = out.get(k, 0) + v
        return Resources.from_fixed(out)

    def __sub__(self, other: "Resources") -> "Resources":
        out = dict(self._amounts)
        for k, v in other._amounts.items():
            out[k] = out.get(k, 0) - v
        return Resources.from_fixed(out)

    def to_dict(self) -> Dict[str, float]:
        return {k: v / PRECISION for k, v in self._amounts.items() if v}

    def __repr__(self):
        return f"Resources({self.to_dict()})"

    def __eq__(self, other):
        return isinstance(other, Resources) and other._amounts == self._amounts


def task_resources(
    num_cpus: Optional[float] = None,
    num_tpus: Optional[float] = None,
    memory: Optional[float] = None,
    resources: Optional[Dict[str, float]] = None,
    default_cpus: float = 1.0,
) -> Resources:
    """Build a task/actor resource request with the reference's defaults:
    tasks default to 1 CPU; actors default to 0 (remote_function.py /
    actor.py option handling)."""
    out: Dict[str, float] = dict(resources or {})
    out[CPU] = default_cpus if num_cpus is None else num_cpus
    if num_tpus:
        out[TPU] = num_tpus
    if memory:
        out[MEMORY] = memory
    return Resources(out)


class NodeResources:
    """Total + available resources of one node (cluster_resource_data.h:416)."""

    __slots__ = ("total", "available", "labels")

    def __init__(self, total: Resources, labels: Optional[Dict[str, str]] = None):
        self.total = total
        self.available = Resources.from_fixed(total.fixed())
        self.labels = labels or {}

    def can_fit(self, req: Resources) -> bool:
        return req.fits_in(self.available)

    def is_feasible(self, req: Resources) -> bool:
        return req.fits_in(self.total)

    def allocate(self, req: Resources) -> None:
        self.available = self.available - req

    def free(self, req: Resources) -> None:
        self.available = self.available + req

    def utilization(self) -> float:
        """Max utilization over resource kinds present on the node (the
        hybrid policy's node-ranking signal, hybrid_scheduling_policy.h:48)."""
        util = 0.0
        for name, tot in self.total.fixed().items():
            if tot <= 0:
                continue
            avail = self.available.fixed().get(name, 0)
            util = max(util, 1.0 - avail / tot)
        return util
