"""Head-side proxy for a remote node joined through a node agent.

``RemoteNodeManager`` subclasses ``NodeManager`` so every head-side code
path — scheduling, lease accounting, dispatch, actor lifecycle, worker
death — treats remote nodes exactly like local ones. What differs is the
mechanics a kernel boundary forces:

  - workers are spawned by the agent (``start_worker`` sends a frame
    instead of fork/exec; the handle's ``proc`` is a :class:`RemoteProc`);
  - worker pipes are tunneled: the handle's ``conn`` is a
    :class:`VirtualConn` whose ``send`` wraps the payload in a
    ``wsend`` frame on the agent channel, and inbound worker frames are
    unwrapped by the runtime's router (``wmsg``);
  - the object store is remote: :class:`RemoteStoreProxy` implements the
    read side by streaming chunks over the channel (the reference's
    chunked object-manager pull, object_manager.proto:63-67) and the
    write side by streaming a push (ObjectManager::Push analog).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

from ..config import Config
from ..ids import NodeID, WorkerID
from ..utils.retry import RetryPolicy
from .node_manager import NodeManager, WorkerHandle
from .resources import NodeResources


class VirtualConn:
    """Stand-in for a worker's pipe: sends ride the agent channel."""

    __slots__ = ("wid", "node")

    def __init__(self, wid: bytes, node: "RemoteNodeManager"):
        self.wid = wid
        self.node = node

    def send(self, payload: dict) -> None:
        self.node.channel_send({"type": "wsend", "wid": self.wid,
                                "msg": payload})

    def close(self) -> None:
        pass


class RemoteProc:
    """Popen-shaped liveness facade for a worker living on another host.
    Death is learned from the agent (``wdeath``) rather than waitpid."""

    __slots__ = ("returncode", "_node", "_wid")

    def __init__(self, node: "RemoteNodeManager", wid: bytes):
        self.returncode: Optional[int] = None
        self._node = node
        self._wid = wid

    def poll(self):
        return self.returncode

    def terminate(self) -> None:
        self._node.channel_send({"type": "kill_worker", "wid": self._wid})

    def kill(self) -> None:
        self.terminate()


class RemoteStoreProxy:
    """The store surface the runtime needs for a node it cannot mmap.

    ``contains`` answers from the head's object directory (GCS locations —
    the head is the owner of record, so directory state is authoritative);
    ``get`` pulls the object's bytes over the channel; pushes stream
    create/chunk/seal frames and wait for the agent's ack.
    """

    def __init__(self, node: "RemoteNodeManager"):
        self._node = node

    def contains(self, object_id: bytes) -> bool:
        gcs = self._node.gcs
        return (gcs is not None
                and self._node.node_id in gcs.get_object_locations(object_id))

    def get(self, object_id: bytes):
        data = self._node.pull_object(object_id)
        return None if data is None else memoryview(data)

    def release(self, object_id: bytes) -> None:
        pass  # pulled bytes are owned by the head-side caller

    def ensure_resident(self, object_id: bytes) -> bool:
        """Restore-and-pin on the agent so a remote worker's direct shm
        read cannot race the agent's spill tier."""
        return self._node.ensure_object(object_id)

    def ensure_resident_many(self, object_ids) -> Dict[bytes, bool]:
        """Batched restore-and-pin: ONE channel round-trip for N objects
        (a per-object ensure against a degraded agent would serialize N
        blocking waits on the caller's thread)."""
        return self._node.ensure_objects(list(object_ids))

    def make_room(self, nbytes: int) -> bool:
        """Ask the agent to spill so a worker's direct put can allocate."""
        return self._node.request_spill(nbytes)

    def delete(self, object_id: bytes) -> None:
        self._node.channel_send({"type": "obj_free", "oid": object_id})

    def put_serialized(self, object_id: bytes, serialized) -> None:
        buf = bytearray(serialized.total_size)
        serialized.write_into(memoryview(buf))
        ok, err = self._node.push_object(object_id, memoryview(buf))
        if not ok:
            # raising keeps callers from registering a GCS location for an
            # object the agent never landed
            from ..exceptions import ObjectStoreFullError

            raise ObjectStoreFullError(
                f"push of {object_id.hex()[:8]} to "
                f"{self._node.hostname} failed ({err})")

    def usage(self):
        return (0, 0)


class RemoteNodeManager(NodeManager):
    def __init__(self, node_id: NodeID, resources: NodeResources,
                 config: Config, on_worker_started, channel,
                 gcs=None, hostname: str = "?"):
        # NodeManager.__init__ would create a local shm store; bypass it and
        # wire the remote-facing fields directly.
        self.socket_path = ""
        self.authkey_hex = ""
        self.node_id = node_id
        self.resources = resources
        self.config = config
        self.store = RemoteStoreProxy(self)
        self.store_name = f"remote:{hostname}"
        self._on_worker_started = on_worker_started
        self._init_pool_state()
        from .resources import TPU

        total_chips = int(resources.total.get(TPU))
        self.free_chips = list(range(total_chips))

        self.channel = channel
        self.gcs = gcs
        self.hostname = hostname
        self.agent_pid: Optional[int] = None  # pid on the agent's host
        # (host, port) of the agent's TransferServer, set by its
        # transfer_ready frame; None until then (fallback: channel push)
        self.transfer_addr: Optional[tuple] = None
        # the agent's shm store name (same transfer_ready frame): when the
        # agent shares this host, its store can be mapped directly
        self.remote_store_name: Optional[str] = None
        self._channel_lock = threading.Lock()
        self._req_counter = 0
        self._pending: Dict[int, dict] = {}       # req -> accumulating state
        self._pending_lock = threading.Lock()
        # serializes pushes so two transfer threads never interleave
        # create/chunk/seal frames for the same object at the agent
        self._push_lock = threading.Lock()
        # delta-heartbeat state, head side: seq of the last pong whose
        # delta we APPLIED (acked on the next ping so the agent knows
        # which base to delta against), the merged status mirror those
        # deltas build, and the resync latch a sequence gap raises so
        # the next ping requests full state
        self.hb_seq = 0  # guarded-by: _lock
        self.hb_resync = False  # guarded-by: _lock
        self.agent_stat: Dict[str, Any] = {}  # guarded-by: _lock
        # leaf-lease grant buffer: submit_leaf queues built frames here
        # and the router's per-pass flush ships ONE lease_batch frame
        # per node (leaf_lease_batch caps a single frame) instead of one
        # lease_exec per task
        self._lease_buf: List[dict] = []  # guarded-by: _lock

    # ---------------------------------------------------------------- channel
    def channel_send(self, msg: dict) -> bool:
        try:
            with self._channel_lock:
                self.channel.send(msg)
            return True
        except (OSError, BrokenPipeError, ValueError):
            return False

    def _new_req(self) -> int:
        with self._pending_lock:
            self._req_counter += 1
            req = self._req_counter
            self._pending[req] = {"event": threading.Event(), "chunks": [],
                                  "error": None}
            return req

    # -------------------------------------------------------------- transfers
    def pull_object(self, object_id: bytes,
                    timeout: float = 120.0) -> Optional[bytes]:
        """Chunked pull over the channel (PullManager analog,
        pull_manager.h:47, collapsed to one in-order stream)."""
        if not self.alive:
            return None
        req = self._new_req()
        with self._pending_lock:
            state = self._pending.get(req)
        if state is None or not self.channel_send(
                {"type": "obj_pull", "oid": object_id, "req": req}):
            with self._pending_lock:
                self._pending.pop(req, None)
            return None
        if not state["event"].wait(timeout):
            with self._pending_lock:
                self._pending.pop(req, None)
            return None
        with self._pending_lock:
            self._pending.pop(req, None)
        if state["error"]:
            return None
        return b"".join(state["chunks"])

    def push_object(self, object_id: bytes, view: memoryview,
                    timeout: float = 120.0):
        """Chunked push (ObjectManager::Push analog); returns
        ``(ok, last_error)``. A push the agent nacks as retryable —
        payload-budget backpressure from its admission control, or a
        transiently-full store (readers still draining) — is retried
        here with backoff for up to ``push_pressure_retry_s``: the
        caller holds a read ref on the source copy the whole time, so
        pressure delays the transfer but can never lose the object."""
        policy = RetryPolicy(
            max_attempts=10_000,  # bounded by the deadline, not attempts
            base_backoff_s=0.2, max_backoff_s=1.0,
            deadline_s=self.config.push_pressure_retry_s,
            retryable=lambda e: "retryable" in str(e), plane="push")
        attempt = 0
        while True:
            ok, err = self._push_object_once(object_id, view, timeout)
            if ok or not self.alive:
                return ok, err
            if not policy.is_retryable(err or ""):
                return False, err
            if not policy.backoff(attempt):
                return False, err
            attempt += 1

    def _push_object_once(self, object_id: bytes, view: memoryview,
                          timeout: float):
        """One push attempt; returns (ok, error_string)."""
        if not self.alive:
            return False, "node dead"
        with self._push_lock:
            # a concurrent transfer may have landed this object already
            if self.gcs is not None and self.node_id in \
                    self.gcs.get_object_locations(object_id):
                return True, None
            req = self._new_req()
            with self._pending_lock:
                state = self._pending.get(req)
            if state is None:
                return False, "shutting down"
            chunk = self.config.object_manager_chunk_size
            # req rides the obj_push frame so the agent can nack an
            # over-budget push IMMEDIATELY; the early ack sets our event
            # and the chunk loop aborts instead of streaming the whole
            # payload through the channel just to be discarded
            ok = self.channel_send({"type": "obj_push", "oid": object_id,
                                    "size": view.nbytes, "req": req})
            for off in range(0, view.nbytes, chunk):
                if not ok or state["event"].is_set():
                    break
                end = min(off + chunk, view.nbytes)
                ok = self.channel_send({
                    "type": "obj_chunk", "oid": object_id, "off": off,
                    "data": bytes(view[off:end]),
                })
            ok = ok and self.channel_send(
                {"type": "obj_seal", "oid": object_id, "req": req})
            if not ok:
                with self._pending_lock:
                    self._pending.pop(req, None)
                return False, "channel send failed"
        # ack wait OUTSIDE _push_lock: the lock only exists to keep the
        # push/chunk/seal frame sequence unfragmented on the channel —
        # holding it across a (up to 120s) ack wait convoys every other
        # push to this node behind one slow store
        if not state["event"].wait(timeout):
            with self._pending_lock:
                self._pending.pop(req, None)
            return False, "timeout"
        with self._pending_lock:
            self._pending.pop(req, None)
        return state["error"] is None, state["error"]

    def ensure_object(self, object_id: bytes, timeout: float = 60.0) -> bool:
        """Ask the agent to make the object shm-resident (restoring from its
        spill tier) and pin it briefly (node_agent obj_ensure)."""
        res = self.ensure_objects([object_id], timeout=timeout)
        return res.get(object_id, False)

    def ensure_objects(self, object_ids, timeout: float = 60.0
                       ) -> Dict[bytes, bool]:
        """Batched obj_ensure: one frame + one ack for N objects."""
        if not self.alive or not object_ids:
            return {oid: False for oid in object_ids}
        req = self._new_req()
        with self._pending_lock:
            state = self._pending.get(req)
        if state is None or not self.channel_send(
                {"type": "obj_ensure", "oids": list(object_ids),
                 "req": req}):
            with self._pending_lock:
                self._pending.pop(req, None)
            return {oid: False for oid in object_ids}
        ok = state["event"].wait(timeout)
        with self._pending_lock:
            self._pending.pop(req, None)
        if not ok or state["error"] is not None:
            return {oid: False for oid in object_ids}
        failed = set(state.get("failed") or ())
        return {oid: oid not in failed for oid in object_ids}

    def fetch_from_peer(self, oid: bytes, host: str, port: int,
                        timeout: float = 120.0,
                        src_store: Optional[str] = None,
                        alts: Optional[list] = None,
                        trace=None) -> Optional[str]:
        """Tell the agent to pull ``oid`` straight from a peer's transfer
        server (host "" = the head). ``src_store`` names the source's shm
        segment when the peer shares the agent's host — the agent then
        maps it and memcpys instead of speaking TCP. ``alts`` lists other
        live holders' transfer addresses (head-resolved) so the agent can
        fail a stalled pull over mid-stripe. ``trace`` is the trace
        context of the task the pull serves; it rides the fetch frame and
        the agent's wire requests so serve spans land on the task's
        causal chain. Returns None on success, else an error string.
        Payload bytes never touch the head or this channel."""
        if not self.alive:
            return "node dead"
        req = self._new_req()
        msg = {"type": "obj_fetch", "oid": oid, "host": host,
               "port": port, "req": req}
        if src_store:
            msg["src_store"] = src_store
        if alts:
            msg["alts"] = list(alts)
        if trace:
            msg["trace"] = tuple(trace)
        with self._pending_lock:
            state = self._pending.get(req)
        if state is None or not self.channel_send(msg):
            with self._pending_lock:
                self._pending.pop(req, None)
            return "channel send failed"
        ok = state["event"].wait(timeout)
        with self._pending_lock:
            self._pending.pop(req, None)
        if not ok:
            return "fetch timed out"
        return state["error"]

    def request_spill(self, nbytes: int, timeout: float = 60.0) -> bool:
        """One obj_spill round trip (the make_room path)."""
        if not self.alive:
            return False
        req = self._new_req()
        with self._pending_lock:
            state = self._pending.get(req)
        if state is None or not self.channel_send(
                {"type": "obj_spill", "bytes": int(nbytes), "req": req}):
            with self._pending_lock:
                self._pending.pop(req, None)
            return False
        ok = state["event"].wait(timeout)
        with self._pending_lock:
            self._pending.pop(req, None)
        return ok and state["error"] is None

    def on_channel_reply(self, msg: dict) -> None:
        """push_ack / pull_data / ensure_ack / fetch_ack / spill_ack frames
        routed here by the runtime router."""
        req = msg.get("req")
        with self._pending_lock:
            state = self._pending.get(req)
        if state is None:
            return
        if msg["type"] in ("push_ack", "ensure_ack", "fetch_ack",
                           "spill_ack"):
            state["error"] = msg.get("error")
            state["failed"] = msg.get("failed")
            state["event"].set()
            return
        if msg.get("error"):
            state["error"] = msg["error"]
            state["event"].set()
            return
        state["chunks"].append(msg["data"])
        if msg.get("eof"):
            state["event"].set()

    # ------------------------------------------------------------- leaf leases
    def submit_leaf(self, spec, build_msg=None) -> bool:
        """Agent-local leaf placement: spend a lease credit and ship the
        fully-built exec frame to the node's AGENT, which picks the
        worker itself (lease_exec). The head's only per-task work is the
        frame build — no pick_node, no dispatch queue, no try_dispatch
        round. The agent answers lease_spill when its pool is saturated
        (credit returned via finish_leaf, task re-enters the router) and
        lease_dead when the chosen worker dies mid-task."""
        if build_msg is None:
            return False
        with self._lock:
            if not self.alive or self.leaf_credits <= 0:
                return False
            self.leaf_credits -= 1
            self.leaf_inflight[spec.task_id] = spec
        msg = build_msg(self, spec)
        # grants BUFFER instead of shipping one frame per task: the
        # router flushes once per scheduling pass (flush_leases), so a
        # pass that places N leaf tasks on this node costs one
        # lease_batch frame, not N lease_exec frames — the per-node
        # ingress term the pod bench measures. A flush-time send failure
        # rolls the credits back there; a death between buffer and flush
        # reroutes through take_leaf_inflight like any in-flight lease.
        with self._lock:
            if not self.alive:
                self.leaf_credits += 1
                self.leaf_inflight.pop(spec.task_id, None)
                return False
            self._lease_buf.append({"task_id": spec.task_id, "msg": msg})
        return True

    def flush_leases(self) -> list:
        """Ship every buffered leaf grant: lease_batch frames of up to
        leaf_lease_batch entries each; a lone grant keeps the scalar
        lease_exec frame (wire-identical to pre-batching traffic at low
        rates). On a send failure the unsent grants' credits roll back
        and their specs return to the caller for rerouting (the router
        rides them through _pending_schedule, like a lease_spill)."""
        with self._lock:
            if not self._lease_buf:
                return []
            buf, self._lease_buf = self._lease_buf, []
        cap = max(1, int(getattr(self.config, "leaf_lease_batch", 64) or 1))
        failed: list = []
        i = 0
        while i < len(buf):
            chunk = buf[i:i + cap]
            i += cap
            if len(chunk) == 1:
                ok = self.channel_send({"type": "lease_exec",
                                        "task_id": chunk[0]["task_id"],
                                        "msg": chunk[0]["msg"]})
            else:
                ok = self.channel_send({"type": "lease_batch",
                                        "tasks": chunk})
                if ok:
                    from . import metrics_defs as mdefs

                    mdefs.leaf_lease_batches().inc()
            if not ok:
                with self._lock:
                    for entry in chunk + buf[i:]:
                        self.leaf_credits += 1
                        spec = self.leaf_inflight.pop(entry["task_id"],
                                                      None)
                        if spec is not None:
                            failed.append(spec)
                break
        return failed

    def lease_buffered(self) -> int:
        with self._lock:
            return len(self._lease_buf)

    # ---------------------------------------------------------- heartbeats
    def ping_frame(self) -> dict:
        """The head half of the delta-heartbeat pair: ack the last pong
        seq whose delta we applied (the agent deltas against exactly
        that base) and carry the resync latch when a gap lost it."""
        with self._lock:
            frame = {"type": "ping", "ack": self.hb_seq}
            if self.hb_resync:
                frame["resync"] = True
        return frame

    def on_pong_delta(self, msg: dict) -> None:
        """Apply one pong's delta-compressed control state. An in-order
        seq keeps the merged status mirror exact and applies held-row
        deltas (dadd/ddel) to the object directory; a full snapshot
        (dfull) replaces the mirror and reconciles the node's directory
        rows; a gap raises the resync latch — deltas built on a base we
        lost are DISCARDED, never guessed at — and is counted."""
        seq = msg.get("seq")
        if seq is None:
            return  # pre-delta pong: nothing to track
        full = bool(msg.get("dfull"))
        accept = False
        resync_now = False
        with self._lock:
            if full or seq == self.hb_seq + 1:
                accept = True
                self.hb_seq = seq
                if full:
                    self.agent_stat = dict(msg.get("stat") or {})
                    self.hb_resync = False
                elif msg.get("stat"):
                    self.agent_stat.update(msg["stat"])
            elif not self.hb_resync:
                self.hb_resync = True
                resync_now = True
        if resync_now:
            from . import metrics_defs as mdefs

            mdefs.heartbeat_resyncs().inc()
            return
        if not accept or self.gcs is None:
            return
        dadd = msg.get("dadd")
        ddel = msg.get("ddel")
        if full:
            if dadd is None:
                return  # status-only resync: no row assertion to apply
            held = {oid: size for oid, size in dadd}
            for oid, size in held.items():
                self.gcs.add_object_location(oid, self.node_id,
                                             size=size or None)
            self.gcs.reconcile_node_rows(self.node_id, held)
        else:
            for oid, size in dadd or ():
                self.gcs.add_object_location(oid, self.node_id,
                                             size=size or None)
            for oid in ddel or ():
                self.gcs.remove_object_location(oid, self.node_id)

    def cancel_leaf(self, task_id: bytes) -> None:
        """Job sweep: a leased task of a dead job may be RUNNING on a
        pool worker only the AGENT can name (the head never learned the
        placement — that was the point of the lease). Ask the agent to
        kill that worker; the resulting wdeath/lease_dead frames settle
        accounting through the normal death path, and the retry lands in
        _cancelled and fails. Best-effort: a dead channel means the node
        sweep already reclaimed everything."""
        self.channel_send({"type": "lease_cancel", "task_id": task_id})

    # ------------------------------------------------------------ worker pool
    def start_conda_worker(self, conda_spec, conda_key: str) -> None:
        """Remote flavor of the dedicated conda-env worker: the env is
        HOST-local, so the AGENT resolves/creates it and spawns under its
        python (the head only registers the handle). Overrides the base,
        which would Popen on the head's host against this node's
        nonexistent local socket."""
        with self._lock:
            if conda_key in self._conda_starting:
                return
            self._conda_starting.add(conda_key)
        worker_id = WorkerID.from_random()
        handle = WorkerHandle(worker_id,
                              RemoteProc(self, worker_id.binary()),
                              self.node_id)
        handle.conda_key = conda_key
        with self._lock:
            self.workers[worker_id] = handle
            self.starting += 1
        self._on_worker_started(handle)
        if not self.channel_send({
                "type": "start_worker", "wid_hex": worker_id.hex(),
                "dedicated": False, "env": {}, "conda": conda_spec}):
            with self._lock:
                self._conda_starting.discard(conda_key)
            self.remove_worker(handle)

    def start_worker(self, dedicated: bool = False,
                     bootstrap: Optional[dict] = None,
                     on_handle=None,
                     conda_spec=None) -> WorkerHandle:
        # mirror NodeManager: register the handle and run the caller's
        # bookkeeping BEFORE the spawn frame leaves — a bootstrapped fork
        # on the agent can answer before this function returns
        worker_id = WorkerID.from_random()
        handle = WorkerHandle(worker_id, RemoteProc(self, worker_id.binary()),
                              self.node_id)
        if dedicated:
            handle.actor_id = b"__pending__"
        with self._lock:
            self.workers[worker_id] = handle
            if not dedicated:
                self.starting += 1
        self._on_worker_started(handle)
        if on_handle is not None:
            on_handle(handle)
        msg = {
            "type": "start_worker",
            "wid_hex": worker_id.hex(),
            "dedicated": dedicated,
            "env": {},
        }
        if bootstrap is not None:
            # the agent delivers it: in-memory via its zygote fork, or on
            # the worker's dial-in if it had to cold-spawn
            msg["bootstrap"] = bootstrap
        if conda_spec is not None:
            # conda envs are HOST-local: the agent resolves/creates the
            # env on its own machine and spawns under its python
            msg["conda"] = conda_spec
        # BEFORE the frame leaves: a bootstrapped fork on the agent can
        # register before channel_send returns, and on_worker_ready skips
        # the boot sample when spawned_at is still 0
        handle.spawned_at = time.monotonic()
        self.channel_send(msg)
        return handle

    def worker_by_wid(self, wid: bytes) -> Optional[WorkerHandle]:
        with self._lock:
            return self.workers.get(WorkerID(wid))

    def _abort_pending(self, reason: str) -> None:
        """Wake every transfer blocked on this channel with an error."""
        with self._pending_lock:
            for state in self._pending.values():
                state["error"] = reason
                state["event"].set()
            self._pending.clear()

    def mark_dead(self) -> None:
        self.alive = False
        self._abort_pending("node died")
        for h in self.workers.values():
            if isinstance(h.proc, RemoteProc):
                h.proc.returncode = 1

    def shutdown(self, unlink_store: bool = True) -> None:
        self.channel_send({"type": "shutdown"})
        self.alive = False
        # in-flight pulls/pushes will never get replies once the channel
        # closes; waking them here keeps driver shutdown from parking a
        # transfer thread for its full timeout
        self._abort_pending("node shut down")
        try:
            self.channel.close()
        except Exception:
            pass
