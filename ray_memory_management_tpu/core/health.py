"""Declarative SLO/alert rules engine over the head tsdb.

A rule is ``(expr over window, threshold, for_duration, severity)``:
the expr is a small query tuple evaluated against utils/tsdb.py on
every heartbeat tick —

    ("rate",     series, window_s)      increments/s over the window
    ("delta",    series, window_s)      increments over the window
    ("value",    series)                last sampled value
    ("quantile", series, q, window_s)   quantile_over_time

— and the alert FIRES only after the expr has breached the threshold
continuously for ``for_duration_s`` (hysteresis against one-tick
spikes), then RESOLVES on the first non-breaching tick. Every
transition is a structured ``events.emit(HEALTH_ALERT)`` plus a
structlog record carrying the offending series' recent samples (the
evidence window) and, when the runtime can attribute one, an exemplar
task/trace id — so an alert pivots straight into ``rmt trace`` /
``rmt logs`` / ``rmt profile``.

The default rule pack covers the failure modes earlier PRs made
countable; every series name it references must exist in
``metrics_defs.DEFS`` (the ``alert-rule-registry`` rmtcheck rule fails
``rmt check`` on drift). ``rmt doctor`` runs the same pack plus the
static probes at the bottom of this module and prints a ranked
diagnosis (scripts/cli.py).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..utils import events as _events
from ..utils import structlog as _structlog
from ..utils import tsdb as _tsdb

HEALTH_ALERT = "HEALTH_ALERT"

# ranking order for doctor / get_alerts (higher = first)
_SEVERITY_RANK = {"ERROR": 2, "WARNING": 1, "INFO": 0}

_RESOLVED_KEEP = 256  # resolved-alert history ring


class Rule:
    """One declarative SLO rule. ``expr`` is a query tuple (module
    docstring); ``cmp`` is ">" (breach above threshold) or "<"."""

    def __init__(self, name: str, expr: Tuple, threshold: float,
                 for_duration_s: float, severity: str,
                 description: str = "", cmp: str = ">"):
        if expr[0] not in ("rate", "delta", "value", "quantile"):
            raise ValueError(f"unknown expr kind {expr[0]!r}")
        if cmp not in (">", "<"):
            raise ValueError("cmp must be '>' or '<'")
        self.name = name
        self.expr = expr
        self.threshold = float(threshold)
        self.for_duration_s = float(for_duration_s)
        self.severity = severity
        self.description = description
        self.cmp = cmp

    @property
    def series(self) -> str:
        return self.expr[1]

    @property
    def window_s(self) -> float:
        if self.expr[0] == "value":
            return 0.0
        return float(self.expr[-1])

    def describe_expr(self) -> str:
        kind = self.expr[0]
        if kind == "value":
            return f"value({self.series})"
        if kind == "quantile":
            return (f"quantile({self.series}, q={self.expr[2]}, "
                    f"{self.expr[3]:g}s)")
        return f"{kind}({self.series}, {self.expr[2]:g}s)"


def default_rules() -> List[Rule]:
    """The shipped rule pack. Thresholds are deliberately low-water —
    these are 'someone should look' signals, not paging SLOs — and
    for_duration spans a few heartbeat ticks so a single bad tick
    never fires."""
    gib = 1024.0 ** 3
    return [
        Rule("task-failure-rate",
             ("rate", "rmt_tasks_failed_total", 30.0), 0.5, 1.0, "ERROR",
             "Tasks reaching FAILED (post-retry) faster than 0.5/s — "
             "app errors, dead workers, or a poisoned node."),
        Rule("serve-shed-rate",
             ("rate", "rmt_serve_shed_total", 30.0), 0.5, 1.0, "WARNING",
             "Serve requests shed (backpressure timeout / no replicas / "
             "queue full) — capacity or routing problem."),
        Rule("kv-backpressure",
             ("rate", "rmt_serve_kv_backpressure_total", 30.0), 0.5, 1.0,
             "WARNING",
             "KV page-pool exhaustion deferring admissions — the paged "
             "cache is at capacity; decode latency will follow."),
        Rule("heartbeat-resyncs",
             ("rate", "rmt_heartbeat_resyncs_total", 60.0), 0.2, 2.0,
             "WARNING",
             "Delta-heartbeat sequence gaps forcing full resyncs — "
             "flaky agent channels or head overload."),
        Rule("quota-throttle",
             ("rate", "rmt_job_quota_rejections_total", 30.0), 0.5, 2.0,
             "WARNING",
             "Job quota rejections — some tenant is starved against its "
             "object/device byte budget."),
        Rule("spill-failures",
             ("rate", "rmt_spill_errors_total", 60.0), 0.2, 2.0, "ERROR",
             "Spill-storage IO errors — external storage degrading; "
             "memory pressure relief is at risk."),
        Rule("worker-exit-rate",
             ("rate", "rmt_workers_exited_total", 30.0), 1.0, 2.0,
             "WARNING",
             "Worker processes exiting faster than 1/s — crash loop, "
             "OOM kills, or churny preemption."),
        Rule("head-rss-ceiling",
             ("value", "rmt_proc_rss_bytes"), 8.0 * gib, 5.0, "ERROR",
             "Head-process RSS past 8 GiB — control-plane state is "
             "outgrowing the host; expect allocator stalls next."),
    ]


class HealthEngine:
    """Evaluates a rule list against a TSDB on each tick and tracks
    per-rule alert lifecycle (inactive -> breaching -> firing ->
    resolved). The exemplar callback (wired by the runtime) maps a
    firing rule to a {task_id, trace_id} pivot when one is
    attributable."""

    def __init__(self, store: _tsdb.TSDB,
                 rules: Optional[List[Rule]] = None,
                 exemplar: Optional[Callable[[Rule], Optional[dict]]]
                 = None):
        self._store = store
        self._rules = list(default_rules() if rules is None else rules)
        self._exemplar = exemplar
        self._lock = threading.Lock()
        # per rule-name: {"breach_since": ts|None, "alert": dict|None}
        self._state: Dict[str, dict] = {}
        self._resolved: deque = deque(maxlen=_RESOLVED_KEEP)

    @property
    def rules(self) -> List[Rule]:
        return list(self._rules)

    def eval_expr(self, rule: Rule,
                  now: Optional[float] = None) -> Optional[float]:
        kind = rule.expr[0]
        s = self._store
        if kind == "rate":
            return s.rate(rule.series, rule.expr[2], now=now)
        if kind == "delta":
            return s.delta(rule.series, rule.expr[2], now=now)
        if kind == "value":
            return s.last(rule.series)
        return s.quantile_over_time(rule.series, rule.expr[2],
                                    rule.expr[3], now=now)

    def _breaches(self, rule: Rule, value: Optional[float]) -> bool:
        if value is None:
            return False
        if rule.cmp == ">":
            return value > rule.threshold
        return value < rule.threshold

    def evaluate(self, now: Optional[float] = None) -> None:
        """One tick: evaluate every rule, firing/resolving alerts.
        Runs on the heartbeat thread; must never raise."""
        ts = time.time() if now is None else now
        for rule in self._rules:
            try:
                value = self.eval_expr(rule, now=now)
            except Exception:
                continue  # a broken expr must not stall its siblings
            breach = self._breaches(rule, value)
            with self._lock:
                st = self._state.setdefault(
                    rule.name, {"breach_since": None, "alert": None})
                if breach:
                    if st["breach_since"] is None:
                        st["breach_since"] = ts
                    alert = st["alert"]
                    if alert is not None:
                        alert["value"] = value  # keep it current
                        continue
                    if ts - st["breach_since"] < rule.for_duration_s:
                        continue
                    alert = self._make_alert(rule, value,
                                             st["breach_since"], ts)
                    st["alert"] = alert
                else:
                    st["breach_since"] = None
                    alert = st["alert"]
                    if alert is None:
                        continue
                    st["alert"] = None
                    alert["state"] = "resolved"
                    alert["resolved_ts"] = ts
                    self._resolved.append(alert)
            # emit OUTSIDE self._lock: events/structlog take their own
            self._emit(rule, alert)

    def _make_alert(self, rule: Rule, value: float, since: float,
                    ts: float) -> dict:
        evidence = self._store.tail(rule.series, n=8)
        exemplar = None
        if self._exemplar is not None:
            try:
                exemplar = self._exemplar(rule)
            except Exception:
                exemplar = None
        return {
            "rule": rule.name,
            "severity": rule.severity,
            "state": "firing",
            "expr": rule.describe_expr(),
            "series": rule.series,
            "window_s": rule.window_s,
            "for_duration_s": rule.for_duration_s,
            "threshold": rule.threshold,
            "value": value,
            "breach_since": since,
            "fired_ts": ts,
            "resolved_ts": None,
            "evidence": evidence,
            "exemplar": exemplar,
            "description": rule.description,
        }

    def _emit(self, rule: Rule, alert: dict) -> None:
        state = alert["state"]
        msg = (f"health alert {state}: {rule.name} "
               f"({alert['expr']} = {alert['value']:g}, threshold "
               f"{rule.cmp} {rule.threshold:g})")
        severity = rule.severity if state == "firing" else _events.INFO
        fields = {
            "rule": rule.name, "state": state, "expr": alert["expr"],
            "value": alert["value"], "threshold": rule.threshold,
            "evidence": list(alert["evidence"]),
        }
        ex = alert.get("exemplar") or {}
        if ex.get("task_id"):
            fields["task_id"] = ex["task_id"]
        if ex.get("trace_id"):
            fields["trace_id"] = ex["trace_id"]
        try:
            _events.emit(HEALTH_ALERT, msg, severity=severity,
                         source="health", **fields)
        except Exception:
            pass
        try:
            level = "INFO" if state == "resolved" else (
                rule.severity if rule.severity in _structlog.LEVELS
                else "WARNING")
            _structlog.emit(level, msg, logger="rmt.health")
        except Exception:
            pass
        try:
            from . import metrics_defs as mdefs
            mdefs.health_alerts().inc(
                tags={"rule": rule.name, "severity": rule.severity})
        except Exception:
            pass

    def alerts(self, state: Optional[str] = None,
               limit: int = 100) -> List[dict]:
        """Current + historical alerts, most severe first (then most
        recent). ``state`` filters to 'firing' or 'resolved'."""
        with self._lock:
            firing = [dict(st["alert"]) for st in self._state.values()
                      if st["alert"] is not None]
            resolved = [dict(a) for a in self._resolved]
        rows: List[dict] = []
        if state in (None, "firing"):
            rows.extend(firing)
        if state in (None, "resolved"):
            rows.extend(resolved)
        rows.sort(key=lambda a: (
            a["state"] != "firing",
            -_SEVERITY_RANK.get(a["severity"], 0),
            -(a["fired_ts"] or 0.0)))
        return rows[: max(0, int(limit))]


# -- static probes (rmt doctor) ------------------------------------------------
# One-shot checks that don't fit the rate-over-window rule shape: direct
# reads of runtime state plus recent-delta sniffs on the tsdb. Each
# finding is {"probe", "severity", "summary"}; everything is defensive
# getattr — doctor must degrade, never crash, on a partial runtime.

def run_probes(rt: Any, store: _tsdb.TSDB) -> List[dict]:
    findings: List[dict] = []
    findings.extend(_probe_dead_nodes(rt))
    findings.extend(_probe_stuck_leases(store))
    findings.extend(_probe_unsealed_creates(store))
    findings.extend(_probe_degraded_spill(store))
    findings.extend(_probe_quota_starved(store))
    findings.sort(key=lambda f: -_SEVERITY_RANK.get(f["severity"], 0))
    return findings


def _probe_dead_nodes(rt: Any) -> List[dict]:
    try:
        nodes = list(getattr(rt, "nodes", {}).values())
        dead = [nm for nm in nodes if not getattr(nm, "alive", True)]
    except Exception:
        return []
    if not dead:
        return []
    ids = ", ".join(
        getattr(nm, "node_id", b"").hex()[:12] for nm in dead[:4])
    return [{"probe": "dead-nodes", "severity": "ERROR",
             "summary": f"{len(dead)} node(s) marked dead ({ids}); "
                        "their leases were re-queued but capacity is "
                        "gone until they rejoin."}]


def _probe_stuck_leases(store: _tsdb.TSDB) -> List[dict]:
    try:
        depth = store.last("rmt_scheduler_queue_depth")
        placed = store.rate("rmt_scheduler_placements_total", 60.0)
        span = store.span("rmt_scheduler_placements_total", 60.0)
    except Exception:
        return []
    if depth and depth > 0 and span >= 5.0 and placed == 0.0:
        return [{"probe": "stuck-leases", "severity": "WARNING",
                 "summary": f"dispatch queues hold {depth:g} task(s) "
                            "but no placement landed in the last "
                            f"{span:.0f}s — leases may be stuck on a "
                            "wedged or saturated node."}]
    return []


def _probe_unsealed_creates(store: _tsdb.TSDB) -> List[dict]:
    try:
        d = store.delta("rmt_stale_creates_aborted_total", 300.0)
    except Exception:
        return []
    if d > 0:
        return [{"probe": "unsealed-creates", "severity": "WARNING",
                 "summary": f"{d:g} unsealed create(s) aborted in the "
                            "last 5 min — fetchers are dying between "
                            "create and seal."}]
    return []


def _probe_degraded_spill(store: _tsdb.TSDB) -> List[dict]:
    try:
        entered = store.delta("rmt_spill_degraded_total", 300.0)
        total = store.last("rmt_spill_degraded_total")
    except Exception:
        return []
    if entered > 0:
        return [{"probe": "degraded-spill", "severity": "ERROR",
                 "summary": "the store entered spill-degraded mode in "
                            "the last 5 min (persistent spill-storage "
                            "failure) — objects are pinned in memory "
                            "under backpressure."}]
    if total and total > 0:
        return [{"probe": "degraded-spill", "severity": "WARNING",
                 "summary": f"spill-degraded mode has triggered "
                            f"{total:g} time(s) this run — spill "
                            "storage has a history of failing."}]
    return []


def _probe_quota_starved(store: _tsdb.TSDB) -> List[dict]:
    try:
        d = store.delta("rmt_job_quota_rejections_total", 300.0)
    except Exception:
        return []
    if d > 0:
        return [{"probe": "quota-starved-jobs", "severity": "WARNING",
                 "summary": f"{d:g} quota rejection(s) in the last "
                            "5 min — at least one job is starved "
                            "against its byte budget."}]
    return []
