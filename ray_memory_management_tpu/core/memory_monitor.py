"""Node memory monitor: kill workloads before the OS OOM-killer does.

The reference's ``MemoryMonitor`` (src/ray/common/memory_monitor.h:48,
kill callback wired in node_manager.cc:336-339,2409): sample host memory
usage on an interval; past the threshold, invoke a kill callback that
terminates the most-recently-started task's worker (newest-first
preserves the oldest — most-progressed — work, the reference's retry-
friendly policy; the killed task retries under its normal budget).
"""

from __future__ import annotations

import threading
from typing import Callable, Optional, Tuple

from ..utils import structlog

log = structlog.get_logger(__name__)


def system_memory_usage() -> Tuple[int, int]:
    """(used_bytes, total_bytes) from /proc/meminfo — available-based,
    like memory_monitor.h's cgroup/proc reads."""
    total = available = None
    with open("/proc/meminfo") as f:
        for line in f:
            if line.startswith("MemTotal:"):
                total = int(line.split()[1]) * 1024
            elif line.startswith("MemAvailable:"):
                available = int(line.split()[1]) * 1024
            if total is not None and available is not None:
                break
    if total is None or available is None:
        raise RuntimeError("could not read /proc/meminfo")
    return total - available, total


class MemoryMonitor:
    def __init__(self,
                 kill_callback: Callable[[], bool],
                 usage_threshold: float = 0.95,
                 check_interval_s: float = 1.0,
                 usage_fn: Callable[[], Tuple[int, int]] = None):
        """``kill_callback`` should relieve pressure (kill one worker)
        and return True if it killed something; ``usage_fn`` is
        injectable for tests."""
        self.kill_callback = kill_callback
        self.usage_threshold = usage_threshold
        self.check_interval_s = check_interval_s
        self.usage_fn = usage_fn or system_memory_usage
        self.num_kills = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()  # allow stop() → start() restart
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="rmt-memory-monitor")
        self._thread.start()

    def is_over_threshold(self) -> bool:
        used, total = self.usage_fn()
        return total > 0 and used / total >= self.usage_threshold

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                if self.is_over_threshold():
                    if self.kill_callback():
                        self.num_kills += 1
                        log.warning(
                            "memory pressure: killed a worker to free "
                            "memory (%d kills total)", self.num_kills)
                        from ..utils import events

                        events.emit(
                            "WORKER_OOM_KILLED",
                            "memory pressure: killed a worker",
                            severity=events.ERROR, source="memory_monitor",
                            kills=self.num_kills)
            except Exception:
                log.exception("memory monitor check failed")
            self._stop.wait(self.check_interval_s)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None


def make_newest_task_killer(runtime) -> Callable[[], bool]:
    """The reference's policy: prefer killing the task that started most
    recently (node_manager.cc retriable-task-first). Returns a callback
    that terminates one busy non-actor worker's process; the owner's
    retry logic resubmits the task."""

    def kill_one() -> bool:
        with runtime._lock:
            node_managers = list(runtime.nodes.values())
        candidates = []  # (start order proxy, handle)
        for nm in node_managers:
            if not nm.alive:
                continue
            for handle in list(nm.workers.values()):
                if handle.actor_id is not None or not handle.inflight:
                    continue
                candidates.append(handle)
        if not candidates:
            return False
        victim = candidates[-1]  # newest-started worker
        try:
            victim.proc.terminate()
            return True
        except Exception:
            return False

    return kill_one
