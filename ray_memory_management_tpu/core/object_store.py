"""Per-node object store: shared-memory tier with spill/restore to disk.

Combines three reference components into the TPU-host store model:
  - plasma store semantics (create/seal/get/release/delete) come from the
    native shm store (native/shmstore.cpp — see its header for the mapping);
  - spilling orchestration mirrors the raylet's LocalObjectManager
    (src/ray/raylet/local_object_manager.h:99,111,180): when an allocation
    fails or usage passes ``object_spilling_threshold``, LRU unreferenced
    objects are written to external storage by IO threads and deleted from
    shm; a get() of a spilled object restores it transparently;
  - the owner-side in-process memory store for small objects
    (src/ray/core_worker/store_provider/memory_store/memory_store.h:43) lives
    in the driver/worker runtime, not here.
"""

from __future__ import annotations

import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional

from ..config import Config
from ..exceptions import ObjectStoreFullError
from ..native import ShmStore, ShmStoreFullError
from . import external_storage as ext
from ..serialization import SerializedObject


class NodeObjectStore:
    """The store owned by one (virtual) node. Thread-safe."""

    def __init__(self, name: str, config: Optional[Config] = None,
                 create: bool = True):
        self.config = config or Config()
        self.name = name
        capacity = self.config.object_store_memory
        self.shm = ShmStore(name, capacity, create=create)
        self._spill_lock = threading.Lock()
        self._spilled: Dict[bytes, str] = {}  # object_id -> url
        # ensure_resident pins: object_id -> (ref-holding view, expiry)
        self._pinned: Dict[bytes, tuple] = {}
        # scope the spill tier per store: several stores on one host (head +
        # node agents) spill the SAME object ids (pushed copies) — in a
        # shared directory one store's restore/delete would remove another
        # store's spill file
        base = self.config.object_store_fallback_directory.rstrip("/")
        self._storage = ext.storage_for_uri(base + "/" + name.strip("/"))
        self._io = ThreadPoolExecutor(
            max_workers=self.config.max_io_workers,
            thread_name_prefix=f"io-{name.strip('/')}",
        )

    # -- write path -----------------------------------------------------------
    def put_serialized(self, object_id: bytes, serialized: SerializedObject) -> None:
        buf = self._create_with_spill(object_id, serialized.total_size)
        serialized.write_into(buf)
        self.shm.seal(object_id)

    def put_bytes(self, object_id: bytes, data) -> None:
        buf = self._create_with_spill(object_id, len(data))
        buf[:] = data
        self.shm.seal(object_id)

    def create(self, object_id: bytes, size: int) -> memoryview:
        return self._create_with_spill(object_id, size)

    def seal(self, object_id: bytes) -> None:
        self.shm.seal(object_id)

    def _create_with_spill(self, object_id: bytes, size: int) -> memoryview:
        """Allocate, spilling LRU objects on pressure — the CreateRequestQueue
        + spill fallback path (plasma create_request_queue.h:32 +
        local_object_manager.h:99)."""
        for _ in range(16):
            try:
                return self.shm.create(object_id, size)
            except ShmStoreFullError:
                freed = self._spill_for(max(size, self.config.min_spilling_size))
                if freed == 0:
                    # ensure_resident pins are a read-race grace, not a
                    # lease: under real pressure they must yield (readers
                    # that miss re-request and re-ensure)
                    if self._release_all_pins():
                        continue
                    raise ObjectStoreFullError(
                        f"store {self.name}: cannot allocate {size} bytes; "
                        f"usage={self.shm.usage()}, nothing spillable"
                    )
        raise ObjectStoreFullError(f"store {self.name}: allocation retry limit")

    def _release_all_pins(self) -> bool:
        """Drop every ensure_resident pin; returns True if any was held."""
        with self._spill_lock:
            victims = list(self._pinned.items())
            self._pinned.clear()
        for oid, (view, _) in victims:
            del view
            self.shm.release(oid)
        return bool(victims)

    def _spill_for(self, need_bytes: int) -> int:
        """Spill at least ``need_bytes`` of LRU unreferenced objects; returns
        bytes freed."""
        with self._spill_lock:
            candidates = self.shm.evict_candidates(need_bytes)
            freed = 0
            n_spilled = 0
            futures = []
            views = {}
            for oid in candidates:
                view = self.shm.get(oid, inc_ref=True)
                if view is None:
                    continue
                views[oid] = view
                futures.append((oid, self._io.submit(
                    self._storage.spill, oid, view)))
            for oid, fut in futures:
                try:
                    url = fut.result()
                except Exception:
                    self.shm.release(oid)
                    continue
                self._spilled[oid] = url
                view = views.pop(oid)
                nbytes = view.nbytes
                del view
                self.shm.release(oid)
                if self.shm.delete(oid):
                    freed += nbytes
                    n_spilled += 1
                else:
                    # a reader raced us; keep the spill copy, reclaim later
                    pass
            if freed:
                from ..utils import events

                events.emit("OBJECT_SPILLED",
                            f"spilled {freed} bytes to external storage",
                            source="object_store", bytes=freed,
                            objects=n_spilled)
            return freed

    def ensure_resident(self, object_id: bytes,
                        grace_s: float = 60.0) -> bool:
        """Make the object shm-resident (restoring from spill if needed) and
        pin it for ``grace_s`` so another process's direct shm read cannot
        race a re-spill/eviction. The pin is a held refcount, released by
        ``sweep_pins``. This is what lets the owner answer "local" to a
        worker truthfully (the restore half of local_object_manager.h:111)."""
        view = self.get(object_id)  # restores; takes a reader ref
        if view is None:
            return False
        import time as _time

        with self._spill_lock:
            prev = self._pinned.pop(object_id, None)
            self._pinned[object_id] = (view, _time.monotonic() + grace_s)
        if prev is not None:
            self.shm.release(object_id)  # drop the superseded pin's ref
        return True

    def sweep_pins(self) -> None:
        """Release expired ensure_resident pins (called from the owner's
        heartbeat loop / the agent's reap loop)."""
        import time as _time

        now = _time.monotonic()
        with self._spill_lock:
            expired = [oid for oid, (_, exp) in self._pinned.items()
                       if exp <= now]
            victims = [(oid, self._pinned.pop(oid)) for oid in expired]
        for oid, (view, _) in victims:
            del view
            self.shm.release(oid)

    # -- read path ------------------------------------------------------------
    def get(self, object_id: bytes) -> Optional[memoryview]:
        """Zero-copy view, restoring from spill if needed. None if absent."""
        view = self.shm.get(object_id)
        if view is not None:
            return view
        url = self._spilled.get(object_id)
        if url is None:
            return None
        data = self._storage.restore(object_id, url)
        try:
            buf = self._create_with_spill(object_id, len(data))
        except ValueError:
            # someone restored it concurrently
            return self.shm.get(object_id)
        buf[:] = data
        self.shm.seal(object_id)
        with self._spill_lock:
            self._spilled.pop(object_id, None)
        self._storage.delete(url)
        return self.shm.get(object_id)

    def contains(self, object_id: bytes) -> bool:
        return self.shm.contains(object_id) or object_id in self._spilled

    def release(self, object_id: bytes) -> None:
        self.shm.release(object_id)

    def delete(self, object_id: bytes) -> None:
        with self._spill_lock:
            url = self._spilled.pop(object_id, None)
        if url:
            self._storage.delete(url)
        self.shm.delete(object_id)

    def usage(self):
        return self.shm.usage()

    def spilled_count(self) -> int:
        return len(self._spilled)

    def close(self, unlink: bool = False) -> None:
        self._io.shutdown(wait=False)
        self.shm.close()
        if unlink:
            ShmStore.unlink(self.name)


class StoreClient:
    """A read/write client to some node's store from another process on the
    host (what workers hold; the plasma-client analog)."""

    def __init__(self, name: str):
        self.shm = ShmStore(name, create=False)

    def get(self, object_id: bytes) -> Optional[memoryview]:
        return self.shm.get(object_id)

    def put_serialized(self, object_id: bytes, serialized: SerializedObject) -> None:
        try:
            buf = self.shm.create(object_id, serialized.total_size)
        except ValueError:
            return  # already present (e.g. task retry re-producing a return)
        serialized.write_into(buf)
        self.shm.seal(object_id)

    def release(self, object_id: bytes) -> None:
        self.shm.release(object_id)

    def contains(self, object_id: bytes) -> bool:
        return self.shm.contains(object_id)

    def close(self):
        self.shm.close()
