"""Per-node object store: shared-memory tier with spill/restore to disk.

Combines three reference components into the TPU-host store model:
  - plasma store semantics (create/seal/get/release/delete) come from the
    native shm store (native/shmstore.cpp — see its header for the mapping);
  - spilling orchestration mirrors the raylet's LocalObjectManager
    (src/ray/raylet/local_object_manager.h:99,111,180): when an allocation
    fails or usage passes ``object_spilling_threshold``, LRU unreferenced
    objects are written to external storage by IO threads and deleted from
    shm; a get() of a spilled object restores it transparently;
  - the owner-side in-process memory store for small objects
    (src/ray/core_worker/store_provider/memory_store/memory_store.h:43) lives
    in the driver/worker runtime, not here.

This shm tier is also the landing zone of DEVICE demotions: when the
HBM tier (core/device_store.py) runs past its budget, LRU device
objects arrive here through the same create/seal path as any put
(optionally bf16-downcast via the codec demotion envelope), and from
here the existing spill plane takes over — HBM → shm → spill, each
tier evicting into the next.

Allocation under pressure WAITS (bounded) instead of failing: capacity held
by in-flight reader refs (executing tasks) or residency pins drains within
milliseconds, and failing immediately turns a transient full store into a
spurious ObjectLostError — the reference's plasma CreateRequestQueue blocks
clients the same way (src/ray/object_manager/plasma/create_request_queue.h:32).
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional

from ..config import Config
from ..exceptions import ObjectStoreFullError
from ..native import ShmStore, ShmStoreFullError
from . import codec as wire_codec
from . import external_storage as ext
from ..serialization import SerializedObject
from ..utils import faults, timeline, tracing
from ..utils.integrity import crc32
from ..utils.retry import RetryExhausted, RetryPolicy


class NodeObjectStore:
    """The store owned by one (virtual) node. Thread-safe."""

    def __init__(self, name: str, config: Optional[Config] = None,
                 create: bool = True):
        self.config = config or Config()
        self.name = name
        capacity = self.config.object_store_memory
        self.shm = ShmStore(name, capacity, create=create)
        self._spill_lock = threading.Lock()
        # per-object restore claims: oid -> Event set when the restore ends.
        # A dict (not one big lock) so restores of DIFFERENT objects run
        # concurrently and a restore parked in the allocation wait never
        # stalls an unrelated get()
        self._restore_mu = threading.Lock()
        self._restoring: Dict[bytes, threading.Event] = {}
        self._spilled: Dict[bytes, str] = {}  # object_id -> url
        # broadcast on any object becoming readable or disappearing
        # (seal/put/restore/delete): racing fetches parked in
        # transfer.create_or_wait wake immediately instead of poll-ticking.
        # Cross-PROCESS seals (StoreClient writes through the shm segment
        # directly) can't notify — waiters keep a short poll backstop.
        self._change_cond = threading.Condition()
        # ensure_resident pins: object_id -> (ref-holding view, expiry)
        self._pinned: Dict[bytes, tuple] = {}
        # scope the spill tier per store: several stores on one host (head +
        # node agents) spill the SAME object ids (pushed copies) — in a
        # shared directory one store's restore/delete would remove another
        # store's spill file
        base = self.config.object_store_fallback_directory.rstrip("/")
        self._storage = ext.storage_for_uri(base + "/" + name.strip("/"),
                                            config=self.config)
        self._io = ThreadPoolExecutor(
            max_workers=self.config.max_io_workers,
            thread_name_prefix=f"io-{name.strip('/')}",
        )
        # lazy full-object CRC32 cache (NOT computed at seal: an eager crc
        # would serialize a full extra pass onto the put path, halving put
        # bandwidth; the first transfer/spill that needs it pays it once)
        self._crc: Dict[bytes, int] = {}
        # crc recorded at spill-write time over the STORED bytes
        # (compressed when a codec applied), verified at restore BEFORE
        # decode — a worn spill volume corrupting at rest is a detected
        # loss, not poison; the decoded payload is then still checked
        # against the full-object crc (verify after decode)
        self._spill_crc: Dict[bytes, int] = {}
        # oid -> codec name for spill copies written compressed (same
        # knob as the wire: transfer_compression; no negotiation needed
        # — this process wrote it, this process decodes it)
        self._spill_codec: Dict[bytes, str] = {}
        # preference list for spill encoding (None when compression is
        # off or the named codec is not importable); the per-payload
        # pick runs through the same probe as the wire
        self._spill_codecs = wire_codec.client_codecs(self.config)
        # unsealed creates by start time: a fetcher that dies mid-pull
        # leaks its allocation until restart without sweep_unsealed()
        self._unsealed: Dict[bytes, float] = {}
        # 0.0 = spilling healthy; else monotonic time before which spill
        # IO is suspended (degraded mode: objects stay in memory under
        # backpressure; a probe decides recovery)
        self._spill_degraded_until = 0.0

    def _notify_object_change(self) -> None:
        with self._change_cond:
            self._change_cond.notify_all()

    def wait_for_object_change(self, timeout: float) -> None:
        """Block until SOME object is sealed/deleted/restored in this
        process (or ``timeout`` elapses). Callers re-check their own
        predicate — this is a wakeup, not a promise about a specific oid."""
        with self._change_cond:
            self._change_cond.wait(timeout)

    # -- write path -----------------------------------------------------------
    def put_serialized(self, object_id: bytes, serialized: SerializedObject) -> None:
        buf = self._create_with_spill(object_id, serialized.total_size)
        serialized.write_into(buf)
        self._unsealed.pop(object_id, None)
        self.shm.seal(object_id)
        self._notify_object_change()

    def put_bytes(self, object_id: bytes, data) -> None:
        buf = self._create_with_spill(object_id, len(data))
        buf[:] = data
        self._unsealed.pop(object_id, None)
        self.shm.seal(object_id)
        self._notify_object_change()

    def create(self, object_id: bytes, size: int,
               timeout_s: Optional[float] = None) -> memoryview:
        """Allocate; ``timeout_s`` overrides the config full-store wait
        budget (e.g. the agent's push handler uses a SHORT budget so a
        pressured push nacks retryable quickly instead of parking the
        object plane)."""
        return self._create_with_spill(object_id, size, timeout_s)

    def seal(self, object_id: bytes) -> None:
        self._unsealed.pop(object_id, None)
        self.shm.seal(object_id)
        self._notify_object_change()

    def checksum(self, object_id: bytes) -> Optional[int]:
        """Full-object CRC32, computed lazily and cached until delete.
        Served in transfer replies so pullers can verify end to end; None
        when the object is absent. Lazy (first serve/spill pays it, not
        the put path) because an eager crc at seal would add a full
        serial pass to every put — measured at ~half the put-path
        bandwidth for large objects."""
        c = self._crc.get(object_id)
        if c is not None:
            return c
        view = self.shm.get(object_id)
        if view is not None:
            try:
                c = crc32(view)
            finally:
                del view
                self.shm.release(object_id)
        else:
            with self._spill_lock:
                # _spill_crc covers the STORED bytes — only the
                # full-object crc when the copy was written raw
                c = (None if object_id in self._spill_codec
                     else self._spill_crc.get(object_id))
                url = self._spilled.get(object_id)
            if c is None and url is not None:
                try:
                    # _spill_read verifies + DECODES (compressed copies)
                    c = crc32(self._spill_read(object_id, url))
                except Exception:  # noqa: BLE001 — concurrently deleted
                    return None
        if c is not None:
            self._crc[object_id] = c
        return c

    def _create_with_spill(self, object_id: bytes, size: int,
                           timeout_s: Optional[float] = None) -> memoryview:
        """Allocate, spilling LRU objects on pressure — the CreateRequestQueue
        + spill fallback path (plasma create_request_queue.h:32 +
        local_object_manager.h:99). When nothing is spillable (capacity held
        by executing tasks' reader refs), waits up to
        ``object_store_full_timeout_s`` (or the caller's ``timeout_s``
        override) for refs to drain rather than failing a transiently-full
        store."""
        if timeout_s is None:
            timeout_s = self.config.object_store_full_timeout_s
        deadline = time.monotonic() + timeout_s
        # residency pins are a read-race grace, not a lease: under sustained
        # pressure they yield (readers that miss re-request and re-ensure),
        # but only after a short delay so promised reads usually land first
        # (never later than half the full-store budget, so short timeouts
        # still get the pin-break before they expire)
        pin_break_at = time.monotonic() + min(0.5, timeout_s / 2)
        while True:
            try:
                buf = self.shm.create(object_id, size)
                self._unsealed[object_id] = time.monotonic()
                return buf
            except ShmStoreFullError:
                pass
            if time.monotonic() >= deadline:
                raise ObjectStoreFullError(
                    f"store {self.name}: cannot allocate {size} bytes within "
                    f"{timeout_s:.1f}s; usage={self.shm.usage()}"
                )
            if self._spill_for(max(size, self.config.min_spilling_size)):
                continue
            if time.monotonic() >= pin_break_at and self._release_all_pins():
                continue
            time.sleep(0.02)

    def _release_all_pins(self) -> bool:
        """Drop every ensure_resident pin; returns True if any was held."""
        with self._spill_lock:
            victims = list(self._pinned.items())
            self._pinned.clear()
        for oid, (view, _) in victims:
            del view
            self.shm.release(oid)
        return bool(victims)

    def _spill_allowed(self) -> bool:
        """False while spill IO is suspended (degraded mode). Once the
        backoff window lapses, a probe write decides recovery: success
        resumes spilling loudly, failure re-arms the window."""
        if self._spill_degraded_until == 0.0:
            return True
        if time.monotonic() < self._spill_degraded_until:
            return False
        if self._storage.probe():
            self._spill_degraded_until = 0.0
            from ..utils import events

            events.emit("SPILL_RECOVERED",
                        f"store {self.name}: spill storage probe "
                        "succeeded, resuming spilling",
                        source="object_store")
            return True
        self._spill_degraded_until = (
            time.monotonic() + self.config.spill_degraded_backoff_s)
        return False

    def _enter_spill_degraded(self, err: BaseException) -> None:
        """Persistent spill failure: degrade to keeping objects in memory
        under backpressure — a LOUD event and counter, never a crash. New
        allocations now wait on reader refs / pins and eventually raise
        ObjectStoreFullError when truly full, which is the correct
        pressure signal for the caller's retry."""
        self._spill_degraded_until = (
            time.monotonic() + self.config.spill_degraded_backoff_s)
        from ..utils import events
        from . import metrics_defs as mdefs

        events.emit("SPILL_DEGRADED",
                    f"store {self.name}: spill storage failing "
                    f"persistently ({err!r}); keeping objects in memory "
                    f"under backpressure, re-probing in "
                    f"{self.config.spill_degraded_backoff_s:.0f}s",
                    severity=events.ERROR, source="object_store")
        mdefs.spill_degraded().inc()

    def _spill_io(self, object_id: bytes, view: memoryview) -> str:
        """One object's spill write under the unified RetryPolicy, with
        the ``spill.write`` fault site and a crc recorded for restore-time
        verification. Runs on an IO thread.

        When the movement-plane codec is on (transfer_compression), the
        spill copy is written COMPRESSED (above the same size threshold,
        behind the same compressibility probe as the wire): fewer disk
        bytes, and restore reads back proportionally less. Encoding
        happens once, outside the retry loop; the recorded spill crc
        covers the stored (compressed) bytes so restore verifies before
        decode, while the decoded object keeps its full-object crc in
        ``_crc`` (verify after decode)."""
        want = self._crc.get(object_id)
        if want is None:
            want = crc32(view)
            self._crc[object_id] = want
        payload: memoryview = view
        cname = None
        if self._spill_codecs is not None:
            if view.nbytes < self.config.transfer_compress_min_bytes:
                wire_codec.count_skip("below_threshold")
            else:
                cand, skip = wire_codec.choose_codec(
                    self._spill_codecs, wire_codec.available_codecs(),
                    view)
                if cand is None:
                    wire_codec.count_skip(skip)
                else:
                    try:
                        payload = memoryview(wire_codec.encode(view, cand))
                        cname = cand
                    except Exception:  # noqa: BLE001 — spill raw instead
                        payload = view
                        cname = None

        def once() -> str:
            try:
                act = faults.fire("spill.write")
                if act is not None:
                    if act.mode == "stall":
                        act.sleep()
                    elif act.mode in ("error", "drop"):
                        act.raise_()
                url = self._storage.spill(object_id, payload)
                if act is not None and act.mode in (
                        "corrupt", "corrupt-compressed"):
                    # overwrite the spill copy with a flipped byte — the
                    # in-memory object is NEVER touched; only the
                    # restore-time crc (over the STORED bytes, so it
                    # fires before any decode) can catch this
                    url = self._storage.spill(
                        object_id,
                        memoryview(faults.corrupt_bytes(payload)))
                return url
            except Exception:
                from . import metrics_defs as mdefs

                mdefs.spill_errors().inc(tags={"op": "write"})
                raise

        policy = RetryPolicy(
            max_attempts=self.config.spill_retry_attempts,
            base_backoff_s=self.config.spill_retry_backoff_s,
            plane="spill")
        t0 = time.time()
        url = policy.run(once)
        # spill-write span: usually pressure-driven (no task context), but
        # a spill forced under a traced task's allocation carries its trace
        timeline.record_event(
            f"spill::write::{object_id.hex()[:8]}", "spill", t0,
            time.time(), extra={"bytes": view.nbytes,
                                "stored_bytes": payload.nbytes,
                                "codec": cname or "identity"},
            trace=tracing.get_current())
        self._spill_crc[object_id] = (
            want if cname is None else crc32(payload))
        if cname is not None:
            self._spill_codec[object_id] = cname
        else:
            self._spill_codec.pop(object_id, None)
        return url

    def _spill_for(self, need_bytes: int) -> int:
        """Spill at least ``need_bytes`` of LRU unreferenced objects; returns
        bytes freed."""
        with self._spill_lock:
            if not self._spill_allowed():
                return 0
            candidates = self.shm.evict_candidates(need_bytes)
            freed = 0
            n_spilled = 0
            futures = []
            views = {}
            for oid in candidates:
                view = self.shm.get(oid, inc_ref=True)
                if view is None:
                    continue
                views[oid] = view
                futures.append((oid, self._io.submit(
                    self._spill_io, oid, view)))
            for oid, fut in futures:
                try:
                    url = fut.result()
                except Exception as e:  # noqa: BLE001 — retries exhausted
                    self.shm.release(oid)
                    self._enter_spill_degraded(e)
                    continue
                self._spilled[oid] = url
                view = views.pop(oid)
                nbytes = view.nbytes
                del view
                self.shm.release(oid)
                if self.shm.delete(oid):
                    freed += nbytes
                    n_spilled += 1
                else:
                    # a reader raced us; keep the spill copy, reclaim later
                    pass
            if freed:
                from ..utils import events
                from . import metrics_defs as mdefs

                events.emit("OBJECT_SPILLED",
                            f"spilled {freed} bytes to external storage",
                            source="object_store", bytes=freed,
                            objects=n_spilled)
                mdefs.objects_spilled().inc(n_spilled)
                mdefs.objects_spilled_bytes().inc(freed)
            return freed

    def make_room(self, need_bytes: int) -> int:
        """Spill until ``need_bytes`` could allocate; returns bytes freed.
        The make-room path behind a worker's direct shm put hitting a full
        store (the raylet-spills-for-plasma-creates flow,
        create_request_queue.h:32). Pin handling matches
        _create_with_spill: residency pins get a short grace before they
        are broken, so promised direct reads usually land first."""
        freed = self._spill_for(need_bytes)
        if freed:
            return freed
        time.sleep(min(0.5, self.config.object_store_full_timeout_s / 2))
        freed = self._spill_for(need_bytes)
        if freed == 0 and self._release_all_pins():
            freed = self._spill_for(need_bytes)
        return freed

    def ensure_resident(self, object_id: bytes,
                        grace_s: float = 60.0) -> bool:
        """Make the object shm-resident (restoring from spill if needed) and
        pin it for ``grace_s`` so another process's direct shm read cannot
        race a re-spill/eviction. The pin is a held refcount, released by
        ``sweep_pins``. This is what lets the owner answer "local" to a
        worker truthfully (the restore half of local_object_manager.h:111)."""
        view = self.get(object_id)  # restores; takes a reader ref
        if view is None:
            return False
        with self._spill_lock:
            prev = self._pinned.pop(object_id, None)
            self._pinned[object_id] = (view, time.monotonic() + grace_s)
        if prev is not None:
            self.shm.release(object_id)  # drop the superseded pin's ref
        return True

    def sweep_pins(self) -> None:
        """Release expired ensure_resident pins (called from the owner's
        heartbeat loop / the agent's reap loop)."""
        now = time.monotonic()
        with self._spill_lock:
            expired = [oid for oid, (_, exp) in self._pinned.items()
                       if exp <= now]
            victims = [(oid, self._pinned.pop(oid)) for oid in expired]
        for oid, (view, _) in victims:
            del view
            self.shm.release(oid)

    # -- read path ------------------------------------------------------------
    def get(self, object_id: bytes) -> Optional[memoryview]:
        """Zero-copy view, restoring from spill if needed. None if absent.

        The retry loop is deadline-based, not attempt-counted: under
        restore/spill thrash a reader can lose the wait on concurrent
        restores many times while the object is genuinely present
        (resident or spilled), and giving up early surfaces upstream as a
        spurious ObjectLostError."""
        timeout_s = self.config.object_store_full_timeout_s
        # waiting out another thread's in-flight restore is PRODUCTIVE and
        # gets the full per-restore budget each time it happens; the hard
        # deadline only backstops a wedged restorer so get() cannot spin
        # forever. Every non-wait branch below returns an authoritative
        # answer, so the loop only iterates through restore waits.
        hard_deadline = time.monotonic() + 4 * (timeout_s + 5.0)
        while True:
            view = self.shm.get(object_id)
            if view is not None:
                return view
            if time.monotonic() >= hard_deadline:
                return self.shm.get(object_id)
            with self._restore_mu:
                ev = self._restoring.get(object_id)
            if ev is not None:
                # another thread is restoring this object: wait it out,
                # then re-check shm (loop)
                ev.wait(timeout_s + 5.0)
                continue
            with self._spill_lock:
                spilled = object_id in self._spilled
            if not spilled:
                # a restore may have completed between our shm miss and the
                # spill-record check (moving the object file -> shm): the
                # re-check is what makes a hit authoritative; a miss with
                # no spill copy and no in-flight restore means absent
                return self.shm.get(object_id)
            with self._restore_mu:
                ev = self._restoring.get(object_id)
                owner = ev is None
                if owner:
                    ev = self._restoring[object_id] = threading.Event()
            if not owner:
                ev.wait(timeout_s + 5.0)
                continue
            try:
                return self._restore_into_shm(object_id)
            finally:
                with self._restore_mu:
                    self._restoring.pop(object_id, None)
                ev.set()

    def _spill_read(self, object_id: bytes, url: str) -> bytes:
        """One object's restore read under the unified RetryPolicy, with
        the ``spill.read`` fault site and crc verification against the
        spill-time checksum — computed over the STORED bytes, so a
        corrupt compressed copy is caught BEFORE the decoder runs; a
        compressed copy is then decoded and re-verified against the
        full-object crc (verify after decode). A mismatch that survives
        retries propagates as loss (RetryExhausted) — corrupted bytes
        are NEVER returned."""

        def once() -> bytes:
            try:
                act = faults.fire("spill.read")
                if act is not None:
                    if act.mode == "stall":
                        act.sleep()
                    elif act.mode in ("error", "drop"):
                        act.raise_()
                data = self._storage.restore(object_id, url)
                if act is not None and act.mode in (
                        "corrupt", "corrupt-compressed"):
                    data = faults.corrupt_bytes(data)
                want = self._spill_crc.get(object_id)
                if want is not None \
                        and self.config.transfer_verify_checksum \
                        and crc32(data) != want:
                    from . import metrics_defs as mdefs

                    mdefs.spill_errors().inc(tags={"op": "checksum"})
                    raise OSError(
                        f"spill payload checksum mismatch restoring "
                        f"{object_id.hex()[:12]} from {url}")
                cname = self._spill_codec.get(object_id)
                if cname is not None:
                    try:
                        data = wire_codec.decode(data, cname)
                    except wire_codec.CodecError as e:
                        from . import metrics_defs as mdefs

                        mdefs.spill_errors().inc(tags={"op": "checksum"})
                        raise OSError(
                            f"spill payload decode failed restoring "
                            f"{object_id.hex()[:12]} from {url}: "
                            f"{e}") from e
                    decoded_want = self._crc.get(object_id)
                    if decoded_want is not None \
                            and self.config.transfer_verify_checksum \
                            and crc32(data) != decoded_want:
                        from . import metrics_defs as mdefs

                        mdefs.spill_errors().inc(tags={"op": "checksum"})
                        raise OSError(
                            f"decoded spill payload checksum mismatch "
                            f"restoring {object_id.hex()[:12]} from "
                            f"{url}")
                return data
            except FileNotFoundError:
                raise  # concurrent delete, not an IO failure
            except Exception:
                from . import metrics_defs as mdefs

                mdefs.spill_errors().inc(tags={"op": "read"})
                raise

        from ..utils.retry import is_retryable_error

        policy = RetryPolicy(
            max_attempts=self.config.spill_retry_attempts,
            base_backoff_s=self.config.spill_retry_backoff_s,
            plane="spill",
            retryable=lambda e: (not isinstance(e, FileNotFoundError)
                                 and is_retryable_error(e)))
        return policy.run(once)

    def _restore_into_shm(self, object_id: bytes) -> Optional[memoryview]:
        """Move one spilled object back into shm; returns a referenced view
        (or None if it was deleted concurrently, or the spill copy proved
        unreadable/corrupt — the caller treats that as object loss and
        re-fetches/reconstructs). Caller holds the _restoring claim for
        this object."""
        with self._spill_lock:
            url = self._spilled.get(object_id)
        if url is None:
            return self.shm.get(object_id)
        t0 = time.time()
        try:
            data = self._spill_read(object_id, url)
        except (OSError, RetryExhausted):
            return None  # concurrently delete()d, or unrecoverable IO
        # restore span: when a traced task's arg get forced the restore,
        # the current context links the disk read into its causal chain
        timeline.record_event(
            f"spill::restore::{object_id.hex()[:8]}", "spill", t0,
            time.time(), extra={"bytes": len(data)},
            trace=tracing.get_current())
        try:
            buf = self._create_with_spill(object_id, len(data))
        except ValueError:
            # a pushed copy landed concurrently
            return self.shm.get(object_id)
        buf[:] = data
        del buf
        # seal, take the reader ref, and drop the spill record under
        # _spill_lock: a concurrent _spill_for must never see the fresh
        # object sealed-with-zero-refs (it would evict it and the pop
        # below would erase the NEW spill record — losing the object)
        with self._spill_lock:
            self._unsealed.pop(object_id, None)
            self.shm.seal(object_id)
            out = self.shm.get(object_id)
            self._spilled.pop(object_id, None)
            self._spill_crc.pop(object_id, None)
            self._spill_codec.pop(object_id, None)
        # synchronous: a delete queued on the _io pool would be dropped by
        # close()'s shutdown(wait=False), orphaning the spill file
        self._storage.delete(url)
        from . import metrics_defs as mdefs

        mdefs.objects_restored().inc()
        mdefs.objects_restored_bytes().inc(len(data))
        self._notify_object_change()
        return out

    def read(self, object_id: bytes):
        """A readable buffer of the object WITHOUT forcing shm residency:
        the shm view when resident (caller must ``release``), the spill
        file's bytes when spilled. Serving a transfer or an inline get must
        never require allocating in a full store — the reference's object
        manager reads spilled objects straight from external storage too
        (local_object_manager.h:180)."""
        for _ in range(2):  # retry once: a concurrent restore moves the
            view = self.shm.get(object_id)  # object spill-file -> shm
            if view is not None:
                return view
            with self._spill_lock:
                url = self._spilled.get(object_id)
            if url is None:
                continue
            try:
                return self._spill_read(object_id, url)
            except (OSError, RetryExhausted):
                continue  # restored or delete()d concurrently, or lost
        return None

    def contains(self, object_id: bytes) -> bool:
        return self.shm.contains(object_id) or object_id in self._spilled

    def release(self, object_id: bytes) -> None:
        self.shm.release(object_id)

    def delete(self, object_id: bytes) -> None:
        with self._spill_lock:
            url = self._spilled.pop(object_id, None)
            pin = self._pinned.pop(object_id, None)
            self._spill_crc.pop(object_id, None)
            self._spill_codec.pop(object_id, None)
        self._crc.pop(object_id, None)
        self._unsealed.pop(object_id, None)
        if pin is not None:
            view, _ = pin
            del view
            self.shm.release(object_id)
        if url:
            self._storage.delete(url)
        self.shm.delete(object_id)
        self._notify_object_change()

    def sweep_unsealed(self, deadline_s: Optional[float] = None) -> int:
        """Abort unsealed creates older than ``deadline_s`` (default:
        config unsealed_create_deadline_s) and return how many. A fetch
        whose process died mid-pull leaks its allocation forever
        otherwise — arena bytes no allocation can reclaim until restart.
        Called from the owner heartbeat / agent reap loops.

        The deadline MUST exceed every bounded transfer timeout (default
        300s vs the ~120s fetch budget): aborting a create a live fetch
        is still streaming into would hand its arena bytes to another
        allocation mid-write. Only creates made through THIS
        NodeObjectStore are tracked (a StoreClient in another process
        seals its own creates synchronously)."""
        if deadline_s is None:
            deadline_s = self.config.unsealed_create_deadline_s
        now = time.monotonic()
        stale = [oid for oid, t in list(self._unsealed.items())
                 if now - t > deadline_s]
        aborted = 0
        for oid in stale:
            if self._unsealed.pop(oid, None) is None:
                continue  # sealed/deleted while we looked
            view = self.shm.get(oid)
            if view is not None:
                # actually sealed (a pop we missed): never abort real data
                del view
                self.shm.release(oid)
                continue
            try:
                if self.shm.delete(oid):  # aborts the unsealed create
                    aborted += 1
            except Exception:  # noqa: BLE001
                pass
        if aborted:
            from ..utils import events
            from . import metrics_defs as mdefs

            events.emit("STALE_CREATE_ABORTED",
                        f"store {self.name}: aborted {aborted} unsealed "
                        f"create(s) older than {deadline_s:.0f}s",
                        severity=events.WARNING, source="object_store",
                        count=aborted)
            mdefs.stale_creates_aborted().inc(aborted)
            self._notify_object_change()
        return aborted

    def usage(self):
        return self.shm.usage()

    def spilled_count(self) -> int:
        return len(self._spilled)

    def spill_degraded(self) -> bool:
        """True while spill IO is suspended after persistent failure."""
        return self._spill_degraded_until != 0.0

    def close(self, unlink: bool = False) -> None:
        self._io.shutdown(wait=False)
        self.shm.close()
        if unlink:
            ShmStore.unlink(self.name)


class StoreClient:
    """A read/write client to some node's store from another process on the
    host (what workers hold; the plasma-client analog)."""

    def __init__(self, name: str):
        self.shm = ShmStore(name, create=False)

    def get(self, object_id: bytes) -> Optional[memoryview]:
        return self.shm.get(object_id)

    def put_serialized(self, object_id: bytes, serialized: SerializedObject) -> None:
        try:
            buf = self.shm.create(object_id, serialized.total_size)
        except ValueError:
            return  # already present (e.g. task retry re-producing a return)
        serialized.write_into(buf)
        self.shm.seal(object_id)

    def release(self, object_id: bytes) -> None:
        self.shm.release(object_id)

    def contains(self, object_id: bytes) -> bool:
        return self.shm.contains(object_id)

    def close(self):
        self.shm.close()
