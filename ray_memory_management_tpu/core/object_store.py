"""Per-node object store: shared-memory tier with spill/restore to disk.

Combines three reference components into the TPU-host store model:
  - plasma store semantics (create/seal/get/release/delete) come from the
    native shm store (native/shmstore.cpp — see its header for the mapping);
  - spilling orchestration mirrors the raylet's LocalObjectManager
    (src/ray/raylet/local_object_manager.h:99,111,180): when an allocation
    fails or usage passes ``object_spilling_threshold``, LRU unreferenced
    objects are written to external storage by IO threads and deleted from
    shm; a get() of a spilled object restores it transparently;
  - the owner-side in-process memory store for small objects
    (src/ray/core_worker/store_provider/memory_store/memory_store.h:43) lives
    in the driver/worker runtime, not here.

Allocation under pressure WAITS (bounded) instead of failing: capacity held
by in-flight reader refs (executing tasks) or residency pins drains within
milliseconds, and failing immediately turns a transient full store into a
spurious ObjectLostError — the reference's plasma CreateRequestQueue blocks
clients the same way (src/ray/object_manager/plasma/create_request_queue.h:32).
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional

from ..config import Config
from ..exceptions import ObjectStoreFullError
from ..native import ShmStore, ShmStoreFullError
from . import external_storage as ext
from ..serialization import SerializedObject


class NodeObjectStore:
    """The store owned by one (virtual) node. Thread-safe."""

    def __init__(self, name: str, config: Optional[Config] = None,
                 create: bool = True):
        self.config = config or Config()
        self.name = name
        capacity = self.config.object_store_memory
        self.shm = ShmStore(name, capacity, create=create)
        self._spill_lock = threading.Lock()
        # per-object restore claims: oid -> Event set when the restore ends.
        # A dict (not one big lock) so restores of DIFFERENT objects run
        # concurrently and a restore parked in the allocation wait never
        # stalls an unrelated get()
        self._restore_mu = threading.Lock()
        self._restoring: Dict[bytes, threading.Event] = {}
        self._spilled: Dict[bytes, str] = {}  # object_id -> url
        # broadcast on any object becoming readable or disappearing
        # (seal/put/restore/delete): racing fetches parked in
        # transfer.create_or_wait wake immediately instead of poll-ticking.
        # Cross-PROCESS seals (StoreClient writes through the shm segment
        # directly) can't notify — waiters keep a short poll backstop.
        self._change_cond = threading.Condition()
        # ensure_resident pins: object_id -> (ref-holding view, expiry)
        self._pinned: Dict[bytes, tuple] = {}
        # scope the spill tier per store: several stores on one host (head +
        # node agents) spill the SAME object ids (pushed copies) — in a
        # shared directory one store's restore/delete would remove another
        # store's spill file
        base = self.config.object_store_fallback_directory.rstrip("/")
        self._storage = ext.storage_for_uri(base + "/" + name.strip("/"))
        self._io = ThreadPoolExecutor(
            max_workers=self.config.max_io_workers,
            thread_name_prefix=f"io-{name.strip('/')}",
        )

    def _notify_object_change(self) -> None:
        with self._change_cond:
            self._change_cond.notify_all()

    def wait_for_object_change(self, timeout: float) -> None:
        """Block until SOME object is sealed/deleted/restored in this
        process (or ``timeout`` elapses). Callers re-check their own
        predicate — this is a wakeup, not a promise about a specific oid."""
        with self._change_cond:
            self._change_cond.wait(timeout)

    # -- write path -----------------------------------------------------------
    def put_serialized(self, object_id: bytes, serialized: SerializedObject) -> None:
        buf = self._create_with_spill(object_id, serialized.total_size)
        serialized.write_into(buf)
        self.shm.seal(object_id)
        self._notify_object_change()

    def put_bytes(self, object_id: bytes, data) -> None:
        buf = self._create_with_spill(object_id, len(data))
        buf[:] = data
        self.shm.seal(object_id)
        self._notify_object_change()

    def create(self, object_id: bytes, size: int,
               timeout_s: Optional[float] = None) -> memoryview:
        """Allocate; ``timeout_s`` overrides the config full-store wait
        budget (e.g. the agent's push handler uses a SHORT budget so a
        pressured push nacks retryable quickly instead of parking the
        object plane)."""
        return self._create_with_spill(object_id, size, timeout_s)

    def seal(self, object_id: bytes) -> None:
        self.shm.seal(object_id)
        self._notify_object_change()

    def _create_with_spill(self, object_id: bytes, size: int,
                           timeout_s: Optional[float] = None) -> memoryview:
        """Allocate, spilling LRU objects on pressure — the CreateRequestQueue
        + spill fallback path (plasma create_request_queue.h:32 +
        local_object_manager.h:99). When nothing is spillable (capacity held
        by executing tasks' reader refs), waits up to
        ``object_store_full_timeout_s`` (or the caller's ``timeout_s``
        override) for refs to drain rather than failing a transiently-full
        store."""
        if timeout_s is None:
            timeout_s = self.config.object_store_full_timeout_s
        deadline = time.monotonic() + timeout_s
        # residency pins are a read-race grace, not a lease: under sustained
        # pressure they yield (readers that miss re-request and re-ensure),
        # but only after a short delay so promised reads usually land first
        # (never later than half the full-store budget, so short timeouts
        # still get the pin-break before they expire)
        pin_break_at = time.monotonic() + min(0.5, timeout_s / 2)
        while True:
            try:
                return self.shm.create(object_id, size)
            except ShmStoreFullError:
                pass
            if time.monotonic() >= deadline:
                raise ObjectStoreFullError(
                    f"store {self.name}: cannot allocate {size} bytes within "
                    f"{timeout_s:.1f}s; usage={self.shm.usage()}"
                )
            if self._spill_for(max(size, self.config.min_spilling_size)):
                continue
            if time.monotonic() >= pin_break_at and self._release_all_pins():
                continue
            time.sleep(0.02)

    def _release_all_pins(self) -> bool:
        """Drop every ensure_resident pin; returns True if any was held."""
        with self._spill_lock:
            victims = list(self._pinned.items())
            self._pinned.clear()
        for oid, (view, _) in victims:
            del view
            self.shm.release(oid)
        return bool(victims)

    def _spill_for(self, need_bytes: int) -> int:
        """Spill at least ``need_bytes`` of LRU unreferenced objects; returns
        bytes freed."""
        with self._spill_lock:
            candidates = self.shm.evict_candidates(need_bytes)
            freed = 0
            n_spilled = 0
            futures = []
            views = {}
            for oid in candidates:
                view = self.shm.get(oid, inc_ref=True)
                if view is None:
                    continue
                views[oid] = view
                futures.append((oid, self._io.submit(
                    self._storage.spill, oid, view)))
            for oid, fut in futures:
                try:
                    url = fut.result()
                except Exception:
                    self.shm.release(oid)
                    continue
                self._spilled[oid] = url
                view = views.pop(oid)
                nbytes = view.nbytes
                del view
                self.shm.release(oid)
                if self.shm.delete(oid):
                    freed += nbytes
                    n_spilled += 1
                else:
                    # a reader raced us; keep the spill copy, reclaim later
                    pass
            if freed:
                from ..utils import events
                from . import metrics_defs as mdefs

                events.emit("OBJECT_SPILLED",
                            f"spilled {freed} bytes to external storage",
                            source="object_store", bytes=freed,
                            objects=n_spilled)
                mdefs.objects_spilled().inc(n_spilled)
                mdefs.objects_spilled_bytes().inc(freed)
            return freed

    def make_room(self, need_bytes: int) -> int:
        """Spill until ``need_bytes`` could allocate; returns bytes freed.
        The make-room path behind a worker's direct shm put hitting a full
        store (the raylet-spills-for-plasma-creates flow,
        create_request_queue.h:32). Pin handling matches
        _create_with_spill: residency pins get a short grace before they
        are broken, so promised direct reads usually land first."""
        freed = self._spill_for(need_bytes)
        if freed:
            return freed
        time.sleep(min(0.5, self.config.object_store_full_timeout_s / 2))
        freed = self._spill_for(need_bytes)
        if freed == 0 and self._release_all_pins():
            freed = self._spill_for(need_bytes)
        return freed

    def ensure_resident(self, object_id: bytes,
                        grace_s: float = 60.0) -> bool:
        """Make the object shm-resident (restoring from spill if needed) and
        pin it for ``grace_s`` so another process's direct shm read cannot
        race a re-spill/eviction. The pin is a held refcount, released by
        ``sweep_pins``. This is what lets the owner answer "local" to a
        worker truthfully (the restore half of local_object_manager.h:111)."""
        view = self.get(object_id)  # restores; takes a reader ref
        if view is None:
            return False
        with self._spill_lock:
            prev = self._pinned.pop(object_id, None)
            self._pinned[object_id] = (view, time.monotonic() + grace_s)
        if prev is not None:
            self.shm.release(object_id)  # drop the superseded pin's ref
        return True

    def sweep_pins(self) -> None:
        """Release expired ensure_resident pins (called from the owner's
        heartbeat loop / the agent's reap loop)."""
        now = time.monotonic()
        with self._spill_lock:
            expired = [oid for oid, (_, exp) in self._pinned.items()
                       if exp <= now]
            victims = [(oid, self._pinned.pop(oid)) for oid in expired]
        for oid, (view, _) in victims:
            del view
            self.shm.release(oid)

    # -- read path ------------------------------------------------------------
    def get(self, object_id: bytes) -> Optional[memoryview]:
        """Zero-copy view, restoring from spill if needed. None if absent.

        The retry loop is deadline-based, not attempt-counted: under
        restore/spill thrash a reader can lose the wait on concurrent
        restores many times while the object is genuinely present
        (resident or spilled), and giving up early surfaces upstream as a
        spurious ObjectLostError."""
        timeout_s = self.config.object_store_full_timeout_s
        # waiting out another thread's in-flight restore is PRODUCTIVE and
        # gets the full per-restore budget each time it happens; the hard
        # deadline only backstops a wedged restorer so get() cannot spin
        # forever. Every non-wait branch below returns an authoritative
        # answer, so the loop only iterates through restore waits.
        hard_deadline = time.monotonic() + 4 * (timeout_s + 5.0)
        while True:
            view = self.shm.get(object_id)
            if view is not None:
                return view
            if time.monotonic() >= hard_deadline:
                return self.shm.get(object_id)
            with self._restore_mu:
                ev = self._restoring.get(object_id)
            if ev is not None:
                # another thread is restoring this object: wait it out,
                # then re-check shm (loop)
                ev.wait(timeout_s + 5.0)
                continue
            with self._spill_lock:
                spilled = object_id in self._spilled
            if not spilled:
                # a restore may have completed between our shm miss and the
                # spill-record check (moving the object file -> shm): the
                # re-check is what makes a hit authoritative; a miss with
                # no spill copy and no in-flight restore means absent
                return self.shm.get(object_id)
            with self._restore_mu:
                ev = self._restoring.get(object_id)
                owner = ev is None
                if owner:
                    ev = self._restoring[object_id] = threading.Event()
            if not owner:
                ev.wait(timeout_s + 5.0)
                continue
            try:
                return self._restore_into_shm(object_id)
            finally:
                with self._restore_mu:
                    self._restoring.pop(object_id, None)
                ev.set()

    def _restore_into_shm(self, object_id: bytes) -> Optional[memoryview]:
        """Move one spilled object back into shm; returns a referenced view
        (or None if it was deleted concurrently). Caller holds the
        _restoring claim for this object."""
        with self._spill_lock:
            url = self._spilled.get(object_id)
        if url is None:
            return self.shm.get(object_id)
        try:
            data = self._storage.restore(object_id, url)
        except OSError:
            return None  # concurrently delete()d
        try:
            buf = self._create_with_spill(object_id, len(data))
        except ValueError:
            # a pushed copy landed concurrently
            return self.shm.get(object_id)
        buf[:] = data
        del buf
        # seal, take the reader ref, and drop the spill record under
        # _spill_lock: a concurrent _spill_for must never see the fresh
        # object sealed-with-zero-refs (it would evict it and the pop
        # below would erase the NEW spill record — losing the object)
        with self._spill_lock:
            self.shm.seal(object_id)
            out = self.shm.get(object_id)
            self._spilled.pop(object_id, None)
        # synchronous: a delete queued on the _io pool would be dropped by
        # close()'s shutdown(wait=False), orphaning the spill file
        self._storage.delete(url)
        from . import metrics_defs as mdefs

        mdefs.objects_restored().inc()
        mdefs.objects_restored_bytes().inc(len(data))
        self._notify_object_change()
        return out

    def read(self, object_id: bytes):
        """A readable buffer of the object WITHOUT forcing shm residency:
        the shm view when resident (caller must ``release``), the spill
        file's bytes when spilled. Serving a transfer or an inline get must
        never require allocating in a full store — the reference's object
        manager reads spilled objects straight from external storage too
        (local_object_manager.h:180)."""
        for _ in range(2):  # retry once: a concurrent restore moves the
            view = self.shm.get(object_id)  # object spill-file -> shm
            if view is not None:
                return view
            with self._spill_lock:
                url = self._spilled.get(object_id)
            if url is None:
                continue
            try:
                return self._storage.restore(object_id, url)
            except OSError:
                continue  # restored or delete()d concurrently
        return None

    def contains(self, object_id: bytes) -> bool:
        return self.shm.contains(object_id) or object_id in self._spilled

    def release(self, object_id: bytes) -> None:
        self.shm.release(object_id)

    def delete(self, object_id: bytes) -> None:
        with self._spill_lock:
            url = self._spilled.pop(object_id, None)
            pin = self._pinned.pop(object_id, None)
        if pin is not None:
            view, _ = pin
            del view
            self.shm.release(object_id)
        if url:
            self._storage.delete(url)
        self.shm.delete(object_id)
        self._notify_object_change()

    def usage(self):
        return self.shm.usage()

    def spilled_count(self) -> int:
        return len(self._spilled)

    def close(self, unlink: bool = False) -> None:
        self._io.shutdown(wait=False)
        self.shm.close()
        if unlink:
            ShmStore.unlink(self.name)


class StoreClient:
    """A read/write client to some node's store from another process on the
    host (what workers hold; the plasma-client analog)."""

    def __init__(self, name: str):
        self.shm = ShmStore(name, create=False)

    def get(self, object_id: bytes) -> Optional[memoryview]:
        return self.shm.get(object_id)

    def put_serialized(self, object_id: bytes, serialized: SerializedObject) -> None:
        try:
            buf = self.shm.create(object_id, serialized.total_size)
        except ValueError:
            return  # already present (e.g. task retry re-producing a return)
        serialized.write_into(buf)
        self.shm.seal(object_id)

    def release(self, object_id: bytes) -> None:
        self.shm.release(object_id)

    def contains(self, object_id: bytes) -> bool:
        return self.shm.contains(object_id)

    def close(self):
        self.shm.close()
