"""Global control state — the GCS equivalent (src/ray/gcs/gcs_server/).

Holds cluster-level state only, as in the reference: node membership +
liveness (gcs_node_manager.h:36, gcs_heartbeat_manager.h:36), the actor
directory (gcs_actor_manager.h:214), placement groups
(gcs_placement_group_manager.h:173), jobs, an internal KV
(gcs_kv_manager.h), pubsub channels (src/ray/pubsub/), and the object
directory (ownership_based_object_directory.h — centralized here because the
driver owns all objects in the single-host round-1 model).

In-process and thread-safe; a gRPC front-end can wrap this for multi-host the
way the reference fronts GcsServer with services, without changing callers.
"""

from __future__ import annotations

import threading
import time
from collections import defaultdict
from typing import Any, Callable, Dict, List, Optional, Set

from ..ids import ActorID, NodeID
from ..utils import events
from .resources import NodeResources


class NodeInfo:
    __slots__ = ("node_id", "resources", "store_name", "alive",
                 "last_heartbeat", "labels", "index")

    def __init__(self, node_id: NodeID, resources: NodeResources,
                 store_name: str, labels: Dict[str, str], index: int):
        self.node_id = node_id
        self.resources = resources
        self.store_name = store_name
        self.alive = True
        self.last_heartbeat = time.monotonic()
        self.labels = labels
        self.index = index


ACTOR_PENDING = "PENDING_CREATION"
ACTOR_ALIVE = "ALIVE"
ACTOR_RESTARTING = "RESTARTING"
ACTOR_DEAD = "DEAD"


class ActorRecord:
    __slots__ = ("actor_id", "spec", "state", "node_id", "worker_id",
                 "num_restarts", "death_cause")

    def __init__(self, actor_id: ActorID, spec):
        self.actor_id = actor_id
        self.spec = spec
        self.state = ACTOR_PENDING
        self.node_id: Optional[NodeID] = None
        self.worker_id = None
        self.num_restarts = 0
        self.death_cause: Optional[str] = None


def resolve_directory_shards(n: int) -> int:
    """0 = auto: one shard per core, clamped to [4, 64] (fewer shards
    than cores re-serializes directory updates; more than 64 buys
    nothing at this scale and bloats the per-GCS footprint)."""
    if n > 0:
        return n
    import os

    return max(4, min(64, os.cpu_count() or 4))


class _DirectoryShard:
    """One lock-striped slice of the object directory. Every table is
    keyed by object id and an oid hashes to exactly one shard, so
    directory updates and free batches for different objects never
    contend on one lock. The three tables live and die together: a
    holder-set entry always has a tier entry, and both are dropped (with
    the size and the job tag) when the last holder leaves."""

    __slots__ = ("lock", "locations", "sizes", "tiers", "jobs")

    def __init__(self):
        self.lock = threading.Lock()
        # object_id bytes -> set of NodeID with a sealed copy
        self.locations: Dict[bytes, Set[NodeID]] = {}  # guarded-by: lock
        # payload sizes alongside the directory (the reference's object
        # directory carries object_size for exactly this reason:
        # locality-aware leasing needs bytes, not just holder sets)
        self.sizes: Dict[bytes, int] = {}  # guarded-by: lock
        # storage tier per (object, node): "hbm" marks a live device copy
        # pinned by a process on that node — visible to locality scoring
        # but NOT host-readable; "shm" is the default host tier
        self.tiers: Dict[bytes, Dict[NodeID, str]] = {}  # guarded-by: lock
        # owning job per object (16-byte job id). An EXPLICIT tag, not a
        # task-id prefix match: a job-death sweep walks these rows and
        # must never be able to touch another job's objects through a
        # 4-byte prefix collision.
        self.jobs: Dict[bytes, bytes] = {}  # guarded-by: lock


class Pubsub:
    """Callback-based pub/sub (the long-poll channels of src/ray/pubsub/
    collapse to direct callbacks in-process)."""

    def __init__(self):
        self._subs: Dict[str, List[Callable[[Any], None]]] = defaultdict(list)  # guarded-by: _lock
        self._lock = threading.Lock()

    def subscribe(self, channel: str, callback: Callable[[Any], None]) -> None:
        with self._lock:
            self._subs[channel].append(callback)

    def publish(self, channel: str, message: Any) -> None:
        with self._lock:
            subs = list(self._subs.get(channel, ()))
        for cb in subs:
            try:
                cb(message)
            except Exception:
                pass


class GCS:
    def __init__(self, storage=None, directory_shards: int = 0):
        from .gcs_storage import InMemoryGcsStorage

        self._lock = threading.RLock()
        # pluggable table storage (gcs_storage.py — the Redis-FT analog,
        # redis_store_client.h:28): durable backends persist the internal KV
        # and detached-actor specs across head restarts
        self.storage = storage or InMemoryGcsStorage()
        self.durable = not isinstance(self.storage, InMemoryGcsStorage)
        self.nodes: Dict[NodeID, NodeInfo] = {}  # guarded-by: _lock
        self.actors: Dict[ActorID, ActorRecord] = {}  # guarded-by: _lock
        self.named_actors: Dict[str, ActorID] = {}  # guarded-by: _lock
        self.placement_groups: Dict[Any, Any] = {}  # guarded-by: _lock
        self.jobs: Dict[Any, dict] = {}  # guarded-by: _lock
        self.kv: Dict[str, bytes] = {  # guarded-by: _lock
            k: v for k, v in self.storage.items("kv")}
        self.pubsub = Pubsub()
        # The object directory is lock-striped into shards keyed by oid
        # (gcs_directory_shards) so add/remove/locate traffic from
        # different nodes never contends on one lock — the GCS-side half
        # of the decentralized control plane. Shard locks are LEAF locks:
        # nothing is acquired while holding one, and batched operations
        # take one shard lock at a time (never two at once), so no
        # ordering edges exist between them.
        self._num_shards = resolve_directory_shards(directory_shards)
        self._shards = [_DirectoryShard() for _ in range(self._num_shards)]
        self._node_index = 0  # guarded-by: _lock

    def _shard(self, oid: bytes) -> _DirectoryShard:
        return self._shards[hash(oid) % self._num_shards]

    def _by_shard(self, oids) -> Dict[int, list]:
        """Group a batch of oids by shard index so batched lookups
        acquire each touched shard lock exactly once."""
        groups: Dict[int, list] = defaultdict(list)
        for oid in oids:
            groups[hash(oid) % self._num_shards].append(oid)
        return groups

    # -- jobs ----------------------------------------------------------------
    # The job table (GcsJobManager analog, gcs_job_manager.h:28): one row
    # per driver — the in-process driver plus every connected thin client.
    # Rows outlive the job (state flips to FINISHED/FAILED) so the state
    # API can show what ran.
    def register_job(self, job_id: bytes, info: Optional[dict] = None
                     ) -> None:
        with self._lock:
            self.jobs[job_id] = {
                "job_id": job_id.hex(),
                "state": "RUNNING",
                "start_time": time.time(),
                "end_time": None,
                **(info or {}),
            }

    def set_job_state(self, job_id: bytes, state: str,
                      message: str = "") -> None:
        with self._lock:
            row = self.jobs.get(job_id)
            if row is None:
                return
            row["state"] = state
            row["end_time"] = time.time()
            if message:
                row["message"] = message

    def list_jobs(self) -> list:
        with self._lock:
            return [dict(v) for v in self.jobs.values()]

    # -- nodes ---------------------------------------------------------------
    def register_node(self, node_id: NodeID, resources: NodeResources,
                      store_name: str,
                      labels: Optional[Dict[str, str]] = None) -> NodeInfo:
        with self._lock:
            info = NodeInfo(node_id, resources, store_name, labels or {},
                            self._node_index)
            self._node_index += 1
            self.nodes[node_id] = info
        self.pubsub.publish("node_added", node_id)
        events.emit("NODE_ADDED", f"node {node_id.hex()[:12]} joined",
                    source="gcs", node_id=node_id.hex())
        return info

    def heartbeat(self, node_id: NodeID) -> None:
        with self._lock:
            info = self.nodes.get(node_id)
            if info:
                info.last_heartbeat = time.monotonic()

    def check_heartbeats(self, timeout_s: float) -> List[NodeID]:
        """Returns nodes newly declared dead (gcs_heartbeat_manager.h:94)."""
        now = time.monotonic()
        dead = []
        with self._lock:
            for info in self.nodes.values():
                if info.alive and now - info.last_heartbeat > timeout_s:
                    info.alive = False
                    dead.append(info.node_id)
        for nid in dead:
            self.pubsub.publish("node_dead", nid)
            events.emit("NODE_DEAD",
                        f"node {nid.hex()[:12]} missed heartbeats",
                        severity=events.ERROR, source="gcs",
                        node_id=nid.hex())
        return dead

    def mark_node_dead(self, node_id: NodeID) -> None:
        with self._lock:
            info = self.nodes.get(node_id)
            if not info or not info.alive:
                return
            info.alive = False
        self.pubsub.publish("node_dead", node_id)
        events.emit("NODE_DEAD", f"node {node_id.hex()[:12]} marked dead",
                    severity=events.ERROR, source="gcs",
                    node_id=node_id.hex())

    def alive_nodes(self) -> List[NodeInfo]:
        with self._lock:
            return [n for n in self.nodes.values() if n.alive]

    # -- actors --------------------------------------------------------------
    def register_actor(self, record: ActorRecord) -> None:
        with self._lock:
            self.actors[record.actor_id] = record
            name = record.spec.registered_name
            if name:
                if name in self.named_actors:
                    raise ValueError(f"actor name already taken: {name}")
                self.named_actors[name] = record.actor_id

    def get_actor(self, actor_id: ActorID) -> Optional[ActorRecord]:
        with self._lock:
            return self.actors.get(actor_id)

    def get_named_actor(self, name: str) -> Optional[ActorRecord]:
        with self._lock:
            aid = self.named_actors.get(name)
            return self.actors.get(aid) if aid else None

    def set_actor_state(self, actor_id: ActorID, state: str,
                        death_cause: Optional[str] = None) -> None:
        with self._lock:
            rec = self.actors.get(actor_id)
            if not rec:
                return
            rec.state = state
            if death_cause:
                rec.death_cause = death_cause
            if state == ACTOR_DEAD and rec.spec.registered_name:
                self.named_actors.pop(rec.spec.registered_name, None)
        self.pubsub.publish("actor_state", (actor_id, state))

    # -- kv ------------------------------------------------------------------
    def kv_put(self, key: str, value: bytes) -> None:
        with self._lock:  # storage write under the lock: persisted order
            self.kv[key] = value  # must match in-memory order
            self.storage.put("kv", key, value)

    def kv_get(self, key: str) -> Optional[bytes]:
        with self._lock:
            return self.kv.get(key)

    def kv_del(self, key: str) -> None:
        with self._lock:
            self.kv.pop(key, None)
            self.storage.delete("kv", key)

    def kv_keys(self, prefix: str = "") -> List[str]:
        with self._lock:
            return [k for k in self.kv if k.startswith(prefix)]

    # -- object directory ----------------------------------------------------
    # Sharded: every method routes through the oid's _DirectoryShard and
    # takes only that shard's (leaf) lock; batched calls group by shard
    # and acquire each touched shard lock once.
    def add_object_location(self, oid: bytes, node_id: NodeID,
                            size: Optional[int] = None,
                            tier: str = "shm",
                            job: Optional[bytes] = None) -> None:
        sh = self._shard(oid)
        with sh.lock:
            locs = sh.locations.get(oid)
            if locs is None:
                locs = sh.locations[oid] = set()
                sh.tiers[oid] = {}
            locs.add(node_id)
            sh.tiers[oid][node_id] = tier
            if size is not None:
                sh.sizes[oid] = size
            if job is not None:
                sh.jobs[oid] = job

    def remove_object_location(self, oid: bytes, node_id: NodeID) -> None:
        sh = self._shard(oid)
        with sh.lock:
            locs = sh.locations.get(oid)
            if locs:
                locs.discard(node_id)
                tiers = sh.tiers.get(oid)
                if tiers:
                    tiers.pop(node_id, None)
                if not locs:
                    del sh.locations[oid]
                    sh.sizes.pop(oid, None)
                    sh.tiers.pop(oid, None)
                    sh.jobs.pop(oid, None)

    def remove_device_location(self, oid: bytes, node_id: NodeID) -> None:
        """Drop a holder only while its copy is still device-tier: the
        owner process died or consumed the buffer. A host copy written
        since (materialization overwrote the tag to 'shm') survives —
        it lives in the node store, not the dead process."""
        sh = self._shard(oid)
        with sh.lock:
            if sh.tiers.get(oid, {}).get(node_id) != "hbm":
                return
        self.remove_object_location(oid, node_id)

    def get_object_locations(self, oid: bytes) -> Set[NodeID]:
        """HOST-READABLE holders only: device-tier (hbm) copies are live
        process-local jax buffers the transfer plane cannot shm-read —
        those readers go through the materialization path instead."""
        sh = self._shard(oid)
        with sh.lock:
            tiers = sh.tiers.get(oid, {})
            return {n for n in sh.locations.get(oid, ())
                    if tiers.get(n, "shm") != "hbm"}

    def locate_objects(self, oids) -> Dict[bytes, tuple]:
        """Batched directory lookup for the scheduler's locality pass:
        ``{oid: (size_bytes, (holder NodeIDs...), {node: tier})}`` with
        ONE lock acquisition per touched shard (the router calls this
        once per scheduling batch, not per oid per candidate node). Size
        is 0 when the directory never learned it (the holder set is
        still valid — the scheduler just can't weigh those bytes).
        Holders INCLUDE device-tier (hbm) copies — an HBM-resident
        argument is the best possible placement target — with the tier
        map telling readers which holders are host-readable. Objects
        with no live directory entry are absent from the result."""
        out: Dict[bytes, tuple] = {}
        for idx, group in self._by_shard(oids).items():
            sh = self._shards[idx]
            with sh.lock:
                for oid in group:
                    locs = sh.locations.get(oid)
                    if locs:
                        out[oid] = (sh.sizes.get(oid, 0), tuple(locs),
                                    dict(sh.tiers.get(oid, {})))
        return out

    def directory_keys(self) -> List[bytes]:
        """Every oid with a live directory entry (the state API's object
        listing), merged across shards — one lock acquisition each."""
        out: List[bytes] = []
        for sh in self._shards:
            with sh.lock:
                out.extend(sh.locations.keys())
        return out

    def prune_location(self, oid: bytes, node_id: NodeID) -> None:
        """Drop a directory entry a fetch proved STALE (the holder said
        "object not in store"): distinct from remove_object_location so
        the repair is visible — counted and evented — because a directory
        that keeps lying re-routes every retry back to the same empty
        holder."""
        self.remove_object_location(oid, node_id)
        try:
            from ..utils import events
            from . import metrics_defs as mdefs

            mdefs.object_directory_prunes().inc()
            events.emit("OBJECT_LOCATION_PRUNED",
                        f"pruned stale holder {node_id[:8] if isinstance(node_id, str) else node_id} "
                        f"of {oid.hex()[:12]} from the object directory",
                        source="gcs")
        except Exception:  # noqa: BLE001
            pass

    def take_objects_locations(self, oids) -> Dict[bytes, Set[NodeID]]:
        """Batch pop: every listed object's location set, removed from
        the directory, ONE lock acquisition per touched shard. The free
        path over a completion burst calls this once instead of 2N
        per-oid calls (per-oid get+remove was a measurable slice of the
        router's free work at high task rates); oids with no locations —
        inline returns — are simply absent from the result."""
        out: Dict[bytes, Set[NodeID]] = {}
        for idx, group in self._by_shard(oids).items():
            sh = self._shards[idx]
            with sh.lock:
                for oid in group:
                    locs = sh.locations.pop(oid, None)
                    sh.sizes.pop(oid, None)
                    sh.tiers.pop(oid, None)
                    sh.jobs.pop(oid, None)
                    if locs:
                        out[oid] = locs
        return out

    def job_object_keys(self, job_id: bytes) -> List[bytes]:
        """Every directory oid explicitly tagged as owned by ``job_id``
        — the walk a job-death sweep starts from. Only tagged rows are
        returned: an untagged row belongs to the in-process driver and
        is never a sweep candidate."""
        out: List[bytes] = []
        for sh in self._shards:
            with sh.lock:
                out.extend(oid for oid, j in sh.jobs.items() if j == job_id)
        return out

    def count_job_rows(self, job_id: bytes) -> int:
        """Live directory rows still tagged to ``job_id`` (leak probe:
        must be zero after the job's sweep completes)."""
        n = 0
        for sh in self._shards:
            with sh.lock:
                n += sum(1 for j in sh.jobs.values() if j == job_id)
        return n

    def object_job(self, oid: bytes) -> Optional[bytes]:
        sh = self._shard(oid)
        with sh.lock:
            return sh.jobs.get(oid)

    def drop_node_objects(self, node_id: NodeID) -> List[bytes]:
        """Remove a dead node from the directory; returns objects that now
        have zero locations (candidates for lineage reconstruction)."""
        orphaned = []
        for sh in self._shards:
            with sh.lock:
                for oid, locs in list(sh.locations.items()):
                    locs.discard(node_id)
                    tiers = sh.tiers.get(oid)
                    if tiers:
                        tiers.pop(node_id, None)
                    if not locs:
                        del sh.locations[oid]
                        sh.sizes.pop(oid, None)
                        sh.tiers.pop(oid, None)
                        sh.jobs.pop(oid, None)
                        orphaned.append(oid)
        return orphaned

    # -- recoverable head state ----------------------------------------------
    # With a durable storage backend, small sealed object VALUES ride a
    # write-ahead log (ns "sealed_objects") and the directory's
    # oid -> size map snapshots per shard (ns "dir_snapshot"), so a head
    # restart can restore every sealed small object and sweep directory
    # rows whose holders died with the old process tree. The runtime
    # gates the WAL on config (sealed_wal_max_bytes); these helpers are
    # storage plumbing only.
    def wal_put_sealed(self, oid: bytes, payload: bytes) -> None:
        self.storage.put("sealed_objects", oid.hex(), payload)

    def wal_del_sealed(self, oids) -> None:
        for oid in oids:
            self.storage.delete("sealed_objects", oid.hex())

    def wal_sealed_items(self) -> List[tuple]:
        return [(bytes.fromhex(k), v)
                for k, v in self.storage.items("sealed_objects")]

    def snapshot_directory(self) -> None:
        """Persist each shard's oid -> size map (holder sets are process
        identities and meaningless across a restart). One storage row
        per NON-EMPTY shard; empty shards delete their row so the
        snapshot never accretes stale entries."""
        import pickle

        for i, sh in enumerate(self._shards):
            with sh.lock:
                rows = {oid: sh.sizes.get(oid, 0) for oid in sh.locations}
            if rows:
                self.storage.put("dir_snapshot", str(i),
                                 pickle.dumps(rows, protocol=4))
            else:
                self.storage.delete("dir_snapshot", str(i))

    def take_directory_snapshot(self) -> Dict[bytes, int]:
        """Read-and-clear the persisted directory snapshot (boot path).
        Returned entries describe objects sealed before the restart;
        the caller restores WAL-backed values and sweeps the rest —
        their shm-store holders died with the old process tree."""
        out: Dict[bytes, int] = {}
        import pickle

        for key, blob in self.storage.items("dir_snapshot"):
            try:
                out.update(pickle.loads(blob))
            except Exception:  # noqa: BLE001 — corrupt row: sweep it
                pass
            self.storage.delete("dir_snapshot", key)
        return out
