"""Global control state — the GCS equivalent (src/ray/gcs/gcs_server/).

Holds cluster-level state only, as in the reference: node membership +
liveness (gcs_node_manager.h:36, gcs_heartbeat_manager.h:36), the actor
directory (gcs_actor_manager.h:214), placement groups
(gcs_placement_group_manager.h:173), jobs, an internal KV
(gcs_kv_manager.h), pubsub channels (src/ray/pubsub/), and the object
directory (ownership_based_object_directory.h — centralized here because the
driver owns all objects in the single-host round-1 model).

In-process and thread-safe; a gRPC front-end can wrap this for multi-host the
way the reference fronts GcsServer with services, without changing callers.
"""

from __future__ import annotations

import pickle
import threading
import time
import zlib
from collections import defaultdict
from typing import Any, Callable, Dict, List, Optional, Set

from ..ids import ActorID, NodeID
from ..utils import events
from .resources import NodeResources


class NodeInfo:
    __slots__ = ("node_id", "resources", "store_name", "alive",
                 "last_heartbeat", "labels", "index")

    def __init__(self, node_id: NodeID, resources: NodeResources,
                 store_name: str, labels: Dict[str, str], index: int):
        self.node_id = node_id
        self.resources = resources
        self.store_name = store_name
        self.alive = True
        self.last_heartbeat = time.monotonic()
        self.labels = labels
        self.index = index


ACTOR_PENDING = "PENDING_CREATION"
ACTOR_ALIVE = "ALIVE"
ACTOR_RESTARTING = "RESTARTING"
ACTOR_DEAD = "DEAD"


class ActorRecord:
    __slots__ = ("actor_id", "spec", "state", "node_id", "worker_id",
                 "num_restarts", "death_cause")

    def __init__(self, actor_id: ActorID, spec):
        self.actor_id = actor_id
        self.spec = spec
        self.state = ACTOR_PENDING
        self.node_id: Optional[NodeID] = None
        self.worker_id = None
        self.num_restarts = 0
        self.death_cause: Optional[str] = None


def resolve_directory_shards(n: int, max_shards: int = 64) -> int:
    """0 = auto: one shard per core, clamped to [4, max_shards] (fewer
    shards than cores re-serializes directory updates; the default 64
    ceiling stops paying off around 8 virtual nodes and bloats the
    per-GCS footprint — pod-scale runs raise it via
    gcs_directory_shards_max)."""
    if n > 0:
        return n
    import os

    return max(4, min(max(4, max_shards), os.cpu_count() or 4))


class _DirectoryShard:
    """One lock-striped slice of the object directory. Every table is
    keyed by object id and an oid hashes to exactly one shard, so
    directory updates and free batches for different objects never
    contend on one lock. The three tables live and die together: a
    holder-set entry always has a tier entry, and both are dropped (with
    the size and the job tag) when the last holder leaves.

    Rows split HOT/COLD: the tables below hold the hot set; rows idle
    past gcs_directory_cold_s (or squeezed out by the per-shard hot-row
    cap, LRU order) spill in pickled batches to the gcs_storage blob
    surface, leaving only the ``cold`` index entry RAM-resident. A
    touched cold row faults its whole batch back in (gcs.py spill /
    fault helpers)."""

    __slots__ = ("lock", "index", "locations", "sizes", "tiers", "jobs",
                 "touch", "cold", "cold_live", "cold_seq", "spill_backoff")

    def __init__(self, index: int = 0):
        self.lock = threading.Lock()
        self.index = index  # shard number: names this shard's cold blobs
        # object_id bytes -> set of NodeID with a sealed copy
        self.locations: Dict[bytes, Set[NodeID]] = {}  # guarded-by: lock
        # payload sizes alongside the directory (the reference's object
        # directory carries object_size for exactly this reason:
        # locality-aware leasing needs bytes, not just holder sets)
        self.sizes: Dict[bytes, int] = {}  # guarded-by: lock
        # storage tier per (object, node): "hbm" marks a live device copy
        # pinned by a process on that node — visible to locality scoring
        # but NOT host-readable; "shm" is the default host tier
        self.tiers: Dict[bytes, Dict[NodeID, str]] = {}  # guarded-by: lock
        # owning job per object (16-byte job id). An EXPLICIT tag, not a
        # task-id prefix match: a job-death sweep walks these rows and
        # must never be able to touch another job's objects through a
        # 4-byte prefix collision.
        self.jobs: Dict[bytes, bytes] = {}  # guarded-by: lock
        # last locate/renew time per HOT row, kept in access order
        # (re-inserted on touch) so the spill pass reads the shard's LRU
        # tail off the front without sorting
        self.touch: Dict[bytes, float] = {}  # guarded-by: lock
        # oid -> cold-batch seq for spilled rows. The whole row (holders,
        # size, tiers, job) lives in the batch blob; this index costs one
        # dict slot + the key bytes per row, the RAM floor the memory
        # bound cannot go below.
        self.cold: Dict[bytes, int] = {}  # guarded-by: lock
        # batch seq -> rows still cold in that blob (blob GC bookkeeping)
        self.cold_live: Dict[int, int] = {}  # guarded-by: lock
        self.cold_seq = 0  # guarded-by: lock
        # set after a degraded/fruitless spill pass so a hot shard does
        # not re-scan its pinned tail on every single add
        self.spill_backoff = 0.0  # guarded-by: lock


class Pubsub:
    """Callback-based pub/sub (the long-poll channels of src/ray/pubsub/
    collapse to direct callbacks in-process)."""

    def __init__(self):
        self._subs: Dict[str, List[Callable[[Any], None]]] = defaultdict(list)  # guarded-by: _lock
        self._lock = threading.Lock()

    def subscribe(self, channel: str, callback: Callable[[Any], None]) -> None:
        with self._lock:
            self._subs[channel].append(callback)

    def publish(self, channel: str, message: Any) -> None:
        with self._lock:
            subs = list(self._subs.get(channel, ()))
        for cb in subs:
            try:
                cb(message)
            except Exception:
                pass


_COLD_NS = "dir_cold"  # storage namespace for spilled directory batches


class GCS:
    def __init__(self, storage=None, directory_shards: int = 0,
                 hot_max_rows: int = 0, cold_s: float = 5.0,
                 shards_max: int = 64):
        from .gcs_storage import InMemoryGcsStorage

        self._lock = threading.RLock()
        # pluggable table storage (gcs_storage.py — the Redis-FT analog,
        # redis_store_client.h:28): durable backends persist the internal KV
        # and detached-actor specs across head restarts
        self.storage = storage or InMemoryGcsStorage()
        self.durable = not isinstance(self.storage, InMemoryGcsStorage)
        self.nodes: Dict[NodeID, NodeInfo] = {}  # guarded-by: _lock
        self.actors: Dict[ActorID, ActorRecord] = {}  # guarded-by: _lock
        self.named_actors: Dict[str, ActorID] = {}  # guarded-by: _lock
        self.placement_groups: Dict[Any, Any] = {}  # guarded-by: _lock
        self.jobs: Dict[Any, dict] = {}  # guarded-by: _lock
        self.kv: Dict[str, bytes] = {  # guarded-by: _lock
            k: v for k, v in self.storage.items("kv")}
        self.pubsub = Pubsub()
        # The object directory is lock-striped into shards keyed by oid
        # (gcs_directory_shards) so add/remove/locate traffic from
        # different nodes never contends on one lock — the GCS-side half
        # of the decentralized control plane. Shard locks are LEAF locks:
        # nothing is acquired while holding one, and batched operations
        # take one shard lock at a time (never two at once), so no
        # ordering edges exist between them.
        self._num_shards = resolve_directory_shards(directory_shards,
                                                    shards_max)
        self._shards = [_DirectoryShard(i) for i in range(self._num_shards)]
        # hot-row budget split evenly across shards; 0 = unbounded (every
        # row RAM-resident, the pre-pod-scale behavior)
        self._hot_cap = (max(16, hot_max_rows // self._num_shards)
                         if hot_max_rows > 0 else 0)
        self._cold_s = max(0.0, cold_s)
        self._node_index = 0  # guarded-by: _lock

    def _shard(self, oid: bytes) -> _DirectoryShard:
        # crc32, not hash(): python seeds str/bytes hashing per process
        # (PYTHONHASHSEED), so hash(oid) lands rows on DIFFERENT shards
        # after a head restart — breaking delta snapshots and making
        # pod-scale shard behavior unreproducible across runs
        return self._shards[zlib.crc32(oid) % self._num_shards]

    def _by_shard(self, oids) -> Dict[int, list]:
        """Group a batch of oids by shard index so batched lookups
        acquire each touched shard lock exactly once."""
        groups: Dict[int, list] = defaultdict(list)
        for oid in oids:
            groups[zlib.crc32(oid) % self._num_shards].append(oid)
        return groups

    # -- hot/cold row split --------------------------------------------------
    # The memory bound: beyond the per-shard hot cap the shard's LRU tail
    # (rows idle past gcs_directory_cold_s; the cap wins over recency)
    # serializes in batches to the gcs_storage blob surface and only a
    # per-oid index entry stays RAM-resident. Any read or mutation of a
    # cold row faults its whole batch back in. All helpers run under the
    # owning shard's (leaf) lock — storage put/get under a shard lock is
    # safe because nothing else is ever acquired while holding one.
    def _cold_key(self, sh: _DirectoryShard, seq: int) -> str:
        return f"{sh.index}:{seq}"

    def _touch_locked(self, sh: _DirectoryShard, oid: bytes) -> None:  # rmtcheck: holds=lock
        sh.touch.pop(oid, None)
        sh.touch[oid] = time.monotonic()

    def _fault_in_locked(self, sh: _DirectoryShard, oid: bytes) -> bool:  # rmtcheck: holds=lock
        """Restore the cold batch holding ``oid`` into the hot tables and
        delete its blob. Returns False when the row is not cold or the
        read was (injected-)failed — a failed fault is a MISS, never a
        loss: the blob and the index entry stay intact for the retry."""
        seq = sh.cold.get(oid)
        if seq is None:
            return False
        from ..utils import faults
        from . import metrics_defs as mdefs

        act = faults.fire("directory.fault")
        if act is not None:
            if act.mode == "stall":
                # a stall models slow blob IO, which genuinely happens
                # under the shard stripe (fault-in reads inside the lock)
                # rmtcheck: disable=blocking-under-lock
                act.sleep()
            else:
                events.emit("DIRECTORY_FAULT_FAILED",
                            f"injected fault reading cold directory batch "
                            f"{self._cold_key(sh, seq)}; row "
                            f"{oid.hex()[:12]} stays cold",
                            severity=events.WARNING, source="gcs")
                return False
        key = self._cold_key(sh, seq)
        try:
            blob = self.storage.get(_COLD_NS, key)
            rows = pickle.loads(blob) if blob is not None else None
        except Exception:  # noqa: BLE001 — unreadable blob: stays a miss
            rows = None
        if rows is None:
            return False
        now = time.monotonic()
        for roid, (locs, size, tiers, job) in rows.items():
            if sh.cold.get(roid) != seq:
                continue  # row was individually dropped since the spill
            sh.cold.pop(roid, None)
            if roid in sh.locations:
                # belt and braces (mutators fault in before re-creating a
                # row, so hot+cold coexistence should not happen): the
                # hot row is newer — union holders, hot tiers win
                sh.locations[roid] |= set(locs)
                merged = dict(tiers)
                merged.update(sh.tiers.get(roid, {}))
                sh.tiers[roid] = merged
            else:
                sh.locations[roid] = set(locs)
                sh.sizes[roid] = size
                sh.tiers[roid] = dict(tiers)
                if job is not None:
                    sh.jobs[roid] = job
            sh.touch[roid] = now
        sh.cold_live.pop(seq, None)
        try:
            self.storage.delete(_COLD_NS, key)
        except Exception:  # noqa: BLE001 — orphan blob; index is gone
            pass
        mdefs.gcs_directory_faults().inc()
        # a fault-in re-admits a whole batch: re-enforce the cap here so
        # a locate sweep over cold rows cannot quietly unbound the hot
        # set (the just-touched row is the MRU end — it stays)
        self._maybe_spill_locked(sh)
        return True

    def _maybe_spill_locked(self, sh: _DirectoryShard) -> None:  # rmtcheck: holds=lock
        """Enforce the per-shard hot-row cap: batch the LRU tail into one
        pickled blob on the storage surface. Spills down to 3/4 of the
        cap so one blob amortizes ~cap/4 adds. A failed write degrades
        to RAM-resident — counted, backed off, rows NEVER lost."""
        cap = self._hot_cap
        if cap <= 0 or len(sh.locations) <= cap:
            return
        now = time.monotonic()
        if now < sh.spill_backoff:
            return
        from ..utils import faults
        from . import metrics_defs as mdefs

        want = len(sh.locations) - max(1, (cap * 3) // 4)
        batch: Dict[bytes, tuple] = {}
        scanned = 0
        for oid, t in sh.touch.items():
            scanned += 1
            if len(batch) >= want or scanned > want * 4 + 1024:
                break
            if sh.jobs.get(oid) is not None:
                # job-tagged rows stay RAM-resident: job-death sweeps
                # walk them by tag and must not fault the cold tier in
                continue
            # the hard cap wins over recency: an over-budget shard spills
            # its full LRU tail down to 3/4 cap even when some of it is
            # younger than cold_s — stopping at just-under-the-cap would
            # degenerate into one tiny blob write per add during a row
            # flood, and blob writes are the expensive half of a spill
            batch[oid] = (list(sh.locations[oid]), sh.sizes.get(oid, 0),
                          dict(sh.tiers.get(oid, {})), sh.jobs.get(oid))
        if not batch:
            sh.spill_backoff = now + self._cold_s
            return
        sh.cold_seq += 1
        seq = sh.cold_seq
        act = faults.fire("directory.spill")
        ok = True
        if act is not None:
            if act.mode == "stall":
                # a stall models slow blob IO, which genuinely happens
                # under the shard stripe (spill writes inside the lock)
                # rmtcheck: disable=blocking-under-lock
                act.sleep()
            else:
                ok = False  # injected write failure (drop/error/corrupt)
        if ok:
            try:
                self.storage.put(_COLD_NS, self._cold_key(sh, seq),
                                 pickle.dumps(batch, protocol=4))
            except Exception:  # noqa: BLE001 — degraded, never lossy
                ok = False
        if not ok:
            for oid in batch:
                self._touch_locked(sh, oid)  # re-age: no immediate retry
            sh.spill_backoff = now + self._cold_s
            events.emit("DIRECTORY_SPILL_DEGRADED",
                        f"directory shard {sh.index} could not spill "
                        f"{len(batch)} cold rows; staying RAM-resident",
                        severity=events.WARNING, source="gcs")
            return
        for oid in batch:
            del sh.locations[oid]
            sh.sizes.pop(oid, None)
            sh.tiers.pop(oid, None)
            sh.touch.pop(oid, None)
            sh.cold[oid] = seq
        sh.cold_live[seq] = len(batch)
        mdefs.gcs_directory_spills().inc()

    def directory_stats(self) -> Dict[str, int]:
        """Hot/cold row counts across shards (one lock acquisition each)
        — the rmt_gcs_directory_{hot,cold}_rows gauge sample and the
        pod-bench memory-bound probe."""
        hot = cold = 0
        for sh in self._shards:
            with sh.lock:
                hot += len(sh.locations)
                cold += len(sh.cold)
        return {"hot": hot, "cold": cold, "shards": self._num_shards}

    # -- jobs ----------------------------------------------------------------
    # The job table (GcsJobManager analog, gcs_job_manager.h:28): one row
    # per driver — the in-process driver plus every connected thin client.
    # Rows outlive the job (state flips to FINISHED/FAILED) so the state
    # API can show what ran.
    def register_job(self, job_id: bytes, info: Optional[dict] = None
                     ) -> None:
        with self._lock:
            self.jobs[job_id] = {
                "job_id": job_id.hex(),
                "state": "RUNNING",
                "start_time": time.time(),
                "end_time": None,
                **(info or {}),
            }

    def set_job_state(self, job_id: bytes, state: str,
                      message: str = "") -> None:
        with self._lock:
            row = self.jobs.get(job_id)
            if row is None:
                return
            row["state"] = state
            row["end_time"] = time.time()
            if message:
                row["message"] = message

    def list_jobs(self) -> list:
        with self._lock:
            return [dict(v) for v in self.jobs.values()]

    # -- nodes ---------------------------------------------------------------
    def register_node(self, node_id: NodeID, resources: NodeResources,
                      store_name: str,
                      labels: Optional[Dict[str, str]] = None) -> NodeInfo:
        with self._lock:
            info = NodeInfo(node_id, resources, store_name, labels or {},
                            self._node_index)
            self._node_index += 1
            self.nodes[node_id] = info
        self.pubsub.publish("node_added", node_id)
        events.emit("NODE_ADDED", f"node {node_id.hex()[:12]} joined",
                    source="gcs", node_id=node_id.hex())
        return info

    def heartbeat(self, node_id: NodeID) -> None:
        with self._lock:
            info = self.nodes.get(node_id)
            if info:
                info.last_heartbeat = time.monotonic()

    def check_heartbeats(self, timeout_s: float) -> List[NodeID]:
        """Returns nodes newly declared dead (gcs_heartbeat_manager.h:94)."""
        now = time.monotonic()
        dead = []
        with self._lock:
            for info in self.nodes.values():
                if info.alive and now - info.last_heartbeat > timeout_s:
                    info.alive = False
                    dead.append(info.node_id)
        for nid in dead:
            self.pubsub.publish("node_dead", nid)
            events.emit("NODE_DEAD",
                        f"node {nid.hex()[:12]} missed heartbeats",
                        severity=events.ERROR, source="gcs",
                        node_id=nid.hex())
        return dead

    def mark_node_dead(self, node_id: NodeID) -> None:
        with self._lock:
            info = self.nodes.get(node_id)
            if not info or not info.alive:
                return
            info.alive = False
        self.pubsub.publish("node_dead", node_id)
        events.emit("NODE_DEAD", f"node {node_id.hex()[:12]} marked dead",
                    severity=events.ERROR, source="gcs",
                    node_id=node_id.hex())

    def alive_nodes(self) -> List[NodeInfo]:
        with self._lock:
            return [n for n in self.nodes.values() if n.alive]

    # -- actors --------------------------------------------------------------
    def register_actor(self, record: ActorRecord) -> None:
        with self._lock:
            self.actors[record.actor_id] = record
            name = record.spec.registered_name
            if name:
                if name in self.named_actors:
                    raise ValueError(f"actor name already taken: {name}")
                self.named_actors[name] = record.actor_id

    def get_actor(self, actor_id: ActorID) -> Optional[ActorRecord]:
        with self._lock:
            return self.actors.get(actor_id)

    def get_named_actor(self, name: str) -> Optional[ActorRecord]:
        with self._lock:
            aid = self.named_actors.get(name)
            return self.actors.get(aid) if aid else None

    def set_actor_state(self, actor_id: ActorID, state: str,
                        death_cause: Optional[str] = None) -> None:
        with self._lock:
            rec = self.actors.get(actor_id)
            if not rec:
                return
            rec.state = state
            if death_cause:
                rec.death_cause = death_cause
            if state == ACTOR_DEAD and rec.spec.registered_name:
                self.named_actors.pop(rec.spec.registered_name, None)
        self.pubsub.publish("actor_state", (actor_id, state))

    # -- kv ------------------------------------------------------------------
    def kv_put(self, key: str, value: bytes) -> None:
        with self._lock:  # storage write under the lock: persisted order
            self.kv[key] = value  # must match in-memory order
            self.storage.put("kv", key, value)

    def kv_get(self, key: str) -> Optional[bytes]:
        with self._lock:
            return self.kv.get(key)

    def kv_del(self, key: str) -> None:
        with self._lock:
            self.kv.pop(key, None)
            self.storage.delete("kv", key)

    def kv_keys(self, prefix: str = "") -> List[str]:
        with self._lock:
            return [k for k in self.kv if k.startswith(prefix)]

    # -- object directory ----------------------------------------------------
    # Sharded: every method routes through the oid's _DirectoryShard and
    # takes only that shard's (leaf) lock; batched calls group by shard
    # and acquire each touched shard lock once.
    def add_object_location(self, oid: bytes, node_id: NodeID,
                            size: Optional[int] = None,
                            tier: str = "shm",
                            job: Optional[bytes] = None) -> None:
        sh = self._shard(oid)
        with sh.lock:
            if sh.cold and oid in sh.cold:
                self._fault_in_locked(sh, oid)
            locs = sh.locations.get(oid)
            if locs is None:
                locs = sh.locations[oid] = set()
                sh.tiers[oid] = {}
            locs.add(node_id)
            sh.tiers[oid][node_id] = tier
            if size is not None:
                sh.sizes[oid] = size
            if job is not None:
                sh.jobs[oid] = job
            self._touch_locked(sh, oid)
            self._maybe_spill_locked(sh)

    def remove_object_location(self, oid: bytes, node_id: NodeID) -> None:
        sh = self._shard(oid)
        with sh.lock:
            if sh.cold and oid in sh.cold:
                self._fault_in_locked(sh, oid)
            locs = sh.locations.get(oid)
            if locs:
                locs.discard(node_id)
                tiers = sh.tiers.get(oid)
                if tiers:
                    tiers.pop(node_id, None)
                if not locs:
                    del sh.locations[oid]
                    sh.sizes.pop(oid, None)
                    sh.tiers.pop(oid, None)
                    sh.jobs.pop(oid, None)
                    sh.touch.pop(oid, None)

    def remove_device_location(self, oid: bytes, node_id: NodeID) -> None:
        """Drop a holder only while its copy is still device-tier: the
        owner process died or consumed the buffer. A host copy written
        since (materialization overwrote the tag to 'shm') survives —
        it lives in the node store, not the dead process."""
        sh = self._shard(oid)
        with sh.lock:
            if sh.cold and oid in sh.cold:
                self._fault_in_locked(sh, oid)
            if sh.tiers.get(oid, {}).get(node_id) != "hbm":
                return
        self.remove_object_location(oid, node_id)

    def get_object_locations(self, oid: bytes) -> Set[NodeID]:
        """HOST-READABLE holders only: device-tier (hbm) copies are live
        process-local jax buffers the transfer plane cannot shm-read —
        those readers go through the materialization path instead."""
        sh = self._shard(oid)
        with sh.lock:
            if sh.cold and oid in sh.cold:
                self._fault_in_locked(sh, oid)
            tiers = sh.tiers.get(oid, {})
            out = {n for n in sh.locations.get(oid, ())
                   if tiers.get(n, "shm") != "hbm"}
            if out:
                self._touch_locked(sh, oid)
            return out

    def locate_objects(self, oids) -> Dict[bytes, tuple]:
        """Batched directory lookup for the scheduler's locality pass:
        ``{oid: (size_bytes, (holder NodeIDs...), {node: tier})}`` with
        ONE lock acquisition per touched shard (the router calls this
        once per scheduling batch, not per oid per candidate node). Size
        is 0 when the directory never learned it (the holder set is
        still valid — the scheduler just can't weigh those bytes).
        Holders INCLUDE device-tier (hbm) copies — an HBM-resident
        argument is the best possible placement target — with the tier
        map telling readers which holders are host-readable. Objects
        with no live directory entry are absent from the result."""
        out: Dict[bytes, tuple] = {}
        for idx, group in self._by_shard(oids).items():
            sh = self._shards[idx]
            with sh.lock:
                for oid in group:
                    if sh.cold and oid in sh.cold:
                        self._fault_in_locked(sh, oid)
                    locs = sh.locations.get(oid)
                    if locs:
                        out[oid] = (sh.sizes.get(oid, 0), tuple(locs),
                                    dict(sh.tiers.get(oid, {})))
                        self._touch_locked(sh, oid)
        return out

    def directory_keys(self) -> List[bytes]:
        """Every oid with a live directory entry (the state API's object
        listing) — hot AND cold — merged across shards, one lock
        acquisition each. Cold rows list from the index alone: no
        fault-in for an enumeration."""
        out: List[bytes] = []
        for sh in self._shards:
            with sh.lock:
                out.extend(sh.locations.keys())
                out.extend(sh.cold.keys())
        return out

    def prune_location(self, oid: bytes, node_id: NodeID) -> None:
        """Drop a directory entry a fetch proved STALE (the holder said
        "object not in store"): distinct from remove_object_location so
        the repair is visible — counted and evented — because a directory
        that keeps lying re-routes every retry back to the same empty
        holder."""
        self.remove_object_location(oid, node_id)
        try:
            from ..utils import events
            from . import metrics_defs as mdefs

            mdefs.object_directory_prunes().inc()
            events.emit("OBJECT_LOCATION_PRUNED",
                        f"pruned stale holder {node_id[:8] if isinstance(node_id, str) else node_id} "
                        f"of {oid.hex()[:12]} from the object directory",
                        source="gcs")
        except Exception:  # noqa: BLE001
            pass

    def take_objects_locations(self, oids) -> Dict[bytes, Set[NodeID]]:
        """Batch pop: every listed object's location set, removed from
        the directory, ONE lock acquisition per touched shard. The free
        path over a completion burst calls this once instead of 2N
        per-oid calls (per-oid get+remove was a measurable slice of the
        router's free work at high task rates); oids with no locations —
        inline returns — are simply absent from the result."""
        out: Dict[bytes, Set[NodeID]] = {}
        for idx, group in self._by_shard(oids).items():
            sh = self._shards[idx]
            with sh.lock:
                for oid in group:
                    if sh.cold and oid in sh.cold:
                        self._fault_in_locked(sh, oid)
                    locs = sh.locations.pop(oid, None)
                    sh.sizes.pop(oid, None)
                    sh.tiers.pop(oid, None)
                    sh.jobs.pop(oid, None)
                    sh.touch.pop(oid, None)
                    if locs:
                        out[oid] = locs
        return out

    def job_object_keys(self, job_id: bytes) -> List[bytes]:
        """Every directory oid explicitly tagged as owned by ``job_id``
        — the walk a job-death sweep starts from. Only tagged rows are
        returned: an untagged row belongs to the in-process driver and
        is never a sweep candidate."""
        out: List[bytes] = []
        for sh in self._shards:
            with sh.lock:
                out.extend(oid for oid, j in sh.jobs.items() if j == job_id)
        return out

    def count_job_rows(self, job_id: bytes) -> int:
        """Live directory rows still tagged to ``job_id`` (leak probe:
        must be zero after the job's sweep completes)."""
        n = 0
        for sh in self._shards:
            with sh.lock:
                n += sum(1 for j in sh.jobs.values() if j == job_id)
        return n

    def object_job(self, oid: bytes) -> Optional[bytes]:
        sh = self._shard(oid)
        with sh.lock:
            return sh.jobs.get(oid)

    def drop_node_objects(self, node_id: NodeID) -> List[bytes]:
        """Remove a dead node from the directory; returns objects that now
        have zero locations (candidates for lineage reconstruction).
        Cold batches are scrubbed IN PLACE (load, drop the node, rewrite
        or delete the blob) — node death must not fault the whole cold
        tier back into head RAM just to forget one holder."""
        orphaned = []
        for sh in self._shards:
            with sh.lock:
                for oid, locs in list(sh.locations.items()):
                    locs.discard(node_id)
                    tiers = sh.tiers.get(oid)
                    if tiers:
                        tiers.pop(node_id, None)
                    if not locs:
                        del sh.locations[oid]
                        sh.sizes.pop(oid, None)
                        sh.tiers.pop(oid, None)
                        sh.jobs.pop(oid, None)
                        sh.touch.pop(oid, None)
                        orphaned.append(oid)
                for seq in list(sh.cold_live.keys()):
                    key = self._cold_key(sh, seq)
                    try:
                        blob = self.storage.get(_COLD_NS, key)
                        rows = pickle.loads(blob) if blob is not None else {}
                    except Exception:  # noqa: BLE001 — unreadable: skip
                        continue
                    changed = False
                    for oid in list(rows.keys()):
                        locs, size, tiers, job = rows[oid]
                        if node_id not in locs:
                            continue
                        changed = True
                        locs = [n for n in locs if n != node_id]
                        if locs:
                            rows[oid] = (
                                locs, size,
                                {n: t for n, t in tiers.items()
                                 if n != node_id}, job)
                        else:
                            del rows[oid]
                            sh.cold.pop(oid, None)
                            orphaned.append(oid)
                    if not changed:
                        continue
                    try:
                        if rows:
                            sh.cold_live[seq] = len(rows)
                            self.storage.put(
                                _COLD_NS, key,
                                pickle.dumps(rows, protocol=4))
                        else:
                            sh.cold_live.pop(seq, None)
                            self.storage.delete(_COLD_NS, key)
                    except Exception:  # noqa: BLE001 — stale holders in
                        pass  # the blob; prune-on-fetch repairs later
        return orphaned

    def reconcile_node_rows(self, node_id: NodeID, held) -> int:
        """Full-resync repair for one node's delta-reported holdings:
        drop every row that still names ``node_id`` but is absent from
        ``held`` (oids the node asserts, post-gap). Cold batches are
        scrubbed IN PLACE like drop_node_objects — without it, a later
        batch fault-in would resurrect stale holders that a fetch then
        has to discover dead. A resync is a rare safety net, so the
        O(cold-tier) blob walk here is off the steady-state path; the
        common case stays O(changes). Returns rows dropped."""
        removed = 0
        for sh in self._shards:
            with sh.lock:
                for oid, locs in list(sh.locations.items()):
                    if node_id not in locs or oid in held:
                        continue
                    locs.discard(node_id)
                    tiers = sh.tiers.get(oid)
                    if tiers:
                        tiers.pop(node_id, None)
                    removed += 1
                    if not locs:
                        del sh.locations[oid]
                        sh.sizes.pop(oid, None)
                        sh.tiers.pop(oid, None)
                        sh.jobs.pop(oid, None)
                        sh.touch.pop(oid, None)
                for seq in list(sh.cold_live.keys()):
                    key = self._cold_key(sh, seq)
                    try:
                        blob = self.storage.get(_COLD_NS, key)
                        rows = pickle.loads(blob) if blob is not None else {}
                    except Exception:  # noqa: BLE001 — unreadable: skip
                        continue
                    changed = False
                    for oid in list(rows.keys()):
                        locs, size, tiers, job = rows[oid]
                        if node_id not in locs or oid in held:
                            continue
                        changed = True
                        removed += 1
                        locs = [n for n in locs if n != node_id]
                        if locs:
                            rows[oid] = (
                                locs, size,
                                {n: t for n, t in tiers.items()
                                 if n != node_id}, job)
                        else:
                            del rows[oid]
                            sh.cold.pop(oid, None)
                    if not changed:
                        continue
                    try:
                        if rows:
                            sh.cold_live[seq] = len(rows)
                            self.storage.put(
                                _COLD_NS, key,
                                pickle.dumps(rows, protocol=4))
                        else:
                            sh.cold_live.pop(seq, None)
                            self.storage.delete(_COLD_NS, key)
                    except Exception:  # noqa: BLE001 — stale holders in
                        pass  # the blob; fetch-failure repairs later
        return removed

    # -- recoverable head state ----------------------------------------------
    # With a durable storage backend, small sealed object VALUES ride a
    # write-ahead log (ns "sealed_objects") and the directory's
    # oid -> size map snapshots per shard (ns "dir_snapshot"), so a head
    # restart can restore every sealed small object and sweep directory
    # rows whose holders died with the old process tree. The runtime
    # gates the WAL on config (sealed_wal_max_bytes); these helpers are
    # storage plumbing only.
    def wal_put_sealed(self, oid: bytes, payload: bytes) -> None:
        self.storage.put("sealed_objects", oid.hex(), payload)

    def wal_del_sealed(self, oids) -> None:
        for oid in oids:
            self.storage.delete("sealed_objects", oid.hex())

    def wal_sealed_items(self) -> List[tuple]:
        return [(bytes.fromhex(k), v)
                for k, v in self.storage.items("sealed_objects")]

    def snapshot_directory(self) -> None:
        """Persist each shard's HOT oid -> size map (holder sets are
        process identities and meaningless across a restart). One
        storage row per NON-EMPTY shard; empty shards delete their row
        so the snapshot never accretes stale entries. Cold rows need no
        snapshot: their batches ALREADY live on the same storage surface
        and take_directory_snapshot merges them on the boot path."""
        for i, sh in enumerate(self._shards):
            with sh.lock:
                rows = {oid: sh.sizes.get(oid, 0) for oid in sh.locations}
            if rows:
                self.storage.put("dir_snapshot", str(i),
                                 pickle.dumps(rows, protocol=4))
            else:
                self.storage.delete("dir_snapshot", str(i))

    def take_directory_snapshot(self) -> Dict[bytes, int]:
        """Read-and-clear the persisted directory snapshot (boot path),
        MERGED with any cold batches the dead head spilled — a row that
        went cold before the crash is still part of the full directory
        the restarted head must account for. Returned entries describe
        objects sealed before the restart; the caller restores
        WAL-backed values and sweeps the rest — their shm-store holders
        died with the old process tree."""
        out: Dict[bytes, int] = {}
        for key, blob in self.storage.items("dir_snapshot"):
            try:
                out.update(pickle.loads(blob))
            except Exception:  # noqa: BLE001 — corrupt row: sweep it
                pass
            self.storage.delete("dir_snapshot", key)
        for key, blob in list(self.storage.items(_COLD_NS)):
            try:
                for oid, row in pickle.loads(blob).items():
                    out[oid] = row[1]
            except Exception:  # noqa: BLE001 — corrupt batch: sweep it
                pass
            self.storage.delete(_COLD_NS, key)
        return out
