"""Driver-side runtime: ownership, submission, routing, fault tolerance.

This is the CoreWorker-of-the-driver (src/ray/core_worker/core_worker.h:63)
fused with the pieces of the raylet the single-host model centralizes:

  - TaskManager: owner-side task state, retries, lineage for reconstruction
    (task_manager.h:86,135);
  - ReferenceCounter (simplified): local python refs pin objects; task args
    are pinned for the task's duration (reference_count.h:61);
  - ObjectRecoveryManager: a lost object with recorded lineage re-submits its
    producing task (object_recovery_manager.h:41);
  - scheduling: dependency resolution then node selection then node-local
    dispatch (direct_task_transport.cc:22 + cluster_task_manager.cc:44);
  - the router thread plays the role of the per-worker gRPC reply streams:
    one thread multiplexes all worker pipes (multiprocessing.connection.wait),
    handling replies inline and farming potentially-blocking worker requests
    (nested get/wait) to a service pool.
"""

from __future__ import annotations

import itertools
import os
import threading
import time
from collections import defaultdict, deque
from concurrent.futures import Future, ThreadPoolExecutor
from concurrent.futures import TimeoutError as _CFTimeoutError
from multiprocessing import connection as mpc
from typing import Any, Dict, List, Optional, Set, Tuple

from .. import _worker_context
from .. import serialization as ser
from ..config import Config
from ..exceptions import (
    ActorDiedError,
    GetTimeoutError,
    NodeDeadError,
    ObjectLostError,
    ObjectStoreFullError,
    QuotaExceededError,
    RmtError,
    TaskError,
    WorkerCrashedError,
)
from ..ids import ActorID, JobID, NodeID, ObjectID, TaskID
from ..utils import events, timeline, tracing
from .gcs import (
    ACTOR_ALIVE, ACTOR_DEAD, ACTOR_PENDING, ACTOR_RESTARTING, ActorRecord, GCS,
)
from . import codec as wire_codec
from . import metrics_defs as mdefs
from .node_manager import NodeManager, WorkerHandle
from .object_ref import ObjectRef
from .object_store import StoreClient
from .resources import CPU, NodeResources, Resources, TPU, task_resources
from .scheduler import ClusterScheduler
from .scheduling_strategies import PlacementGroupSchedulingStrategy
from .task_spec import ActorCreationSpec, TaskSpec


class _SendChannel:
    """Per-connection outbound queue drained by the shared sender pool."""

    __slots__ = ("conn", "handle", "q", "cond", "dead", "scheduled")

    def __init__(self, conn, handle):
        self.conn = conn
        self.handle = handle
        self.q: deque = deque()
        self.cond = threading.Condition()
        self.dead = False
        self.scheduled = False  # claimed by / queued for a pool thread


class _SenderPool:
    """Fixed thread pool draining per-connection send channels.

    Replaces one-sender-thread-per-connection: at hundreds of live workers
    (a Serve deployment, an actor-churn burst) per-connection threads cost
    a thread spawn on every worker's first dispatch and a scheduler that
    must juggle hundreds of mostly-idle threads. A channel with queued
    messages is claimed by exactly ONE pool thread at a time (so writes to
    a connection stay ordered), drained completely with back-to-back
    messages coalesced into batch frames, then released. A worker that
    stops draining its pipe pins only the one pool thread writing to it —
    when all threads are pinned the pool grows (bounded) so stalled
    consumers can never freeze everyone else's sends, and surplus threads
    retire once idle."""

    def __init__(self, runtime: "Runtime", base_threads: int = 4,
                 max_threads: int = 64):
        self._rt = runtime
        self._cond = threading.Condition()
        self._ready: deque = deque()  # scheduled channels awaiting a thread
        self._base = base_threads
        self._max = max_threads
        self._threads = 0
        self._idle = 0
        self._stopping = False
        with self._cond:
            for _ in range(base_threads):
                self._spawn_locked()

    def stop(self) -> None:
        """Retire every pool thread (runtime shutdown). Without this a
        test suite creating hundreds of runtimes accumulates hundreds of
        parked daemon threads for the process lifetime."""
        with self._cond:
            self._stopping = True
            self._cond.notify_all()

    def _spawn_locked(self) -> None:
        self._threads += 1
        threading.Thread(target=self._loop, daemon=True,
                         name="rmt-sender").start()

    def enqueue(self, chan: _SendChannel, msg: dict) -> bool:
        with chan.cond:
            if chan.dead:
                return False
            chan.q.append(msg)
            claim = not chan.scheduled
            if claim:
                chan.scheduled = True
        if claim:
            with self._cond:
                self._ready.append(chan)
                # isolation guarantee: if every pool thread is pinned on a
                # blocked pipe (worker not draining), GROW rather than let
                # one stalled consumer freeze cluster-wide sends; surplus
                # threads retire after idling (see _loop). The cap bounds
                # the pathological case of dozens of simultaneously
                # wedged workers.
                if self._idle == 0 and self._threads < self._max:
                    self._spawn_locked()
                else:
                    self._cond.notify()
        return True

    def _loop(self) -> None:
        while True:
            with self._cond:
                self._idle += 1
                while not self._ready:
                    if self._stopping:
                        self._idle -= 1
                        self._threads -= 1
                        return
                    if not self._cond.wait(timeout=10.0):
                        if self._threads > self._base:
                            # surplus grow-thread with nothing to do
                            self._idle -= 1
                            self._threads -= 1
                            return
                self._idle -= 1
                chan = self._ready.popleft()
            while True:
                with chan.cond:
                    if chan.dead or not chan.q:
                        chan.scheduled = False
                        chan.q.clear()
                        break
                    msgs = list(chan.q)
                    chan.q.clear()
                payload = msgs[0] if len(msgs) == 1 else {
                    "type": "batch", "msgs": msgs}
                if not self._rt._send_payload(chan.conn, payload):
                    with chan.cond:
                        chan.dead = True
                        chan.q.clear()
                        chan.scheduled = False
                    self._rt._on_worker_death(chan.handle)
                    break


class _SlimFuture:
    """Minimal future for object resolution (the values in
    ``runtime.futures``). One is allocated per task return on the submit
    hot path, where ``concurrent.futures.Future``'s per-instance lock +
    condition cost ~9us each — this one allocates three slots and shares
    a single class-level condition across all instances (completions far
    outnumber waiters, and a waiter re-checking its own future on a
    broadcast costs microseconds). API-compatible with the stdlib Future
    for the operations the runtime uses: done / result / set_result /
    set_exception / add_done_callback."""

    __slots__ = ("_state", "_value", "_cbs")

    _cond = threading.Condition()
    _PENDING, _RESULT, _EXC = 0, 1, 2

    def __init__(self):
        self._state = 0
        self._value = None
        self._cbs = None

    def done(self) -> bool:
        return self._state != 0

    def _finish(self, state: int, value, notify: bool = True) -> None:
        with self._cond:
            if self._state:
                return  # first completion wins, like the stdlib
            self._value = value
            self._state = state
            cbs, self._cbs = self._cbs, None
            if notify:
                self._cond.notify_all()
        for cb in cbs or ():
            try:
                cb(self)
            except Exception:  # noqa: BLE001 — parity with stdlib
                pass

    def set_result(self, value) -> None:
        self._finish(self._RESULT, value)

    def set_exception(self, exc) -> None:
        self._finish(self._EXC, exc)

    def set_result_quiet(self, value) -> None:
        """Resolve without waking waiters — for burst completion paths
        that call :meth:`broadcast` ONCE after resolving a whole batch
        (per-future notify_all made a parked getter context-switch per
        completion instead of per batch). Callbacks still fire here."""
        self._finish(self._RESULT, value, notify=False)

    @classmethod
    def broadcast(cls) -> None:
        with cls._cond:
            cls._cond.notify_all()

    def add_done_callback(self, cb) -> None:
        with self._cond:
            if not self._state:
                if self._cbs is None:
                    self._cbs = []
                self._cbs.append(cb)
                return
        cb(self)

    def result(self, timeout: Optional[float] = None):
        # fast path: no lock when already resolved (reads are safe: _state
        # is written last under the condition, and the GIL orders it)
        state = self._state
        if not state:
            with self._cond:
                self._cond.wait_for(lambda: self._state, timeout)
                state = self._state
        if state == self._RESULT:
            return self._value
        if state == self._EXC:
            raise self._value
        from concurrent.futures import TimeoutError as _FutTimeout

        raise _FutTimeout()


# lifecycle stage spans derived from a task's transition stamps (the
# reference's task_events state timeline): (stage, from-stamp, to-stamp)
_STAGE_EDGES = (
    ("submit_to_queue", "SUBMITTED", "QUEUED"),
    ("queue_to_schedule", "QUEUED", "SCHEDULED"),
    ("schedule_to_dispatch", "SCHEDULED", "DISPATCHED"),
    ("dispatch_to_run", "DISPATCHED", "RUNNING"),
    ("run", "RUNNING", "WORKER_DONE"),
    ("total", "SUBMITTED", "FINISHED"),
)


def stage_durations(ts: Dict[str, float]) -> Dict[str, float]:
    """Stage -> seconds from whichever transition stamps are present
    (actor tasks skip the queue/schedule stages; failed tasks have no
    FINISHED). Negative spans (clock adjustments) are dropped."""
    out: Dict[str, float] = {}
    for stage, a, b in _STAGE_EDGES:
        ta = ts.get(a)
        tb = ts.get(b)
        if ta is not None and tb is not None and tb >= ta:
            out[stage] = tb - ta
    return out


# Driver-side lifecycle spans emitted once per finished task from its
# transition stamps: (span name, start stamp, end stamp). The worker
# emits the matching exec slice (RUNNING→WORKER_DONE) in its own
# process; sharing the task's span_id makes them one flow group, which
# is how Perfetto draws submit→schedule→dispatch→exec→result arrows
# across the process boundary.
_LIFECYCLE_SPANS = (
    ("submit", "SUBMITTED", ("QUEUED", "SCHEDULED", "DISPATCHED",
                             "RUNNING")),
    ("schedule", "QUEUED", ("SCHEDULED",)),
    ("dispatch", "SCHEDULED", ("DISPATCHED",)),
    ("queue", "DISPATCHED", ("RUNNING",)),
    ("prefetch_wait", "PREFETCH_START", ("PREFETCH_DONE",)),
    ("result", "WORKER_DONE", ("FINISHED", "FAILED")),
)


def emit_lifecycle_spans(name: str, task_id: bytes, trace_ctx,
                         ts: Dict[str, float]) -> None:
    """Record the head-side stage spans of one completed task on the
    timeline, each carrying the task's trace context (actor tasks skip
    the queue/schedule stamps — their submit span ends at the first
    stamp that exists)."""
    targs = {"task_id": task_id.hex()}
    for stage, a, ends in _LIFECYCLE_SPANS:
        ta = ts.get(a)
        if ta is None:
            continue
        tb = next((ts[b] for b in ends if b in ts), None)
        if tb is None or tb < ta:
            continue
        timeline.record_event(
            f"{stage}::{name}", "lifecycle", ta, tb, tid="lifecycle",
            extra={**targs, "stage": stage}, trace=trace_ctx)


class _TaskRecord:
    __slots__ = ("spec", "retries_left", "state", "payload",
                 "args_released", "gc_returns", "ts", "rusage")

    def __init__(self, spec: TaskSpec, payload: dict, retries_left: int,
                 gc_returns: bool = True):
        self.spec = spec
        self.payload = payload  # original submission payload, for resubmit
        self.retries_left = retries_left
        self.state = "PENDING"
        # state-transition stamps (time.time()); worker-side RUNNING /
        # WORKER_DONE merge in from the done reply's piggybacked tstamps
        self.ts: Dict[str, float] = {"SUBMITTED": time.time()}
        # worker-side resource deltas (cpu_s, peak_rss, hbm_bytes) merged
        # from the done reply's piggybacked rusage, like ts above
        self.rusage: Optional[Dict[str, float]] = None
        # the task holds a reference on each of its ref args until it
        # reaches a terminal state (reference_count.h task-argument refs);
        # this flag makes the release idempotent across the several
        # terminal paths (done / permanent fail / cancel)
        self.args_released = False
        # False for worker/client submissions: their return handles are
        # bare (no distributed refcount), so neither their values nor
        # their metadata are ever GC'd — the pre-refactor behavior
        self.gc_returns = gc_returns


class _ActorInfo:
    __slots__ = ("spec", "record", "node_id", "handle", "seq", "pending",
                 "creation_future", "handle_count")

    def __init__(self, spec: ActorCreationSpec, record: ActorRecord):
        self.spec = spec
        self.record = record
        self.node_id: Optional[NodeID] = None
        self.handle: Optional[WorkerHandle] = None
        self.seq = itertools.count()
        self.pending: deque = deque()  # TaskSpecs waiting for ALIVE
        self.creation_future: Future = Future()
        self.handle_count = 0


class _RefShard:
    """One stripe of the head's refcount table: a leaf lock over this
    stripe's counts and its zero-ref free buffer. oids map to stripes by
    hash, so ref churn on disjoint objects never shares a mutex (the
    single _ref_mu this replaces was the refcount hot path's last global
    serialization point)."""

    __slots__ = ("lock", "refs", "frees")

    def __init__(self):
        self.lock = threading.Lock()
        self.refs: Dict[bytes, int] = defaultdict(int)  # guarded-by: lock
        self.frees: List[bytes] = []  # zero-ref batch buffer  # guarded-by: lock


class Runtime:
    def __init__(self, config: Config, nodes_spec: List[dict],
                 namespace: Optional[str] = None):
        self.config = config
        self.job_id = JobID.from_random()
        self.namespace = namespace or f"rmt_{os.getpid()}_{id(self) & 0xffff}"
        from ..native import reap_stale_stores

        reap_stale_stores("rmt_")  # SIGKILLed drivers leave orphans
        from .gcs_storage import open_storage

        self.gcs = GCS(open_storage(config.gcs_storage_path),
                       directory_shards=config.gcs_directory_shards,
                       hot_max_rows=config.gcs_directory_hot_max_rows,
                       cold_s=config.gcs_directory_cold_s,
                       shards_max=config.gcs_directory_shards_max)
        import sys as _sys

        self.gcs.register_job(self.job_id.binary(), {
            "type": "driver",
            "entrypoint": " ".join(_sys.argv[:2]) or "driver",
        })
        self.scheduler = ClusterScheduler(
            self.gcs, config, load_fn=self._node_queue_depth)
        self.nodes: Dict[NodeID, NodeManager] = {}
        self._store_clients: Dict[NodeID, StoreClient] = {}
        self._head_node_id: Optional[NodeID] = None

        # owner state
        self.memory_store: Dict[bytes, bytes] = {}  # small objects (serialized)
        from .device_store import DeviceObjectStore

        from .device_store import resolve_capacity

        # driver-pinned jax.Arrays: a budgeted HBM tier that LRU-demotes
        # unpinned entries into the head node's shm store (which spills
        # below itself), bf16-downcasting f32 payloads when configured
        self.device_store = DeviceObjectStore(
            capacity_bytes=resolve_capacity(config),
            on_demote=self._demote_device_object)
        # job-aware demotion order: under HBM pressure a low-priority
        # tenant's cold pins demote before a high-priority tenant's
        # (LRU within one priority); driver-owned pins demote last
        self.device_store.set_victim_rank(self._device_victim_rank)
        # device-object ownership: oid -> "driver" | WorkerHandle
        self._device_locations: Dict[bytes, Any] = {}
        # driver device objects demoted to host, eligible for
        # re-promotion on their next device read
        self._demoted_device: Set[bytes] = set()  # guarded-by: _lock
        self._materialize_futs: Dict[bytes, Future] = {}
        self._log_tails: Dict[Any, bytes] = {}  # worker id -> partial line
        self.futures: Dict[bytes, Future] = {}
        # live promise ids (create_promise): freeing one PURGES its
        # pending future (a task future must outlive frees for its
        # waiters; a freed promise means the caller is gone and a late
        # external resolution must be dropped, not stored ownerless)
        self._promises: Set[bytes] = set()  # guarded-by: _lock
        self.tasks: Dict[bytes, _TaskRecord] = {}  # guarded-by: _lock
        self.lineage: Dict[bytes, bytes] = {}  # object id -> producing task id  # guarded-by: _lock
        # lock-STRIPED refcount shards (decentralized control plane):
        # ObjectRef __del__/__init__ storms on the APPLICATION thread,
        # worker ref-table ingestion, and the router's completion sweep
        # each touch disjoint oids most of the time — one refcount mutex
        # (the old _ref_mu) serialized them all. Each shard guards its
        # own refs dict + zero-ref free buffer; oid -> shard by hash.
        # Lock order: shard locks are LEAF locks nesting INSIDE _lock;
        # never take _lock (or a second shard) while holding one —
        # multi-oid paths acquire shards one at a time, or in ascending
        # index order when a check must span several (_try_prune).
        from .gcs import resolve_directory_shards

        self._ref_shard_n = resolve_directory_shards(
            config.gcs_directory_shards)
        self._ref_shards = [_RefShard() for _ in range(self._ref_shard_n)]
        self.actors: Dict[bytes, _ActorInfo] = {}
        self.fn_blobs: Dict[bytes, bytes] = {}
        self.cls_blobs: Dict[bytes, bytes] = {}
        self._waiting_deps: Dict[bytes, Set[bytes]] = {}  # task -> missing oids  # guarded-by: _lock
        self._dep_waiters: Dict[bytes, List[bytes]] = defaultdict(list)  # guarded-by: _lock
        self._pending_schedule: deque = deque()  # guarded-by: _lock
        # decentralized ownership bookkeeping (reference_count.h:39-61):
        # per-worker borrow pins (each holds one local_refs count until
        # the worker releases or dies) and per-worker owned-put
        # attribution (objects whose owner is the producing worker)
        self._worker_borrows: Dict[bytes, set] = {}  # guarded-by: _lock
        self._worker_owned: Dict[bytes, set] = {}  # guarded-by: _lock
        # lineage pinning (reference_count.h lineage refcounting): how many
        # RETAINED task records list this oid as a ref arg. A producer's
        # record/lineage can only be pruned when no downstream record still
        # needs it for transitive reconstruction.
        self._lineage_dependents: Dict[bytes, int] = defaultdict(int)
        # bounded history of GC'd tasks so observability survives pruning
        # (the reference's GcsTaskManager keeps a capped task-event log
        # for the same reason); entries are tiny summary dicts
        self.task_history: deque = deque(maxlen=10_000)
        # per-stage latency samples (bounded) for exact percentile
        # summaries (state.summarize_task_latencies); the stage histogram
        # metric keeps the unbounded bucketed view
        self.task_latencies: Dict[str, deque] = {}
        # trace plane: trace_id -> [task_id, ...] so state.get_trace /
        # summarize_critical_path can find a trace's tasks without
        # scanning the whole table; insertion-ordered, oldest trace
        # evicted past the cap (one trace can hold many tasks, so the
        # bound is on traces, matching task_history's retention spirit)
        self._traces: Dict[str, List[bytes]] = {}
        self._traces_cap = 2_000
        # log plane: head-side store over every process's structured
        # records (worker done replies + flush frames, agent pongs, and
        # this process's own emits via the direct attach)
        from ..utils import structlog as _structlog

        self.log_store = _structlog.LogStore()
        _structlog.configure(role="driver")
        _structlog.install_logging_capture()
        _structlog.attach_store(self.log_store)
        # profiling plane: head-side store over every process's stack
        # samples (worker flush frames, agent pongs, and this process's
        # own continuous sampler via the direct attach)
        from ..utils import profiler as _profiler

        self.profile_store = _profiler.ProfileStore()
        _profiler.configure(role="driver")
        _profiler.attach_store(self.profile_store)
        _profiler.start_sampler(hz=float(config.profile_hz))
        # health plane: bounded time-series history over the head's
        # merged registry (sampled on the heartbeat tick) + the SLO
        # rules engine over it. Constructed even under RMT_HEALTH=0 so
        # the query surfaces exist; the gate keeps the store empty.
        from ..utils import tsdb as _tsdb
        from .health import HealthEngine

        self.tsdb = _tsdb.TSDB(
            raw_points=config.tsdb_raw_points,
            downsample_every=config.tsdb_downsample_every,
            downsample_points=config.tsdb_downsample_points,
            max_series_per_name=config.tsdb_max_series_per_name)
        self.health = HealthEngine(self.tsdb,
                                   exemplar=self._health_exemplar)
        # bounded per-resource samples from finished tasks' rusage deltas
        # (state.summarize_task_latencies resource percentiles)
        self.task_resources: Dict[str, deque] = {}
        # hot-path instruments hoisted once (accessor calls touch the
        # registry lock)
        self._m_submitted = mdefs.tasks_submitted()
        self._m_finished = mdefs.tasks_finished()
        self._m_failed = mdefs.tasks_failed()
        self._m_retried = mdefs.tasks_retried()
        self._m_stage_hist = mdefs.task_stage_seconds()
        self._m_prefetch_started = mdefs.prefetch_started()
        self._m_prefetch_completed = mdefs.prefetch_completed()
        self._m_leaf_placed = mdefs.sched_local_placed()
        self._m_leaf_spill = mdefs.sched_local_spillback()
        self._m_worker_exits = mdefs.workers_exited()
        self._leaf_rr = 0  # round-robin cursor over nodes (router only)
        self._leaf_run = 0  # tasks placed on the cursor node this run (router only)
        # recoverable head state: sealed small objects WAL through the
        # durable GCS kv (gcs_storage_path); directory snapshots ride
        # the heartbeat loop. Volatile (in-memory) storage skips both.
        self._wal_enabled = (self.gcs.durable
                             and config.sealed_wal_max_bytes > 0)
        self._wal_max = config.sealed_wal_max_bytes
        self._hb_ticks = 0
        if self.gcs.durable:
            # the previous head's directory rows name holders (stores,
            # workers) that died with its process tree: sweep them, then
            # restore every WAL-sealed object — a head restart loses no
            # sealed object (unsealed creates have no WAL row, so they
            # are swept with the directory)
            self.gcs.take_directory_snapshot()
            for oid, payload in self.gcs.wal_sealed_items():
                self.memory_store[oid] = payload
                fut = _SlimFuture()
                fut.set_result(True)
                self.futures[oid] = fut
        # dep-ready tasks awaiting scheduling, drained in BATCHES by the
        # router's pump: per-task inline scheduling cost ~7 lock/notify
        # round-trips; batching pays them once per burst (the reference
        # batches the same way through the raylet lease request queue)
        self._submit_q: deque = deque()
        self._submit_nudged = False
        self._cancelled: Set[bytes] = set()
        # multi-tenant job plane (job_plane.py): one ledger per live job
        # holding quota state, usage accounting, the cpu-slot throttle and
        # stride-scheduling virtual time. The in-process driver's own job
        # gets an unlimited ledger so the single-tenant path is unchanged.
        from .job_plane import JobLedger

        self._job_ledgers: Dict[bytes, JobLedger] = {
            self.job_id.binary(): JobLedger(self.job_id.binary())
        }  # guarded-by: _lock (ledger internals self-locked, leaf locks)
        self._swept_jobs: Set[bytes] = set()  # guarded-by: _lock
        # job -> (monotonic deadline, trigger) for re-running a sweep that
        # hit an error (job.sweep fault site); drained by the heartbeat loop
        self._sweep_retry: Dict[bytes, tuple] = {}  # guarded-by: _lock
        self._m_job_sweeps = mdefs.job_sweeps()
        self._m_job_preempted = mdefs.job_preemptions()
        self._m_quota_rej = mdefs.job_quota_rejections()
        mdefs.jobs_active().set(float(len(self._job_ledgers)))

        self._lock = threading.RLock()
        self._conn_handles: Dict[Any, WorkerHandle] = {}
        self._router_adds: List[Any] = []  # conns awaiting selector register
        self._router_removals: List[Any] = []  # closed conns to unregister
        self._request_pool = ThreadPoolExecutor(
            max_workers=8, thread_name_prefix="rmt-serve"
        )
        self._transfer_pool = ThreadPoolExecutor(
            max_workers=8, thread_name_prefix="rmt-xfer"
        )
        self._xfer_serving: Dict[NodeID, int] = {}  # outbound serves/node
        self._xfer_served_total: Dict[NodeID, int] = {}  # lifetime serves
        # broadcast distribution gate: per-oid in-flight pull count +
        # wakeup when a pull lands (a NEW holder exists to pull from)
        self._bcast_cond = threading.Condition()
        self._oid_pulls: Dict[bytes, int] = {}  # guarded-by: _bcast_cond
        import socket as _socket

        self._hostname = _socket.gethostname()  # fixed for process life
        self._conn_send_locks: Dict[Any, threading.Lock] = {}
        # lazy p2p transfer servers over LOCAL node stores (node_id -> srv)
        self._xfer_servers: Dict[NodeID, Any] = {}
        # authenticated transfer connections reused across head-side pulls
        from .transfer import ConnectionPool

        self._xfer_conn_pool = ConnectionPool(
            max_idle_per_peer=config.transfer_pool_size)
        # install the deterministic fault plane (no-op without a spec);
        # configure_from also exports RMT_fault_injection_* so spawned
        # agents/zygotes/workers replay the same schedule
        from ..utils import faults as _faults

        _faults.configure_from(config)
        # agent-local leaf scheduling: constraint-free small tasks take a
        # per-node lease credit (NodeManager.submit_leaf) instead of the
        # full pick_node pass; disabled under fault injection so chaos
        # runs keep exercising the battle-tested dispatch/retry path
        # (the leaf path intentionally skips the control.dispatch site)
        self._leaf_enabled = (
            config.leaf_lease_slots >= 0
            and not getattr(config, "fault_injection_spec", ""))
        from ..utils.retry import RetryPolicy

        # one dispatch policy for every queue hand-off (hoisted: a
        # policy object per submit showed in the task hot path)
        self._dispatch_retry = RetryPolicy(
            max_attempts=3, base_backoff_s=0.02, plane="dispatch")
        self._wakeup_r, self._wakeup_w = os.pipe()
        self._stop = threading.Event()
        self.pg_manager = None  # set by placement_group module on first use

        # worker registration socket (workers dial back in after exec).
        # No HMAC challenge on the SAME-HOST worker socket: connecting
        # requires write permission on the 0600 socket file, which is the
        # same same-user trust boundary the challenge would enforce — and
        # the challenge costs two extra round trips per worker connect,
        # measurable in actor-churn bursts (the reference's raylet/plasma
        # Unix sockets are likewise permission-trusted, raylet_client.h:236).
        # The cluster authkey still guards everything that crosses hosts.
        self._authkey = os.urandom(16)
        self._socket_path = f"/tmp/{self.namespace}.sock"
        from multiprocessing.connection import Listener

        self._listener = Listener(self._socket_path, family="AF_UNIX")
        os.chmod(self._socket_path, 0o600)
        self._workers_by_id: Dict[bytes, WorkerHandle] = {}
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="rmt-accept"
        )
        self._accept_thread.start()

        # multi-host plane: TCP listener for node agents (node_agent.py) —
        # the head side of the raylet-joins-GCS handshake
        self._agent_nodes: Dict[Any, Any] = {}  # channel conn -> RemoteNodeManager
        self._node_listener = None
        self._node_listener_thread = None
        self.node_listener_address: Optional[Tuple[str, int]] = None
        self._agent_procs: List[Any] = []  # agents spawned by this driver
        self._agent_proc_by_node: Dict[NodeID, Any] = {}
        if config.enable_node_listener:
            from multiprocessing.connection import Listener as _TCPListener

            self._node_listener = _TCPListener(
                (config.node_listener_host, config.node_listener_port),
                family="AF_INET", authkey=self._authkey,
            )
            self.node_listener_address = self._node_listener.address
            self._node_listener_thread = threading.Thread(
                target=self._agent_accept_loop, daemon=True,
                name="rmt-node-accept",
            )
            self._node_listener_thread.start()

        for i, spec in enumerate(nodes_spec):
            self.add_node(spec, head=(i == 0))

        self._send_cond = threading.Condition()
        self._send_channels: Dict[Any, _SendChannel] = {}  # guarded-by: _send_cond
        self._sender_pool = _SenderPool(self)
        self._router = threading.Thread(
            target=self._router_loop, daemon=True, name="rmt-router"
        )
        self._router.start()
        self._hb = threading.Thread(
            target=self._heartbeat_loop, daemon=True, name="rmt-heartbeat"
        )
        self._hb.start()
        self._memory_monitor = None
        if config.memory_monitor_interval_s > 0:
            from .memory_monitor import MemoryMonitor, make_newest_task_killer

            self._memory_monitor = MemoryMonitor(
                make_newest_task_killer(self),
                usage_threshold=config.memory_usage_threshold,
                check_interval_s=config.memory_monitor_interval_s,
            )
            self._memory_monitor.start()
        for nm in self.nodes.values():
            nm.prestart()
        if config.gcs_storage_path:
            self._recreate_detached_actors()
        # best-effort cleanup if the driver exits without shutdown(): shm
        # stores are kernel objects and would otherwise outlive the process
        import atexit

        atexit.register(self._atexit_shutdown)

    # ------------------------------------------------------------------ nodes
    def add_node(self, spec: dict, head: bool = False) -> NodeID:
        node_id = NodeID.from_random()
        res = task_resources(
            num_cpus=spec.get("num_cpus", 4),
            num_tpus=spec.get("num_tpus", 0),
            resources=spec.get("resources"),
            default_cpus=spec.get("num_cpus", 4),
        )
        node_res = NodeResources(res)
        store_name = f"/{self.namespace}_{node_id.hex()[:8]}"
        nm = NodeManager(
            node_id, node_res, store_name, self.config,
            on_worker_started=self._register_worker,
            socket_path=self._socket_path,
            authkey_hex="",  # permission-trusted worker socket (see above)
        )
        with self._lock:
            self.nodes[node_id] = nm
            self.gcs.register_node(node_id, node_res, store_name,
                                   spec.get("labels"))
            if head or self._head_node_id is None:
                self._head_node_id = node_id
                # the driver process lives on the head node: stamp its
                # own log records with that identity
                from ..utils import structlog as _structlog

                _structlog.configure(node_id=node_id.hex())
        self._wakeup()
        return node_id

    def remove_node(self, node_id: NodeID) -> None:
        """Simulate node failure (Cluster.remove_node, cluster_utils.py:238):
        workers die, store contents are lost, GCS broadcasts node death."""
        with self._lock:
            nm = self.nodes.get(node_id)
            if nm is None:
                return
            nm.alive = False
            if hasattr(nm, "mark_dead"):  # remote: wake pending transfers
                nm.mark_dead()
            self.gcs.mark_node_dead(node_id)
            workers = list(nm.workers.values())
        # snapshot AFTER alive=False, under the node's own lock: a submit
        # racing this drain either lands before it (captured here) or
        # sees the dead flag and raises NodeDeadError (re-placed by
        # _submit_to_node). Without the ordering, a late submit wedges
        # the spec on a queue nobody drains again.
        with nm._lock:
            requeue = list(nm.queue)
            nm.queue.clear()
        for h in workers:
            try:
                h.proc.terminate()
            except Exception:
                pass
        # router will observe EOFs; handle queued (not yet dispatched) tasks
        for spec in requeue:
            self._schedule(spec)
        # agent-leased leaf tasks died with the node (the agent can no
        # longer report lease_dead) — retry them under their budget
        for task_id, spec in nm.take_leaf_inflight().items():
            self._maybe_retry(task_id, spec, WorkerCrashedError(
                f"node died with leased task {spec.name} in flight"))
        self.gcs.drop_node_objects(node_id)
        self._wakeup()

    def head_node(self) -> NodeManager:
        return self.nodes[self._head_node_id]

    def _node_queue_depth(self, node_id: NodeID) -> int:
        nm = self.nodes.get(node_id)
        return nm.backlog() if nm is not None else 0

    def _same_host_store(self, nm) -> Optional[str]:
        """The shm store name of ``nm`` if its store lives on THIS host
        (an agent that registered from the same hostname advertises its
        segment name in transfer_ready), else None. Same-host reads map
        the segment directly — one kernel, zero protocol."""
        name = getattr(nm, "remote_store_name", None)
        if name and getattr(nm, "hostname", None) == self._hostname:
            return name
        return None

    def _store_client_for(self, node_id: NodeID) -> StoreClient:
        # Same-host nodes: the driver maps the store directly (one kernel)
        # — including same-host AGENTS, whose store is just another named
        # shm segment. True remote nodes: reads ride the chunked DCN
        # object plane through the node's agent channel
        # (object_manager.proto:63-67 analog).
        with self._lock:
            cli = self._store_clients.get(node_id)
            if cli is None:
                nm = self.nodes[node_id]
                from .remote_node import RemoteNodeManager

                if isinstance(nm, RemoteNodeManager):
                    shm_name = self._same_host_store(nm)
                    if shm_name is not None:
                        try:
                            cli = StoreClient(shm_name)
                        except Exception:  # noqa: BLE001 — segment gone:
                            cli = nm.store  # fall back to the channel
                    else:
                        cli = nm.store  # RemoteStoreProxy
                elif nm is self.head_node():
                    # reuse the node's own mapping
                    cli = nm.store
                else:
                    cli = StoreClient(nm.store_name)
                self._store_clients[node_id] = cli
        return cli

    # ---------------------------------------------------------------- workers
    def _register_worker(self, handle: WorkerHandle) -> None:
        with self._lock:
            self._workers_by_id[handle.worker_id.binary()] = handle

    def _accept_loop(self) -> None:
        """Bind dialing-in worker processes to their handles (the raylet's
        RegisterClient handshake)."""
        while not self._stop.is_set():
            try:
                conn = self._listener.accept()
            except (OSError, EOFError):
                if self._stop.is_set():
                    return
                continue
            try:
                msg = conn.recv()
            except (EOFError, OSError):
                conn.close()
                continue
            # a bootstrapped worker can reply so fast that its sender
            # coalesces ready + actor_ready into one batch frame
            trailing = []
            if msg.get("type") == "batch" and msg["msgs"]:
                trailing = msg["msgs"][1:]
                msg = msg["msgs"][0]
            if msg.get("type") != "ready":
                conn.close()
                continue
            with self._lock:
                handle = self._workers_by_id.get(msg["worker_id"])
                if handle is None or handle.death_processed:
                    # unknown, or the unborn-worker sweep already declared
                    # it dead — binding the conn would put a corpse back
                    # in the idle pool
                    conn.close()
                    continue
                handle.conn = conn
                self._conn_handles[conn] = handle
                self._conn_send_locks[conn] = threading.Lock()
                self._router_adds.append(conn)
                pending = list(handle.pending_msgs)
                handle.pending_msgs.clear()
            nm = self.nodes.get(handle.node_id)
            if nm:
                nm.on_worker_ready(handle)
            for m in pending:
                self._send(handle, m)
            for m in trailing:  # replies that rode the ready batch
                try:
                    self._handle_worker_message(handle, m)
                except Exception:  # noqa: BLE001 — never kill the accept
                    pass           # loop on one bad frame
            self._wakeup()
            self._pump()

    # ------------------------------------------------------------ node agents
    def _agent_accept_loop(self) -> None:
        """Admit node agents joining over TCP (GcsNodeManager::HandleRegister
        analog, gcs_node_manager.h:36): read the hello, create the head-side
        RemoteNodeManager, and hand the channel to the router."""
        from .remote_node import RemoteNodeManager

        while not self._stop.is_set():
            try:
                conn = self._node_listener.accept()
            except (OSError, EOFError):
                if self._stop.is_set():
                    return
                continue
            try:
                msg = conn.recv()
            except (EOFError, OSError):
                conn.close()
                continue
            if msg.get("type") != "register_node":
                conn.close()
                continue
            from ..config import WIRE_PROTOCOL_VERSION

            if msg.get("proto") != WIRE_PROTOCOL_VERSION:
                # mixed-version cluster: refuse at the handshake, with
                # both versions named, rather than mis-parse frames later
                try:
                    conn.send({
                        "type": "error",
                        "error": (
                            "wire protocol mismatch: head speaks "
                            f"v{WIRE_PROTOCOL_VERSION}, agent spoke "
                            f"v{msg.get('proto')} — upgrade the older "
                            "side"),
                    })
                except (OSError, BrokenPipeError):
                    pass
                conn.close()
                continue
            node_id = NodeID.from_random()
            res = task_resources(
                num_cpus=msg.get("num_cpus", 4),
                num_tpus=msg.get("num_tpus", 0),
                resources=msg.get("resources"),
                default_cpus=msg.get("num_cpus", 4),
            )
            node_res = NodeResources(res)
            nm = RemoteNodeManager(
                node_id, node_res, self.config,
                on_worker_started=self._register_worker,
                channel=conn, gcs=self.gcs,
                hostname=msg.get("hostname", "?"),
            )
            # pid on the agent's host — fault-injection tooling (NodeKiller
            # sigkill mode) and diagnostics key off it
            nm.agent_pid = msg.get("pid")
            try:
                conn.send({
                    "type": "registered",
                    "node_id": node_id.binary(),
                    "config": self.config.to_dict(),
                })
            except (OSError, BrokenPipeError):
                conn.close()
                continue
            with self._lock:
                self.nodes[node_id] = nm
                self.gcs.register_node(node_id, node_res, nm.store_name,
                                       msg.get("labels"))
                self._agent_nodes[conn] = nm
                self._router_adds.append(conn)
            nm.prestart()
            self._wakeup()

    def _handle_agent_message(self, nm, msg: dict) -> None:
        mtype = msg["type"]
        if mtype == "wmsg":
            handle = nm.worker_by_wid(msg["wid"])
            if handle is None:
                return
            inner = msg["msg"]
            if inner.get("type") == "ready":
                self._bind_remote_worker(nm, handle)
                return
            self._handle_worker_message(handle, inner)
        elif mtype in ("push_ack", "pull_data", "ensure_ack", "fetch_ack",
                       "spill_ack"):
            nm.on_channel_reply(msg)
        elif mtype == "transfer_ready":
            # the agent's p2p transfer server is up: record where peers
            # (and the head) can pull this node's objects from — and its
            # shm store name, which same-host peers map directly
            nm.transfer_addr = (msg["host"], msg["port"])
            nm.remote_store_name = msg.get("store_name")
        elif mtype == "lease_spill":
            # the agent's local pool is saturated: take the lease credit
            # back and reroute through the full scheduling pass (NOT the
            # leaf path — spillbacks ride _pending_schedule)
            spec = nm.finish_leaf(msg["task_id"])
            if spec is not None:
                self._m_leaf_spill.inc()
                with self._lock:
                    self._pending_schedule.append(spec)
                self._wakeup()
        elif mtype == "lease_dead":
            # the worker the agent picked died before replying; the
            # agent unbound the lease — retry under the task's budget
            spec = nm.finish_leaf(msg["task_id"])
            if spec is not None:
                self._maybe_retry(msg["task_id"], spec, WorkerCrashedError(
                    f"leased worker died running {spec.name}"))
        elif mtype == "wdeath":
            handle = nm.worker_by_wid(msg["wid"])
            if handle is not None:
                if handle.proc.returncode is None:
                    handle.proc.returncode = 1
                self._on_worker_death(handle)
        elif mtype == "pong":
            # remote agents flush their structured-event buffer on the
            # keepalive reply (node_agent.py ping handler); timeline
            # spans recorded agent-side (transfer serves, spill IO) and
            # the agent's structured log records ride the same reply so
            # the head's dump covers every process
            events.ingest(msg.get("events") or [])
            timeline.ingest_events(msg.get("profile") or [])
            from ..utils import profiler as _profiler
            from ..utils import structlog as _structlog

            _structlog.ingest(msg.get("logs"))
            _profiler.ingest(msg.get("samples"))
            # delta-compressed control state rides the same reply:
            # status-key deltas merge into the node's head-side mirror
            # and held-row deltas (sim plane) land in the directory;
            # a seq gap raises the resync latch for the next ping
            nm.on_pong_delta(msg)

    def _bind_remote_worker(self, nm, handle: WorkerHandle) -> None:
        from .remote_node import VirtualConn

        vconn = VirtualConn(handle.worker_id.binary(), nm)
        with self._lock:
            handle.conn = vconn
            self._conn_handles[vconn] = handle
            self._conn_send_locks[vconn] = threading.Lock()
            pending = list(handle.pending_msgs)
            handle.pending_msgs.clear()
        nm.on_worker_ready(handle)
        for m in pending:
            self._send(handle, m)
        self._pump()

    def _on_agent_death(self, nm) -> None:
        """The agent channel broke: the whole remote node is gone (node
        death via heartbeat timeout / connection loss — NodeManager death
        handling, gcs_node_manager.h)."""
        with self._lock:
            if not nm.alive:
                return
            nm.mark_dead()
            self.gcs.mark_node_dead(nm.node_id)
            workers = list(nm.workers.values())
        # same drain ordering as remove_node: dead flag first, then the
        # queue snapshot under the node's lock, so a racing submit can
        # never land a spec behind the one-and-only drain
        with nm._lock:
            requeue = list(nm.queue)
            nm.queue.clear()
        for h in workers:
            self._on_worker_death(h)
        for spec in requeue:
            self._schedule(spec)
        # leases the dead agent held: no lease_dead frame is coming
        for task_id, spec in nm.take_leaf_inflight().items():
            self._maybe_retry(task_id, spec, WorkerCrashedError(
                f"node agent died with leased task {spec.name} in flight"))
        self.gcs.drop_node_objects(nm.node_id)
        self._wakeup()

    def add_remote_node_process(self, num_cpus: int = 4, num_tpus: int = 0,
                                timeout: float = 30.0) -> NodeID:
        """Spawn a node-agent subprocess joined to this head — the in-repo
        stand-in for ``rmt start --address`` on another host (and the test
        vehicle for the multi-host plane: the agent shares NOTHING with the
        head but the TCP channel)."""
        import subprocess
        import sys as _sys

        if self.node_listener_address is None:
            raise RuntimeError("node listener disabled by config")
        host, port = self.node_listener_address
        before = set(self.nodes)
        import os as _os

        env = dict(_os.environ)
        pkg_parent = _os.path.dirname(_os.path.dirname(
            _os.path.dirname(_os.path.abspath(__file__))))
        parts = [p for p in env.get("PYTHONPATH", "").split(_os.pathsep)
                 if p]
        if pkg_parent not in parts:
            env["PYTHONPATH"] = _os.pathsep.join([pkg_parent] + parts)
        proc = subprocess.Popen(
            [_sys.executable, "-m",
             "ray_memory_management_tpu.core.node_agent",
             "--address", f"{host}:{port}",
             "--authkey", self._authkey.hex(),
             "--num-cpus", str(num_cpus),
             "--num-tpus", str(num_tpus)],
            env=env, close_fds=True,
        )
        self._agent_procs.append(proc)
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                # match THIS child by its pid (registration carries the
                # agent's pid): a concurrently-registering agent must not
                # be attributed to our Popen handle
                new = [n for n in self.nodes if n not in before
                       and getattr(self.nodes[n], "agent_pid", None)
                       == proc.pid]
            if new:
                self._agent_proc_by_node[new[0]] = proc
                return new[0]
            if proc.poll() is not None:
                raise RuntimeError(
                    f"node agent exited rc={proc.returncode} before joining")
            time.sleep(0.05)
        raise TimeoutError("node agent did not register in time")

    def stop_remote_node(self, node_id: NodeID) -> None:
        """Gracefully retire an agent-process node: mark it dead in the
        cluster (requeueing its work) and terminate the agent process —
        the provider-side terminate half of the autoscaler contract."""
        self.remove_node(node_id)
        proc = self._agent_proc_by_node.pop(node_id, None)
        if proc is not None:
            try:
                proc.terminate()
                proc.wait(timeout=5.0)
            except Exception:
                pass

    def _send(self, handle: WorkerHandle, msg: dict) -> bool:
        with self._lock:
            if handle.conn is None:
                if handle.alive():
                    handle.pending_msgs.append(msg)
                    return True
                return False
            lock = self._conn_send_locks.get(handle.conn)
        if lock is None:
            return False
        try:
            with lock:
                handle.conn.send(msg)
            return True
        except (OSError, BrokenPipeError, ValueError):
            return False

    def _wakeup(self) -> None:
        try:
            os.write(self._wakeup_w, b"x")
        except OSError:
            pass

    # ---------------------------------------------------------- async sender
    def _sender_enqueue(self, handle: WorkerHandle, msg: dict) -> bool:
        """Queue a message for the connection's sender thread, which
        coalesces back-to-back dispatches to the same worker into one
        batch frame (one pickle + ONE pipe write). Every write to a worker
        pipe wakes its process — on a loaded host that is two context
        switches — so the write count, not the byte count, is the cost
        model; the calling thread never writes inline under load, it keeps
        producing while the pool drains (see _SenderPool for the
        slow-consumer isolation story)."""
        with self._lock:
            if handle.conn is None:
                if handle.alive():
                    handle.pending_msgs.append(msg)
                    return True
                return False
            conn = handle.conn
        with self._send_cond:
            chan = self._send_channels.get(conn)
            if chan is None:
                if conn not in self._conn_send_locks:
                    return False  # conn already swept by a death event
                chan = _SendChannel(conn, handle)
                self._send_channels[conn] = chan
        return self._sender_pool.enqueue(chan, msg)

    def _send_payload(self, conn, payload: dict) -> bool:
        lock = self._conn_send_locks.get(conn)
        if lock is None:
            return False
        try:
            with lock:
                conn.send(payload)
            return True
        except (OSError, BrokenPipeError, ValueError):
            return False

    # ---------------------------------------------------------------- router
    def _router_loop(self) -> None:
        """Single receive loop over all worker pipes.

        Uses one persistent epoll-backed selector: rebuilding a poll set per
        iteration (``multiprocessing.connection.wait``) costs ~100 us per
        round with tens of fds, which at high task rates was the single
        largest driver-side line item. Selectors are not thread-safe, so
        registration changes ride ``_router_adds`` and are applied here.
        """
        import selectors

        sel = selectors.DefaultSelector()
        sel.register(self._wakeup_r, selectors.EVENT_READ, None)
        registered: Dict[Any, Any] = {}

        def unregister(r) -> None:
            try:
                sel.unregister(r)
            except (KeyError, ValueError):
                pass
            registered.pop(r, None)

        def drain(r, on_msg, on_eof) -> None:
            # drain a bounded burst from this conn before moving on, so one
            # chatty peer cannot starve the others
            for _ in range(64):
                try:
                    msg = r.recv()
                except (EOFError, OSError):
                    unregister(r)
                    on_eof()
                    return
                on_msg(msg)
                try:
                    if not r.poll(0):
                        return
                except (OSError, ValueError):
                    return

        while not self._stop.is_set():
            with self._lock:
                adds = self._router_adds
                self._router_adds = []
                removals = self._router_removals
                self._router_removals = []
            for conn in removals:
                # conns closed outside the router (death by failed send)
                # must leave the selector HERE: a closed-but-registered fd
                # number can be reused by a new worker's pipe
                unregister(conn)
                try:
                    conn.close()
                except OSError:
                    pass
            for conn in adds:
                if conn not in registered and (
                        conn in self._conn_handles
                        or conn in self._agent_nodes):
                    try:
                        registered[conn] = sel.register(
                            conn, selectors.EVENT_READ, None)
                    except KeyError:
                        # fd number reused while a stale entry lingers:
                        # evict it and retry once
                        unregister(conn)
                        try:
                            registered[conn] = sel.register(
                                conn, selectors.EVENT_READ, None)
                        except (ValueError, KeyError, OSError):
                            pass
                    except (ValueError, OSError):
                        pass
            try:
                events = sel.select(timeout=0.25)
            except OSError:
                time.sleep(0.01)
                continue
            for key, _ in events:
                r = key.fileobj
                if r == self._wakeup_r:
                    try:
                        os.read(self._wakeup_r, 4096)
                    except OSError:
                        pass
                    continue
                handle = self._conn_handles.get(r)
                if handle is not None:
                    drain(r,
                          lambda m, h=handle: self._handle_worker_message(h, m),
                          lambda h=handle: self._on_worker_death(h))
                    continue
                nm = self._agent_nodes.get(r)
                if nm is not None:
                    def agent_eof(nm=nm, r=r):
                        self._agent_nodes.pop(r, None)
                        self._on_agent_death(nm)

                    drain(r, lambda m, n=nm: self._handle_agent_message(n, m),
                          agent_eof)
                    continue
                unregister(r)
            self._pump()

    def _handle_worker_message(self, handle: WorkerHandle, msg: dict) -> None:
        mtype = msg["type"]
        if mtype == "batch":  # coalesced replies from the worker's sender
            dones: List[dict] = []
            for m in msg["msgs"]:
                if m["type"] == "done":
                    dones.append(m)
                    continue
                if dones:  # flush in arrival order before the odd frame
                    self._on_tasks_done(handle, dones)
                    dones = []
                self._handle_worker_message(handle, m)
            if dones:
                self._on_tasks_done(handle, dones)
            return
        if mtype == "done":
            self._on_tasks_done(handle, [msg])
        elif mtype == "log":
            self._print_worker_log(handle, msg["data"])
        elif mtype == "stolen":
            self._on_tasks_stolen(handle, msg)
        elif mtype == "actor_created":
            self._on_actor_created(handle, msg)
        elif mtype == "device_materialized":
            self._on_device_materialized(handle, msg)
        elif mtype == "device_demoted":
            self._on_device_demoted(handle, msg)
        elif mtype == "device_consumed":
            self._on_device_consumed(handle, msg)
        elif mtype == "owned_put":
            # one-way registration of a worker-owned put: the worker
            # already minted the id and wrote its node store (zero
            # blocking round trips on the put path). Handled INLINE so
            # the location exists before the router reads this worker's
            # NEXT message — a nested submit referencing the id must not
            # race the registration on the request pool (the dep-ready
            # check treats future-less unknown ids as ready, so losing
            # that race would misread a live object as lost).
            self._on_owned_put(handle, msg)
        elif mtype == "profile":
            # flush frame from a worker's ticker (or its final exit
            # flush): straggler spans, plus optional piggybacked event,
            # log-record and metric-series batches that merge into the
            # head's buffers/registry (the agent->head aggregation path)
            if msg.get("profile"):
                timeline.ingest_events(msg["profile"])
            if msg.get("events"):
                events.ingest(msg["events"])
            if msg.get("logs"):
                from ..utils import structlog as _structlog

                _structlog.ingest(msg["logs"])
            if msg.get("series"):
                from ..utils import metrics as _metrics

                _metrics.merge_series(msg["series"])
            if msg.get("samples"):
                from ..utils import profiler as _profiler

                _profiler.ingest(msg["samples"])
        elif mtype == "pong":
            pass
        else:
            # nested-call requests from user code in the worker; may block on
            # futures, so never service them on the router thread
            self._request_pool.submit(self._serve_worker_request, handle, msg)

    def _print_worker_log(self, handle: WorkerHandle, data: bytes) -> None:
        """Worker stdout/stderr chunk -> driver output, one prefixed line at
        a time (the reference's log monitor format, ``(pid=..., ip=...)``).
        Chunks are joined per worker so a line split across reads does not
        print as two."""
        import sys

        wid = handle.worker_id
        buf = self._log_tails.get(wid, b"") + data
        lines, sep, tail = buf.rpartition(b"\n")
        self._log_tails[wid] = tail
        if not sep:
            return
        prefix = (f"(worker={wid.hex()[:8]} "
                  f"node={handle.node_id.hex()[:8]}) ")
        out = "".join(
            prefix + line + "\n"
            for line in lines.decode("utf-8", "replace").split("\n")
        )
        try:
            sys.stderr.write(out)
            sys.stderr.flush()
        except (OSError, ValueError):
            pass

    # ------------------------------------------------------- task submission
    def _index_trace_locked(self, trace_ctx, task_id: bytes) -> None:
        """With self._lock held: register a task under its trace so the
        state API can reconstruct the span tree after records prune.
        Python dicts iterate in insertion order, so eviction past the cap
        drops the OLDEST trace."""
        if not trace_ctx:
            return
        tasks = self._traces.get(trace_ctx[0])
        if tasks is None:
            while len(self._traces) >= self._traces_cap:
                self._traces.pop(next(iter(self._traces)), None)
            tasks = self._traces[trace_ctx[0]] = []
        tasks.append(task_id)

    def submit_task(self, payload: dict,
                    adopt_returns: bool = True) -> List[bytes]:
        # owning job: thin clients / job_submission drivers tag their
        # payloads; untagged submits (the in-process driver, worker-side
        # nested submits) belong to the root job. The task id inherits
        # the job's 4-byte prefix so returns are attributable by eye.
        job = payload.get("job_id") or self.job_id.binary()
        led = self.ledger_for(job)
        task_id = TaskID.for_task(
            self.job_id if job == self.job_id.binary() else JobID(job))
        num_returns = payload.get("num_returns", 1)
        return_ids = [
            ObjectID.for_return(task_id, i).binary() for i in range(num_returns)
        ]
        if payload.get("fn_blob") is not None:
            self.fn_blobs.setdefault(payload["fn_id"], payload["fn_blob"])
        # trace plane: a nested submit carries its parent context on the
        # payload (attached worker-side by WorkerRuntimeProxy); a driver
        # submit inherits any context the caller installed, else this
        # task roots a fresh trace
        parent_ctx = tracing.from_wire(payload.get("trace_parent")) \
            or tracing.get_current()
        trace_ctx = tracing.child_of(parent_ctx)
        spec = TaskSpec(
            task_id=task_id.binary(),
            name=payload.get("name", "task"),
            fn_id=payload["fn_id"],
            args=payload["args"],
            kwargs=payload.get("kwargs", {}),
            num_returns=num_returns,
            return_ids=return_ids,
            resources=payload.get("resources", {"CPU": 1.0}),
            strategy=payload.get("strategy"),
            max_retries=payload.get(
                "max_retries", self.config.task_max_retries
            ),
            retry_exceptions=payload.get("retry_exceptions", False),
            runtime_env=payload.get("runtime_env"),
            trace_ctx=trace_ctx,
            job_id=job,
        )
        rec = _TaskRecord(spec, payload, spec.max_retries,
                          gc_returns=adopt_returns)
        self._m_submitted.inc()
        with led.lock:
            led.tasks_submitted += 1
        with self._lock:
            self.tasks[spec.task_id] = rec
            self._index_trace_locked(trace_ctx, spec.task_id)
            for oid in return_ids:
                self.futures[oid] = _SlimFuture()
                self.lineage[oid] = spec.task_id
                if adopt_returns:
                    # pre-registered handle ref, ADOPTED by the
                    # caller's ObjectRef: without it a fast task
                    # completing before the wrap would see refcount
                    # zero and GC its result
                    self._incref(oid)
            # the pending task keeps its ref args (and their
            # lineage) alive even if the caller drops every handle
            # before it runs
            for oid in self._ref_deps(spec):
                self._incref(oid)
                self._lineage_dependents[oid] += 1
            nudge = self._queue_when_deps_ready_locked(spec)
        if nudge:
            self._wakeup()
        return return_ids

    def _ref_deps(self, spec: TaskSpec) -> List[bytes]:
        return spec.ref_deps  # cached on the spec (see TaskSpec.ref_deps)

    def _queue_when_deps_ready_locked(self, spec: TaskSpec) -> bool:  # rmtcheck: holds=_lock
        """With self._lock held: either park the task on its unresolved
        deps (LocalDependencyResolver analog, dependency_resolver.h:29) or
        append it to the submit queue for the router's batched scheduling
        pass. Returns True when the caller should nudge the router."""
        missing: Set[bytes] = set()
        for oid in self._ref_deps(spec):
            fut = self.futures.get(oid)
            if fut is not None and not fut.done():
                missing.add(oid)
        if missing:
            self._waiting_deps[spec.task_id] = missing
            for oid in missing:
                self._dep_waiters[oid].append(spec.task_id)
            return False
        rec = self.tasks.get(spec.task_id)
        if rec is not None:
            rec.ts["QUEUED"] = time.time()
        self._submit_q.append(spec)
        if self._submit_nudged:
            return False
        self._submit_nudged = True
        return True

    def _resolve_deps_then_schedule(self, spec: TaskSpec) -> None:
        """Queue the task once its args are materialized; the router pump
        schedules queued tasks in batches."""
        with self._lock:
            nudge = self._queue_when_deps_ready_locked(spec)
        if nudge:
            self._wakeup()

    def _deps_ready_locked(self, oid: bytes) -> bool:  # rmtcheck: holds=_lock
        """With self._lock held: resolve every task parked on ``oid``,
        queueing newly-unblocked specs for the router's batched scheduling
        pass. Returns True when the caller should nudge the router."""
        nudge = False
        for task_id in self._dep_waiters.pop(oid, ()):
            missing = self._waiting_deps.get(task_id)
            if missing is None:
                continue
            missing.discard(oid)
            if not missing:
                del self._waiting_deps[task_id]
                rec = self.tasks.get(task_id)
                if rec:
                    rec.ts["QUEUED"] = time.time()
                    self._submit_q.append(rec.spec)
                    if not self._submit_nudged:
                        self._submit_nudged = True
                        nudge = True
        return nudge

    def _on_dep_ready(self, oid: bytes) -> None:
        with self._lock:
            nudge = self._deps_ready_locked(oid)
        if nudge:
            self._wakeup()

    def _release_pg_allocation(self, spec: TaskSpec) -> None:
        if spec.placement is not None and self.pg_manager is not None:
            self.pg_manager.release_key(spec.task_id)

    def _release_task_args(self, spec: TaskSpec) -> None:
        """Drop the references a task held on its ref args (idempotent;
        called from every terminal path)."""
        with self._lock:
            rec = self.tasks.get(spec.task_id)
            if rec is None or rec.args_released:
                return
            rec.args_released = True
        for oid in self._ref_deps(spec):
            self.remove_local_ref(oid)

    def _fail_task(self, spec: TaskSpec, exc: Exception) -> None:
        self._release_pg_allocation(spec)
        with self._lock:
            for oid in spec.return_ids:
                fut = self.futures.get(oid)
                if fut and not fut.done():
                    fut.set_exception(exc)
            rec = self.tasks.get(spec.task_id)
            if rec:
                rec.state = "FAILED"
                rec.ts["FAILED"] = time.time()
        if rec:
            self._m_failed.inc()
        self._release_task_args(spec)
        self._release_job_slot(spec)

    # --------------------------------------------- agent-local leaf scheduling
    def _leaf_eligible(self, spec: TaskSpec) -> bool:
        """A LEAF task may bypass the head's full placement pass: no
        placement-group/affinity constraint, no runtime_env, not an
        actor method, at most one CPU (and nothing else), and every ref
        arg already in the driver memory store — so the exec frame is
        self-contained (args inline, no transfer planning, no locality
        scoring)."""
        if (spec.is_actor_task or spec.strategy is not None
                or spec.placement is not None or spec.runtime_env):
            return False
        req = spec.req
        for name in req.names():
            if name == CPU:
                if req.get(name) > 1.0:
                    return False
            elif req.get(name):
                return False
        for oid in self._ref_deps(spec):
            if oid not in self.memory_store:
                return False
        return True

    def _try_leaf_place(self, spec: TaskSpec) -> bool:
        """Decentralized leaf dispatch: hand the task straight to a node
        holding spare lease credit (round-robin over nodes), skipping
        pick_node + locality. A local node rides its ordinary dispatch
        queue; a remote node gets the fully-built exec frame and its
        AGENT picks the worker (lease_exec). Every pool saturated →
        spillback to the shared scheduler."""
        nodes = list(self.nodes.values())
        if not nodes:
            return False
        n = len(nodes)
        # sticky round-robin: place short RUNS (4 tasks) on one node
        # before advancing, so a burst reaches each node as a few
        # contiguous dispatches instead of a per-task interleave — the
        # node's dispatch thread wakes once per run, not once per task
        if self._leaf_run >= 4:
            self._leaf_rr += 1
            self._leaf_run = 0
        start = self._leaf_rr % n
        placed = False
        for i in range(n):
            idx = (start + i) % n
            nm = nodes[idx]
            if nm.submit_leaf(spec, self._leaf_task_msg):
                if idx == start:
                    self._leaf_run += 1
                else:
                    self._leaf_rr, self._leaf_run = idx, 1
                placed = True
                break
        if not placed:
            self._m_leaf_spill.inc()
            return False
        self._m_leaf_placed.inc()
        with self._lock:
            rec = self.tasks.get(spec.task_id)
            if rec:
                rec.state = "SCHEDULED"
                rec.ts["SCHEDULED"] = time.time()
        return True

    def _leaf_task_msg(self, nm, spec: TaskSpec) -> dict:
        """The exec frame for an agent-routed leaf task. Unlike
        _task_msg the fn blob ships once per NODE (the agent re-attaches
        it per worker from its own cache) and args are always inline —
        _leaf_eligible required every ref dep in the memory store."""
        args = [self._finalize_arg(a) for a in spec.args]
        kwargs = {k: self._finalize_arg(v) for k, v in spec.kwargs.items()}
        msg = {
            "type": "exec", "task_id": spec.task_id, "fn_id": spec.fn_id,
            "name": spec.name, "args": args, "kwargs": kwargs,
            "return_ids": spec.return_ids,
        }
        with nm._lock:
            if spec.fn_id not in nm.lease_known_fns:
                msg["fn_blob"] = self.fn_blobs[spec.fn_id]
                nm.lease_known_fns.add(spec.fn_id)
        if spec.trace_ctx:
            msg["trace_ctx"] = spec.trace_ctx
        return msg

    def _schedule(self, spec: TaskSpec, pump: bool = True,
                  locality: Optional[Dict[NodeID, int]] = None) -> None:
        if spec.task_id in self._cancelled:
            self._fail_task(spec, TaskError(spec.name, None, "cancelled"))
            return
        strategy = spec.strategy
        if isinstance(strategy, PlacementGroupSchedulingStrategy) or (
            spec.placement is not None
        ):
            from .placement_group import resolve_pg_node

            node_id = resolve_pg_node(self, spec)
            if node_id is None:
                with self._lock:
                    self._pending_schedule.append(spec)
                return
        else:
            if locality is None:
                # non-batched callers (retries, node-death re-placement):
                # compute this spec's locality solo
                locality = self._batch_locality([spec]).get(spec.task_id)
            try:
                node_id = self.scheduler.pick_node(spec.req, strategy,
                                                   locality=locality)
            except ValueError as e:
                self._fail_task(spec, TaskError(spec.name, None, str(e)))
                return
            if node_id is None:
                with self._lock:
                    self._pending_schedule.append(spec)
                return
        self._place_on_node(spec, node_id, pump=pump)

    def _submit_to_node(self, node_id: NodeID, spec: TaskSpec) -> None:
        """Hand one spec to a node's dispatch queue under the dispatch
        RetryPolicy: a transient control.dispatch failure (the injectable
        fault site in NodeManager.submit) is retried with backoff instead
        of failing a task the cluster could still run."""
        try:
            self._dispatch_retry.run(self.nodes[node_id].submit, spec)
        except NodeDeadError:
            # the node died between placement and hand-off (e.g. while
            # this task's args were still in transfer) — re-place on a
            # live node instead of wedging on a queue nobody drains
            self._schedule(spec)

    def _place_on_node(self, spec: TaskSpec, node_id: NodeID,
                       pump: bool = True) -> None:
        nm = self.nodes[node_id]
        if not self._ensure_args_local(spec, node_id):
            return  # transfer in flight; re-placed when it completes
        had_backlog = bool(nm.queue)
        self._submit_to_node(node_id, spec)
        with self._lock:
            rec = self.tasks.get(spec.task_id)
            if rec:
                rec.state = "SCHEDULED"
                rec.ts["SCHEDULED"] = time.time()
        if not pump:
            return  # router pump dispatches for the whole batch
        if had_backlog:
            # a backlogged node dispatches from the router's pump on every
            # completion; re-running the head-of-line check per submit
            # would be O(queue) work for nothing. The self-pipe nudge is
            # ~1 us and wakes no other process.
            self._wakeup()
        else:
            self._pump_node(nm)

    def _ensure_args_local(self, spec: TaskSpec, node_id: NodeID) -> bool:
        """Make every ref arg readable on ``node_id``'s store. Inline args in
        the driver memory store don't need transfer (they ship in the exec
        message). Cross-node copies run on the transfer pool — the chunked
        push/pull object plane (object_manager.h:114) collapsed to a same-host
        memcpy."""
        to_fetch: List[Tuple[bytes, list]] = []
        with self._lock:
            for oid in self._ref_deps(spec):
                if oid in self.memory_store:
                    continue
                target_store = self.nodes[node_id].store
                if target_store.contains(oid):
                    continue
                locs = self.gcs.get_object_locations(oid)
                locs = [l for l in locs if l != node_id and
                        self.nodes.get(l) and self.nodes[l].alive]
                if not locs:
                    if oid in self._device_locations:
                        # device-resident dep: materialize off the router
                        # thread, then re-place the task
                        self._transfer_pool.submit(
                            self._materialize_then_reschedule, oid, spec,
                            node_id)
                        return False
                    # lost object: trigger recovery, then retry scheduling
                    self._transfer_pool.submit(
                        self._recover_then_reschedule, oid, spec, node_id
                    )
                    return False
                # hold the CANDIDATE set, not a picked source: the pick
                # happens inside _transfer_from on the transfer thread,
                # where the broadcast gate can first wait for an earlier
                # in-flight copy to land and then pull from the NEW holder
                # (distribution tree) — a pick taken here, possibly
                # seconds before the transfer runs, would always name the
                # original producer
                to_fetch.append((oid, locs))
        if not to_fetch:
            return True
        prestage = bool(self.config.argument_prefetch)

        def do_transfers(resubmit: bool = True):
            lost = None
            degraded = []
            landed = 0
            for oid, locs in to_fetch:
                try:
                    self._transfer_from(oid, locs, node_id)
                    landed += 1
                except Exception as e:  # noqa: BLE001
                    # A failed or backpressured prefetch must never fail
                    # the task while the object is still live somewhere:
                    # the worker's own arg fetch (get_objects ->
                    # _serve_get) re-transfers, restores from spill, or
                    # serves the bytes inline as its last resort. Only a
                    # genuinely lost object goes to lineage recovery.
                    if self._object_alive(oid):
                        degraded.append((oid, e))
                    elif lost is None:
                        lost = (oid, e)
            if lost is not None:
                if resubmit:
                    # recovery re-places the task (and fails it only when
                    # the object is unrecoverable)
                    self._recover_then_reschedule(lost[0], spec, node_id)
                    return
                # prestaged task is already on the node's dispatch queue:
                # its worker's arg get runs lineage recovery (_serve_get)
                degraded.append(lost)
            if degraded:
                events.emit(
                    "TRANSFER_DEGRADED",
                    f"dispatching {spec.name} with {len(degraded)} arg(s) "
                    f"not prefetched (first: {degraded[0][0].hex()[:8]}: "
                    f"{degraded[0][1]!r}); worker will fetch inline",
                    severity=events.WARNING, source="object_manager")
            if not resubmit:
                # prestage epilogue: the task was submitted before the
                # pull started — just account, stamp, and nudge dispatch
                if landed:
                    self._m_prefetch_completed.inc(landed)
                with self._lock:
                    rec = self.tasks.get(spec.task_id)
                    if rec:
                        rec.ts["PREFETCH_DONE"] = time.time()
                self._wakeup()
                return
            try:
                self._submit_to_node(node_id, spec)
                self._wakeup()
            except Exception as e:  # noqa: BLE001
                self._fail_task(spec, TaskError(spec.name, e))

        if prestage:
            # pipelined argument prestage: hand the task to the node's
            # dispatch queue NOW and pull its args concurrently, so the
            # striped pull overlaps queue wait instead of serializing in
            # front of execution. Safe because a worker that dequeues the
            # task early simply blocks in its arg get until the SAME copy
            # lands (create_or_wait dedupes racing fetches) or falls back
            # to the inline-serve path.
            with self._lock:
                rec = self.tasks.get(spec.task_id)
                if rec:
                    rec.ts.setdefault("PREFETCH_START", time.time())
            self._m_prefetch_started.inc(len(to_fetch))
            self._transfer_pool.submit(
                self._with_trace, spec.trace_ctx, do_transfers, False)
            return True
        self._transfer_pool.submit(
            self._with_trace, spec.trace_ctx, do_transfers)
        return False

    @staticmethod
    def _with_trace(ctx, fn, *args):
        """Run ``fn`` on this (pool) thread with ``ctx`` installed as the
        current trace context: transfers happen off the submitting thread,
        so the context must travel to the thread doing the IO for the
        spans/wire-requests it records to name the right task."""
        token = tracing.set_current(ctx)
        try:
            return fn(*args)
        finally:
            tracing.reset(token)

    def _object_alive(self, oid: bytes) -> bool:
        """True while ANY live copy exists: the driver memory store, or a
        live node's store/spill tier (GCS locations cover both — spilled
        objects keep their node's location)."""
        with self._lock:
            if oid in self.memory_store:
                return True
        return any(
            self.nodes.get(l) is not None and self.nodes[l].alive
            for l in self.gcs.get_object_locations(oid))

    def _xfer_dec_locked(self, src: NodeID) -> None:
        n = self._xfer_serving.get(src, 1) - 1
        if n > 0:
            self._xfer_serving[src] = n
        else:
            self._xfer_serving.pop(src, None)

    def _pick_transfer_source(self, locs) -> NodeID:
        """Least-loaded holder, taking a serve count the caller MUST pair
        with ``_xfer_dec_locked`` (``_transfer_from`` does) — the single
        source-selection point for every transfer path."""
        with self._lock:
            src = min(locs, key=lambda l: self._xfer_serving.get(l, 0))
            self._xfer_serving[src] = self._xfer_serving.get(src, 0) + 1
            self._xfer_served_total[src] = (
                self._xfer_served_total.get(src, 0) + 1)
        return src

    def _live_holders(self, oid: bytes, dst: NodeID) -> list:
        """Current live holders of ``oid`` other than ``dst`` — re-read at
        transfer time so pulls that waited at the broadcast gate see
        copies that landed while they waited."""
        return [l for l in self.gcs.get_object_locations(oid)
                if l != dst and self.nodes.get(l) is not None
                and self.nodes[l].alive]

    def _holder_addrs(self, oid: bytes) -> list:
        """Transfer-plane (host, port) addresses of the CURRENT live
        holders of ``oid`` — the alt-source resolver a fetch re-invokes
        at each failover, so holders that died mid-pull are excluded and
        copies that landed since are found. Head-local holders serve via
        their lazy local TransferServer ("" host = loopback for the
        head; agents receive their head_ip substitution in _obj_fetch)."""
        out = []
        for l in self.gcs.get_object_locations(oid):
            nm = self.nodes.get(l)
            if nm is None or not nm.alive:
                continue
            addr = getattr(nm, "transfer_addr", None)
            if addr is not None:
                out.append((addr[0], addr[1]))
            elif getattr(nm, "store", None) is not None:
                try:
                    out.append(("", self._local_transfer_server(l).port))
                except Exception:  # noqa: BLE001
                    pass
        return out

    def _fetch_policy(self):
        """The head-side transfer RetryPolicy from config knobs."""
        from ..utils.retry import RetryPolicy

        return RetryPolicy(
            max_attempts=self.config.transfer_retry_attempts,
            base_backoff_s=self.config.transfer_retry_backoff_s,
            plane="transfer")

    def _prune_stale_location(self, oid: bytes, node_id: NodeID,
                              err: Optional[str]) -> None:
        """Drop a GCS object-directory location that a fetch proved stale
        ("object not in store"): the directory said the holder had it, the
        holder disagreed — leaving the entry would re-route every retry
        and failover back to the same empty holder."""
        if not err or "object not in store" not in err:
            return
        try:
            self.gcs.prune_location(oid, node_id)
        except Exception:  # noqa: BLE001
            pass

    def _broadcast_admit(self, oid: bytes, timeout: float = 15.0) -> None:
        """Distribution-tree admission for multi-destination pulls of ONE
        object: at most ``transfer_broadcast_fanout`` concurrent pulls per
        live holder. Excess pulls WAIT until an in-flight copy lands —
        each landing registers a new holder in the GCS, raising the cap
        AND giving the waiter a closer source, so an n-destination
        broadcast becomes a pipelined tree (O(size·log n) source egress)
        instead of n serial streams off one node. The gate is advisory:
        waits are deadline-bounded and a timeout proceeds anyway (worst
        case is the old source-bottlenecked behavior, never a stall)."""
        fanout = self.config.transfer_broadcast_fanout
        if fanout <= 0:
            return
        deadline = time.monotonic() + timeout
        waited = False
        with self._bcast_cond:
            while True:
                holders = max(1, len(self._live_holders(oid, dst=None)))
                if self._oid_pulls.get(oid, 0) < fanout * holders:
                    break
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                waited = True
                self._bcast_cond.wait(min(remaining, 1.0))
            self._oid_pulls[oid] = self._oid_pulls.get(oid, 0) + 1
        if waited:
            try:
                from . import metrics_defs as mdefs

                mdefs.transfer_broadcast_waits().inc()
            except Exception:  # noqa: BLE001
                pass

    def _broadcast_release(self, oid: bytes) -> None:
        with self._bcast_cond:
            n = self._oid_pulls.get(oid, 1) - 1
            if n > 0:
                self._oid_pulls[oid] = n
            else:
                self._oid_pulls.pop(oid, None)
            self._bcast_cond.notify_all()

    def _transfer_from(self, oid: bytes, locs, dst: NodeID) -> None:
        """Move ``oid`` to ``dst`` from the best CURRENT holder. Admission
        through the broadcast gate first (late pulls in a fan-out wait for
        an earlier copy, then pull from the new holder), then a fresh
        holder read — the passed ``locs`` is only the fallback when the
        re-read finds nothing (e.g. locations not yet registered). Serve
        accounting is balanced on every exit."""
        self._broadcast_admit(oid)
        try:
            fresh = self._live_holders(oid, dst)
            src = self._pick_transfer_source(fresh or locs)
            try:
                self._transfer_object(oid, src, dst)
            finally:
                with self._lock:
                    self._xfer_dec_locked(src)
        finally:
            self._broadcast_release(oid)

    def _local_transfer_server(self, node_id: NodeID):
        """Lazy TransferServer over a LOCAL node's store, so remote agents
        can pull its objects directly (the head serves like any peer)."""
        from .transfer import TransferServer

        with self._lock:
            srv = self._xfer_servers.get(node_id)
            if srv is None:
                srv = TransferServer(
                    self.nodes[node_id].store, self._authkey,
                    self.config.object_manager_chunk_size,
                    max_conns=self.config.transfer_max_conns,
                    idle_timeout=self.config.transfer_idle_timeout_s,
                    compress_min_bytes=(
                        self.config.transfer_compress_min_bytes))
                self._xfer_servers[node_id] = srv
        return srv

    def _transfer_object(self, oid: bytes, src: NodeID, dst: NodeID) -> None:
        """Move an object between node stores, recording ONE transfer
        span per movement (every path — memcpy, channel push, p2p pull —
        funnels through here). The span is a CHILD of the current trace
        context (the task the transfer serves, installed by _with_trace),
        so Perfetto draws task→transfer arrows and the critical-path
        summary can attribute the time."""
        cur = tracing.get_current()
        ctx = tracing.child_of(cur) if cur else None
        t0 = time.time()
        try:
            self._transfer_object_impl(oid, src, dst, trace=ctx)
        finally:
            timeline.record_event(
                f"transfer::{oid.hex()[:8]}", "transfer", t0, time.time(),
                extra={"oid": oid.hex(), "src": str(src), "dst": str(dst)},
                trace=ctx)

    def _transfer_object_impl(self, oid: bytes, src: NodeID, dst: NodeID,
                              trace=None) -> None:
        """Move an object between node stores. Same-host pairs memcpy
        between shm mappings. Pairs involving a remote node are
        RECEIVER-DRIVEN over the p2p transfer plane (transfer.py): the
        destination pulls chunks straight from the source's transfer
        server, so payload bytes never transit the head and never queue
        behind dispatch frames on the agent channel (the reference's
        object-manager peer pull, object_manager.h:114). The channel
        push/pull path remains as the fallback."""
        from .remote_node import RemoteNodeManager

        src_nm = self.nodes[src]
        dst_nm = self.nodes[dst]
        src_remote = isinstance(src_nm, RemoteNodeManager)
        dst_remote = isinstance(dst_nm, RemoteNodeManager)

        if dst_remote:
            # destination agent pulls from the source's server; when the
            # two share a host it maps the source's shm segment directly
            # and memcpys (no TCP, no chunk protocol)
            if src_remote:
                addr = src_nm.transfer_addr
                src_store = (src_nm.remote_store_name
                             if src_nm.hostname == dst_nm.hostname else None)
            else:
                addr = ("", self._local_transfer_server(src).port)
                src_store = (src_nm.store_name
                             if dst_nm.hostname == self._hostname else None)
            if addr is not None:
                err = dst_nm.fetch_from_peer(oid, addr[0], addr[1],
                                             src_store=src_store,
                                             alts=self._holder_addrs(oid),
                                             trace=trace)
                if err is None:
                    self.gcs.add_object_location(oid, dst)
                    return
                events.emit(
                    "TRANSFER_FALLBACK",
                    f"p2p fetch of {oid.hex()[:8]} failed ({err}); "
                    "falling back to channel push",
                    severity=events.WARNING, source="object_manager")
        elif src_remote:
            # local destination: the head pulls from the source's server
            # straight into the destination store (no staging buffer)
            addr = src_nm.transfer_addr
            if addr is not None:
                from .transfer import fetch_object

                err = fetch_object(
                    addr[0], addr[1], self._authkey, oid, dst_nm.store,
                    self.config.object_manager_chunk_size,
                    pool=self._xfer_conn_pool,
                    stripe_threshold=self.config.transfer_stripe_threshold,
                    stripe_count=self.config.transfer_stripe_count,
                    alt_sources=lambda: self._holder_addrs(oid),
                    retry=self._fetch_policy(),
                    verify_checksum=self.config.transfer_verify_checksum,
                    stripe_deadline=self.config.transfer_stripe_deadline_s,
                    trace=trace,
                    codecs=wire_codec.client_codecs(self.config))
                if err is None:
                    self.gcs.add_object_location(oid, dst)
                    return
                self._prune_stale_location(oid, src, err)
                events.emit(
                    "TRANSFER_FALLBACK",
                    f"p2p fetch of {oid.hex()[:8]} failed ({err}); "
                    "falling back to channel pull",
                    severity=events.WARNING, source="object_manager")

        # same-host memcpy, or the channel push/pull fallback
        src_cli = self._store_client_for(src)
        view = src_cli.get(oid)  # local: shm view; remote: pulled bytes
        if view is None and src_cli is not getattr(src_nm, "store", None):
            # same-host mapping can't see objects SPILLED inside the
            # source agent; the channel proxy serves them from the spill
            # file (mirror of the _read_from_stores fallback)
            proxy = getattr(src_nm, "store", None)
            if proxy is not None:
                view = proxy.get(oid)
                src_cli = proxy
        if view is None:
            raise ObjectLostError(oid.hex(), f"vanished from {src}")
        try:
            if dst_remote:
                ok, perr = dst_nm.push_object(oid, view)
                if not ok:
                    # our read ref (view) kept the source copy live the
                    # whole time — a receiver that stayed full past the
                    # retry budget is PRESSURE, not loss; type the error
                    # so callers degrade (inline-serve / dispatch-anyway)
                    # instead of reporting a live object lost
                    if perr and "retryable" in perr:
                        raise ObjectStoreFullError(
                            f"push of {oid.hex()[:8]} to "
                            f"{dst_nm.hostname} backpressured past the "
                            f"retry budget ({perr})")
                    raise ObjectLostError(
                        oid.hex(),
                        f"push to {dst_nm.hostname} failed ({perr})")
            else:
                dst_store = dst_nm.store
                chunk = self.config.object_manager_chunk_size
                try:
                    buf = dst_store.create(oid, view.nbytes)
                except ValueError:
                    return  # already there
                for off in range(0, view.nbytes, chunk):
                    end = min(off + chunk, view.nbytes)
                    buf[off:end] = view[off:end]
                dst_store.seal(oid)
                # same-host copies count as data movement too — without
                # this the virtual-node benches under-report bytes moved
                mdefs.transfer_bytes().observe(
                    float(view.nbytes), tags={"direction": "local_copy"})
            self.gcs.add_object_location(oid, dst, size=view.nbytes)
        finally:
            src_cli.release(oid)

    def _recover_then_reschedule(self, oid: bytes, spec: TaskSpec,
                                 node_id: NodeID) -> None:
        try:
            self._recover_object(oid)
            self._place_on_node(spec, node_id)
        except Exception as e:
            self._fail_task(spec, TaskError(spec.name, e))

    def _materialize_then_reschedule(self, oid: bytes, spec: TaskSpec,
                                     node_id: NodeID) -> None:
        try:
            if not self._ensure_device_materialized(oid):
                self._recover_object(oid)
            self._place_on_node(spec, node_id)
        except Exception as e:
            self._fail_task(spec, TaskError(spec.name, e))

    def _batch_locality(self, specs) -> Dict[TaskID, Dict[NodeID, int]]:
        """Per-task argument-bytes-by-node for a scheduling batch: ONE
        batched GCS directory lookup (locate_objects) over the union of
        every task's ref args, folded into ``{task_id: {node_id:
        bytes}}`` for the scheduler's soft locality score. Memory-store
        (inline) args never count — they ship in the exec message.
        Tasks with no ref args are absent from the result (the common
        no-arg task pays one attribute check, nothing else)."""
        if self.config.scheduler_locality_weight <= 0:
            return {}
        want: Set[bytes] = set()
        deps_by_task = []
        for spec in specs:
            deps = self._ref_deps(spec)
            if deps:
                deps_by_task.append((spec, deps))
                want.update(deps)
        if not want:
            return {}
        with self._lock:
            want = {oid for oid in want if oid not in self.memory_store}
        if not want:
            return {}
        directory = self.gcs.locate_objects(want)
        out: Dict[TaskID, Dict[NodeID, int]] = {}
        for spec, deps in deps_by_task:
            acc: Dict[NodeID, int] = {}
            for oid in deps:
                size, holders, tiers = directory.get(oid, (0, (), {}))
                if not size:
                    continue
                for nid in holders:
                    # device-resident args count double: running where
                    # the HBM pin lives avoids the device→host
                    # materialization on top of the wire transfer
                    w = 2 if tiers.get(nid) == "hbm" else 1
                    acc[nid] = acc.get(nid, 0) + size * w
            if acc:
                out[spec.task_id] = acc
        return out

    # ------------------------------------------------------------- dispatch
    def _pump(self) -> None:
        if self.pg_manager is not None:
            self.pg_manager.retry_pending()
        # free-flushing is ROUTER-only work: an application thread that
        # inline-pumps on submit must not pay for store deletes + record
        # prune cascades there (that cost on the submitting thread is
        # what the deferred buffer exists to avoid)
        if threading.current_thread() is self._router:
            self._flush_deferred_frees()
        with self._lock:
            submits = list(self._submit_q)
            self._submit_q.clear()
            self._submit_nudged = False
            pending = list(self._pending_schedule)
            self._pending_schedule.clear()
        # batched scheduling: place every queued task first (no per-task
        # dispatch pump), then run ONE dispatch pass per node below.
        # Locality is computed for the WHOLE batch up front — one GCS
        # directory lookup over the union of every task's ref args, not
        # one per task per candidate node
        multi_job = len(self._job_ledgers) > 1
        for batch, fresh in ((submits, True), (pending, False)):
            if not batch:
                continue
            if multi_job:
                # job plane: park specs whose job is at its cpu_slots cap
                # (they re-enter as slots free), then interleave the rest
                # by stride-scheduled virtual time so concurrent jobs get
                # priority-weighted fair shares of this drain
                batch = self._admit_batch(batch)
                if not batch:
                    continue
            if fresh and self._leaf_enabled:
                # leaf fast path: fresh submits only — spillbacks and
                # retries arrive via _pending_schedule and always take
                # the full pass (no leaf ping-pong)
                rest = []
                for spec in batch:
                    if (spec.task_id in self._cancelled
                            or not self._leaf_eligible(spec)
                            or not self._try_leaf_place_or_preempt(spec)):
                        rest.append(spec)
                batch = rest
                if not batch:
                    continue
            loc_by_task = self._batch_locality(batch)
            for spec in batch:
                self._schedule(spec, pump=False,
                               locality=loc_by_task.get(spec.task_id, {}))
        bounced = False
        for nm in list(self.nodes.values()):
            # ship this pass's buffered leaf grants: one lease_batch
            # frame per node instead of one lease_exec per task. Specs a
            # broken channel bounced reroute like a lease_spill.
            for spec in nm.flush_leases():
                self._m_leaf_spill.inc()
                with self._lock:
                    self._pending_schedule.append(spec)
                bounced = True
            self._pump_node(nm)
        if bounced:
            self._wakeup()

    def _pump_node(self, nm: NodeManager) -> None:
        nm.try_dispatch(self._send_task)
        victim = nm.pick_steal_victim()
        if victim is not None:
            # idle capacity + pipelined backlog elsewhere: ask the busiest
            # worker to hand back its not-yet-started tasks (work stealing).
            # The steal frame rides the SENDER QUEUE so it cannot overtake
            # task frames still queued for this conn, and holds the
            # victim's send_lock so it serializes with a concurrent
            # _send_task msg build — otherwise the steal could slip ahead
            # of a pipelined dispatch whose fn_blob decision predates it.
            with victim.send_lock:
                ok = self._sender_enqueue(victim, {"type": "steal"})
            if not ok:
                victim.steal_pending = False
                self._on_worker_death(victim)  # retries its inflight

    def _on_tasks_stolen(self, handle: WorkerHandle, msg: dict) -> None:
        nm = self.nodes.get(handle.node_id)
        if nm is None:
            return
        specs = nm.return_stolen(handle, msg["task_ids"])
        if specs:
            self._pump_node(nm)

    def _send_task(self, handle: WorkerHandle, spec: TaskSpec) -> None:
        # two dispatchers can target one worker concurrently (submit-path
        # pump + router pump); the fn_blob ships-once decision inside
        # _task_msg must stay atomic with enqueue order
        with handle.send_lock:
            msg = self._task_msg(handle, spec)
            ok = self._sender_enqueue(handle, msg)
        if not ok:
            self._on_worker_death(handle)
            return
        rec = self.tasks.get(spec.task_id)  # lock-free: dict read + stamp
        if rec is not None:
            rec.ts["DISPATCHED"] = time.time()

    def _task_msg(self, handle: WorkerHandle, spec: TaskSpec) -> dict:
        args = [self._finalize_arg(a) for a in spec.args]
        kwargs = {k: self._finalize_arg(v) for k, v in spec.kwargs.items()}
        if spec.is_actor_task:
            msg = {
                "type": "exec_actor", "task_id": spec.task_id,
                "actor_id": spec.actor_id, "method": spec.method,
                "name": spec.name, "args": args, "kwargs": kwargs,
                "return_ids": spec.return_ids, "seq": spec.seq,
            }
        else:
            msg = {
                "type": "exec", "task_id": spec.task_id, "fn_id": spec.fn_id,
                "name": spec.name, "args": args, "kwargs": kwargs,
                "return_ids": spec.return_ids,
            }
            if spec.runtime_env:
                msg["runtime_env"] = spec.runtime_env
            if spec.fn_id not in handle.known_fns:
                msg["fn_blob"] = self.fn_blobs[spec.fn_id]
                handle.known_fns.add(spec.fn_id)
            if handle.visible_chips is not None:
                msg["visible_chips"] = ",".join(
                    str(c) for c in handle.visible_chips
                )
        if spec.trace_ctx:
            # the dispatch frame carries the task's trace context so the
            # worker's exec span (and any nested submit inside the task
            # body) lands on the same causal chain
            msg["trace_ctx"] = spec.trace_ctx
        return msg

    def _finalize_arg(self, arg):
        kind, payload = arg
        if kind == "ref":
            data = self.memory_store.get(payload)
            if data is not None:
                return ("v", data)
        return arg

    # ------------------------------------------------------------ completion
    def _on_tasks_done(self, handle: WorkerHandle, msgs: List[dict]) -> None:
        """Process a burst of task completions from one worker. The success
        path takes self._lock ONCE for the whole burst (futures, return
        locations, dep-waiter resolution) — per-message locking was the
        completion side's dominant cost at high task rates."""
        profile: List[dict] = []
        logs: List[dict] = []
        samples: List[dict] = []
        for m in msgs:
            if m.get("profile"):
                profile.extend(m["profile"])
            if m.get("logs"):
                logs.extend(m["logs"])
            if m.get("samples"):
                samples.extend(m["samples"])
        if profile:
            timeline.ingest_events(profile)
        if logs:
            # BEFORE futures resolve: a task's last log line must be
            # queryable (state.get_logs) the moment its get() returns
            from ..utils import structlog as _structlog

            _structlog.ingest(logs)
        if samples:
            # same contract as logs: the burner's stacks are queryable
            # (state.get_profile) the moment its get() returns
            from ..utils import profiler as _profiler

            _profiler.ingest(samples)
        nm = self.nodes.get(handle.node_id)
        for m in msgs:
            # borrowed-ref tables ride every done reply (success or not)
            if m.get("borrows") or m.get("releases") \
                    or m.get("owned_drops"):
                self._apply_worker_ref_tables(
                    handle, m.get("borrows"), m.get("releases"),
                    m.get("owned_drops"))
        simple: List[tuple] = []
        errored: List[tuple] = []
        for m in msgs:
            task_id = m["task_id"]
            spec = handle.inflight.get(task_id)
            if spec is not None:
                if nm:
                    nm.finish_task(handle, task_id)
            elif nm:
                # agent-leased leaf task: the head's worker handle never
                # saw the dispatch, so finish_task would re-idle an
                # already-idle handle — return the lease credit instead
                spec = nm.finish_leaf(task_id)
            if spec is not None and spec.placement is not None:
                self._release_pg_allocation(spec)
            (errored if m["error"] is not None else simple).append((m, spec))
        for m, spec in errored:
            task_id = m["task_id"]
            with self._lock:
                rec = self.tasks.get(task_id)
            exc = ser.loads(m["error"])
            if rec and spec and rec.retries_left > 0 and spec.retry_exceptions:
                rec.retries_left -= 1
                self._m_retried.inc()
                events.emit(
                    "TASK_RETRY",
                    f"retrying {spec.name} after {type(exc).__name__}",
                    severity=events.WARNING, source="core_worker",
                    task_id=task_id.hex())
                self._resolve_deps_then_schedule(spec)
                continue
            if rec and spec:
                self._fail_task(spec, exc)
        if not simple:
            return
        if self._wal_enabled:
            # durability pre-pass BEFORE any future resolves: once a
            # get() returns, the sealed value must survive a head
            # restart (the WAL write is the seal). Outside the batch
            # lock — storage IO must not serialize completions.
            for m, _spec in simple:
                for oid, kind, data in m["returns"]:
                    if kind == "v" and len(data) <= self._wal_max:
                        self.gcs.wal_put_sealed(oid, data)
        nudge = False
        to_free: List[bytes] = []
        done_t = time.time()  # one stamp for the whole burst
        stage_durs: List[Dict[str, float]] = []
        rusage_list: List[Dict[str, float]] = []
        # head-side lifecycle spans: collected under the lock, emitted
        # outside it (record_event takes the timeline lock)
        trace_spans: Optional[List[tuple]] = \
            [] if timeline.is_enabled() else None
        with self._lock:
            for m, spec in simple:
                for oid, kind, data in m["returns"]:
                    if kind == "v":
                        self.memory_store[oid] = data
                    else:
                        # "store" returns carry total_size as the payload:
                        # the directory learns bytes for locality scoring
                        self.gcs.add_object_location(oid, handle.node_id,
                                                     size=data)
                    fut = self.futures.get(oid)
                    if fut is None:
                        self.futures[oid] = fut = _SlimFuture()
                    if not fut.done():
                        if isinstance(fut, _SlimFuture):
                            fut.set_result_quiet(True)  # broadcast below,
                        else:                           # once per burst
                            fut.set_result(True)
                    # dep-waiter resolution under the same (batch-wide) lock
                    if self._deps_ready_locked(oid):
                        nudge = True
                rec = self.tasks.get(m["task_id"])
                if rec:
                    rec.state = "FINISHED"
                    wt = m.get("tstamps")
                    if wt:
                        rec.ts.update(wt)
                    rec.ts["FINISHED"] = done_t
                    ru = m.get("rusage")
                    if ru:
                        rec.rusage = ru
                        rusage_list.append(ru)
                    stage_durs.append(stage_durations(rec.ts))
                    if trace_spans is not None:
                        trace_spans.append(
                            (rec.spec.name, rec.spec.task_id,
                             rec.spec.trace_ctx, dict(rec.ts)))
                # arg release + fire-and-forget GC stay inside the batch
                # lock (per-task locking was the completion side's
                # dominant cost); only the zero-ref free_object calls run
                # outside it
                if spec is not None and rec is not None \
                        and not rec.args_released:
                    rec.args_released = True
                    for oid in self._ref_deps(spec):
                        if self._decref(oid):
                            to_free.append(oid)
                if spec is not None and rec is not None and rec.gc_returns:
                    # returns whose every handle was dropped BEFORE the
                    # task finished have no refcount-zero transition left
                    # to trigger GC — sweep them now (driver-owned refs
                    # only: worker/client return handles are bare)
                    to_free.extend(
                        roid for roid in spec.return_ids
                        if not self._ref_held(roid))
        _SlimFuture.broadcast()  # wake getters once for the whole burst
        self._m_finished.inc(len(simple))
        if trace_spans:
            for name, tid_, tctx, ts in trace_spans:
                emit_lifecycle_spans(name, tid_, tctx, ts)
        if stage_durs:
            self._record_task_latencies(stage_durs)
        if rusage_list:
            self._record_task_resources(rusage_list)
        self.free_objects(to_free)
        if len(self._job_ledgers) > 1:
            # cpu_slots throttle: finished tasks return their slots and
            # pull the next parked spec of their job into the submit queue
            for m, spec in simple:
                if spec is not None:
                    self._release_job_slot(spec, finished=True)
        if nudge:
            self._wakeup()

    def _record_task_latencies(self,
                               durs_list: List[Dict[str, float]]) -> None:
        """Fold finished tasks' stage durations into the bounded
        percentile buffers and the stage histogram (outside the batch
        lock — histogram observes take the instrument lock)."""
        hist = self._m_stage_hist
        lat = self.task_latencies
        for durs in durs_list:
            for stage, d in durs.items():
                buf = lat.get(stage)
                if buf is None:
                    buf = lat[stage] = deque(maxlen=4096)
                buf.append(d)
                hist.observe(d, tags={"stage": stage})

    def _record_task_resources(self,
                               rusage_list: List[Dict[str, float]]) -> None:
        """Fold finished tasks' rusage deltas into bounded per-resource
        percentile buffers (state.summarize_task_latencies resources
        section), the attribution analog of _record_task_latencies."""
        res = self.task_resources
        for ru in rusage_list:
            for key in ("cpu_s", "peak_rss", "hbm_bytes"):
                v = ru.get(key)
                if v is None:
                    continue
                buf = res.get(key)
                if buf is None:
                    buf = res[key] = deque(maxlen=4096)
                buf.append(float(v))

    # --------------------------------------------------------------- actors
    def create_actor(self, payload: dict) -> bytes:
        actor_id = ActorID.from_random()
        # owning job: the job-death sweep kills the job's actors through
        # its ledger (detached actors included — detachment outlives the
        # DRIVER CONNECTION, not the job itself)
        job = payload.get("job_id") or self.job_id.binary()
        led = self.ledger_for(job)
        with led.lock:
            led.actors.add(actor_id.binary())
        if payload.get("cls_blob") is not None:
            self.cls_blobs.setdefault(payload["cls_id"], payload["cls_blob"])
        spec = ActorCreationSpec(
            actor_id=actor_id.binary(),
            name=payload.get("name", "Actor"),
            cls_id=payload["cls_id"],
            args=payload["args"],
            kwargs=payload.get("kwargs", {}),
            resources=payload.get("resources", {}),
            strategy=payload.get("strategy"),
            max_restarts=payload.get("max_restarts", 0),
            max_task_retries=payload.get("max_task_retries", 0),
            max_concurrency=payload.get("max_concurrency", 1),
            placement=payload.get("placement"),
            detached=payload.get("detached", False),
            registered_name=payload.get("registered_name"),
            runtime_env=payload.get("runtime_env"),
        )
        record = ActorRecord(actor_id, spec)
        self.gcs.register_actor(record)
        if spec.detached and spec.registered_name:
            # durable record: a head restarted on the same GCS storage
            # recreates this actor (fresh state, original creation spec —
            # the GCS-FT restart semantics of gcs_actor_manager.h:214)
            persist = dict(payload)
            if persist.get("cls_blob") is None:
                persist["cls_blob"] = self.cls_blobs.get(payload["cls_id"])
            try:
                self.gcs.storage.put("detached_actors",
                                     spec.registered_name,
                                     ser.dumps(persist))
            except Exception:
                pass  # non-picklable args: actor works, just not durable
        info = _ActorInfo(spec, record)
        with self._lock:
            self.actors[spec.actor_id] = info
        self._request_pool.submit(self._start_actor, info)
        return spec.actor_id

    def _recreate_detached_actors(self) -> None:
        """Head-restart path: re-run the creation spec of every persisted
        detached actor found in durable GCS storage."""
        for name, blob in self.gcs.storage.items("detached_actors"):
            if self.gcs.get_named_actor(name) is not None:
                continue
            try:
                payload = ser.loads(blob)
                self.create_actor(payload)
            except Exception:
                self.gcs.storage.delete("detached_actors", name)

    def _start_actor(self, info: _ActorInfo) -> None:
        spec = info.spec
        req = Resources(spec.resources)
        try:
            if spec.placement is not None:
                from .placement_group import resolve_pg_node_for_actor

                node_id = resolve_pg_node_for_actor(self, spec)
            else:
                node_id = None
                deadline = time.monotonic() + self.config.worker_lease_timeout_s
                while node_id is None and time.monotonic() < deadline:
                    node_id = self.scheduler.pick_node(
                        req, spec.strategy, queue_if_busy=False)
                    if node_id is None:
                        time.sleep(0.02)
            if node_id is None:
                raise TimeoutError(
                    f"no resources to place actor {spec.name}"
                )
        except Exception as e:
            self.gcs.set_actor_state(info.record.actor_id, ACTOR_DEAD, str(e))
            info.creation_future.set_exception(ActorDiedError(str(e)))
            self._fail_actor_queue(info, ActorDiedError(str(e)))
            return
        nm = self.nodes[node_id]
        info.node_id = node_id
        chips = None
        n_chips = int(req.get(TPU))
        if n_chips:
            chips = nm.take_chips(n_chips)
        # PG actors: the bundle reservation already deducted node resources
        lease = Resources({}) if spec.placement is not None else req
        msg = {
            "type": "create_actor", "actor_id": spec.actor_id,
            "cls_id": spec.cls_id, "name": spec.name,
            "args": [self._finalize_arg(a) for a in spec.args],
            "kwargs": {k: self._finalize_arg(v)
                       for k, v in spec.kwargs.items()},
            "max_concurrency": spec.max_concurrency,
            # the blob always rides along: this worker is brand new
            "cls_blob": self.cls_blobs[spec.cls_id],
        }
        if spec.runtime_env:
            msg["runtime_env"] = spec.runtime_env
        if chips is not None:
            msg["visible_chips"] = ",".join(str(c) for c in chips)

        def on_handle(h):
            # runs BEFORE the spawn: a bootstrapped fork can reply
            # actor_ready within milliseconds, so every lookup that reply
            # touches (dedication, info.handle, the record) must already
            # be in place
            h.known_classes.add(spec.cls_id)
            nm.dedicate_to_actor(h, spec.actor_id, lease, chips)
            info.handle = h
            info.record.node_id = node_id
            info.record.worker_id = h.worker_id

        # the create message is the spawn's startup token (dedicated
        # worker + assigned task, worker_pool.h:446): the fork path hands
        # it to the child in memory — no registration round trip on the
        # actor-creation critical path. Conda actors cold-spawn under the
        # env's python (dedicated runtime-env worker); local resolution
        # may block this (request-pool) thread like a pip install would.
        conda_spec = (spec.runtime_env or {}).get("conda") \
            if spec.runtime_env else None
        try:
            nm.start_worker(dedicated=True, bootstrap=msg,
                            on_handle=on_handle, conda_spec=conda_spec)
        except Exception as e:  # noqa: BLE001 — conda env unavailable
            self.gcs.set_actor_state(info.record.actor_id, ACTOR_DEAD,
                                     str(e))
            if not info.creation_future.done():
                info.creation_future.set_exception(ActorDiedError(str(e)))
            self._fail_actor_queue(info, ActorDiedError(str(e)))

    def _on_actor_created(self, handle: WorkerHandle, msg: dict) -> None:
        actor_id = msg["actor_id"]
        with self._lock:
            info = self.actors.get(actor_id)
        if info is None:
            return
        if msg["error"] is not None:
            exc = ser.loads(msg["error"])
            self.gcs.set_actor_state(
                info.record.actor_id, ACTOR_DEAD, str(exc)
            )
            if not info.creation_future.done():
                info.creation_future.set_exception(exc)
            self._fail_actor_queue(info, exc)
            return
        self.gcs.set_actor_state(info.record.actor_id, ACTOR_ALIVE)
        if not info.creation_future.done():
            info.creation_future.set_result(True)
        flush = []
        with self._lock:
            while info.pending:
                flush.append(info.pending.popleft())
        for spec in flush:
            self._dispatch_actor_task(info, spec)

    def submit_actor_task(self, payload: dict,
                          adopt_returns: bool = True) -> List[bytes]:
        actor_id = payload["actor_id"]
        with self._lock:
            info = self.actors.get(actor_id)
        if info is None:
            raise ActorDiedError("unknown actor")
        job = payload.get("job_id") or self.job_id.binary()
        led = self.ledger_for(job)
        task_id = TaskID.for_task(
            self.job_id if job == self.job_id.binary() else JobID(job))
        num_returns = payload.get("num_returns", 1)
        return_ids = [
            ObjectID.for_return(task_id, i).binary() for i in range(num_returns)
        ]
        parent_ctx = tracing.from_wire(payload.get("trace_parent")) \
            or tracing.get_current()
        trace_ctx = tracing.child_of(parent_ctx)
        spec = TaskSpec(
            task_id=task_id.binary(),
            name=f"{info.spec.name}.{payload['method']}",
            fn_id=b"",
            args=payload["args"],
            kwargs=payload.get("kwargs", {}),
            num_returns=num_returns,
            return_ids=return_ids,
            resources={},
            actor_id=actor_id,
            method=payload["method"],
            seq=next(info.seq),
            max_retries=info.spec.max_task_retries,
            trace_ctx=trace_ctx,
            job_id=job,
        )
        rec = _TaskRecord(spec, payload, info.spec.max_task_retries,
                          gc_returns=adopt_returns)
        self._m_submitted.inc()
        with led.lock:
            led.tasks_submitted += 1
        with self._lock:
            self.tasks[spec.task_id] = rec
            self._index_trace_locked(trace_ctx, spec.task_id)
            for oid in return_ids:
                self.futures[oid] = _SlimFuture()
                # lineage here serves record GC, not reconstruction —
                # _recover_object refuses actor results explicitly
                self.lineage[oid] = spec.task_id
                if adopt_returns:
                    self._incref(oid)
            for oid in self._ref_deps(spec):
                self._incref(oid)
                self._lineage_dependents[oid] += 1
        state = info.record.state
        if state == ACTOR_DEAD:
            self._fail_task(spec, ActorDiedError(
                info.record.death_cause or "actor is dead"))
        elif state == ACTOR_ALIVE:
            self._dispatch_actor_task(info, spec)
        else:  # pending / restarting: queue in seq order
            with self._lock:
                info.pending.append(spec)
        return return_ids

    def _dispatch_actor_task(self, info: _ActorInfo, spec: TaskSpec) -> None:
        # Dependencies: actor tasks with pending-object args wait like normal
        # tasks, but must preserve seq order; the pipe preserves send order, so
        # we only defer if a dep is truly unready.
        missing = []
        with self._lock:
            for oid in self._ref_deps(spec):
                fut = self.futures.get(oid)
                if fut is not None and not fut.done():
                    missing.append(fut)
        if missing:
            # completion callbacks, NOT parked pool threads: a thread per
            # dep-blocked actor task starved the 8-thread request pool
            # (>8 blocked tasks deadlocked all worker-request service —
            # VERDICT r1 item 9). Only the final send runs on the pool.
            remaining = [len(missing)]
            count_lock = threading.Lock()

            def on_dep_done(_f):
                with count_lock:
                    remaining[0] -= 1
                    if remaining[0]:
                        return
                if self._stop.is_set():
                    return  # shutdown's future fail-pass fired us: do not
                    # resubmit dispatch work into a tearing-down pool
                # dep errors are ignored here on purpose: the send path
                # re-checks availability and runs recovery / fails the task
                try:
                    self._request_pool.submit(
                        self._ensure_actor_args_then_send, info, spec)
                except RuntimeError:
                    pass  # pool already shut down

            for fut in missing:
                fut.add_done_callback(on_dep_done)
            return
        self._ensure_actor_args_then_send(info, spec)

    def _ensure_actor_args_then_send(self, info: _ActorInfo,
                                     spec: TaskSpec) -> None:
        if self._stop.is_set():
            return  # tearing down: no materialize/recovery round trips
        handle = info.handle
        if handle is None or not handle.alive():
            with self._lock:
                info.pending.append(spec)
            return
        node_id = info.node_id
        # device-resident deps block on a worker round-trip the router
        # itself must service, and a store-resident transfer can park in
        # the pressured-push retry loop for the whole retry budget —
        # never do either on the router thread
        with self._lock:
            blocking_dep = any(
                o in self._device_locations
                or (o not in self.memory_store
                    and not self.nodes[node_id].store.contains(o))
                for o in self._ref_deps(spec))
        if blocking_dep and \
                threading.current_thread() is self._router:
            self._request_pool.submit(
                self._ensure_actor_args_then_send, info, spec)
            return
        # transfer any store-resident args to the actor's node
        for oid in self._ref_deps(spec):
            with self._lock:
                in_mem = oid in self.memory_store
            if in_mem:
                continue
            if self.nodes[node_id].store.contains(oid):
                continue
            self._ensure_device_materialized(oid)
            locs = [l for l in self.gcs.get_object_locations(oid)
                    if l != node_id and self.nodes.get(l)
                    and self.nodes[l].alive]
            if locs:
                try:
                    self._transfer_from(oid, locs, node_id)
                except Exception as e:  # noqa: BLE001
                    # same degrade rule as do_transfers: pressure (or a
                    # dying source) must not fail or hang the task while
                    # the object is live — the actor worker's own arg
                    # fetch re-transfers or reads the bytes inline
                    if self._object_alive(oid):
                        events.emit(
                            "TRANSFER_DEGRADED",
                            f"dispatching actor task {spec.name} with "
                            f"arg {oid.hex()[:8]} not prefetched "
                            f"({e!r}); worker will fetch inline",
                            severity=events.WARNING,
                            source="object_manager")
                        continue
                    try:
                        self._recover_object(oid)
                    except Exception as re:  # noqa: BLE001
                        self._fail_task(spec, TaskError(spec.name, re))
                        return
            elif not self.nodes[node_id].store.contains(oid):
                try:
                    self._recover_object(oid)
                except Exception as e:
                    self._fail_task(spec, TaskError(spec.name, e))
                    return
        handle.inflight[spec.task_id] = spec
        if not self._sender_enqueue(handle, self._task_msg(handle, spec)):
            self._on_worker_death(handle)

    def kill_actor(self, actor_id: bytes, no_restart: bool = True) -> None:
        with self._lock:
            info = self.actors.get(actor_id)
        if info is None:
            return
        if no_restart:
            info.spec.max_restarts = 0
        if info.spec.detached and info.spec.registered_name:
            # an explicit kill retires the durable record too
            self.gcs.storage.delete("detached_actors",
                                    info.spec.registered_name)
        self.gcs.set_actor_state(
            info.record.actor_id, ACTOR_DEAD, "killed via kill()"
        )
        self._release_actor_pg(info)
        handle = info.handle
        if handle is not None:
            try:
                handle.proc.terminate()
            except Exception:
                pass
        self._fail_actor_queue(info, ActorDiedError("actor killed"))

    def _fail_actor_queue(self, info: _ActorInfo, exc: Exception) -> None:
        with self._lock:
            pending = list(info.pending)
            info.pending.clear()
        for spec in pending:
            self._fail_task(spec, exc)

    # ------------------------------------------------------- failure handling
    def _on_worker_death(self, handle: WorkerHandle) -> None:
        with self._lock:
            if handle.death_processed:
                return
            if handle.conn is not None and \
                    handle.conn not in self._conn_handles:
                return  # conn already swept by an earlier death event
            handle.death_processed = True
            # a late 'ready' dial-in must not resurrect this handle (the
            # accept loop checks death_processed too, belt-and-braces)
            self._workers_by_id.pop(handle.worker_id.binary(), None)
            dead_conn = handle.conn
            if dead_conn is not None:
                self._conn_handles.pop(dead_conn, None)
                self._conn_send_locks.pop(dead_conn, None)
            inflight = dict(handle.inflight)
            handle.inflight.clear()
            if dead_conn is None:
                pass  # never dialed in: nothing registered anywhere
            elif hasattr(dead_conn, "fileno"):
                # real pipe: the ROUTER must unregister it from the selector
                # before it is closed (a closed fd number can be reused)
                self._router_removals.append(dead_conn)
            else:
                dead_conn.close()  # VirtualConn: never in the selector
        if dead_conn is not None:
            with self._send_cond:
                chan = self._send_channels.pop(dead_conn, None)
            if chan is not None:
                with chan.cond:
                    chan.dead = True
                    chan.q.clear()
                    chan.cond.notify_all()  # retire its sender thread
        if dead_conn is not None and hasattr(dead_conn, "fileno"):
            self._wakeup()
        self._m_worker_exits.inc()  # health plane's worker-churn signal
        nm = self.nodes.get(handle.node_id)
        if nm:
            nm.remove_worker(handle)
            for task_id in inflight:
                # a locally-leased leaf task dies with its worker before
                # finish_task could return the node's lease credit
                nm.release_leaf(task_id)
        self._release_worker_refs(handle)  # borrow pins die with the worker
        self._drop_device_location(handle)
        if handle.actor_id is not None:
            self._on_actor_worker_death(handle, inflight)
        else:
            for task_id, spec in inflight.items():
                self._maybe_retry(task_id, spec, WorkerCrashedError(
                    f"worker {handle.worker_id} died running {spec.name}"
                ))
        if nm and nm.alive:
            self._pump_node(nm)

    def _maybe_retry(self, task_id: bytes, spec: TaskSpec,
                     exc: Exception) -> None:
        with self._lock:
            rec = self.tasks.get(task_id)
            can_retry = rec is not None and rec.retries_left > 0
            if can_retry:
                rec.retries_left -= 1
        if can_retry:
            self._m_retried.inc()
            events.emit("TASK_RETRY",
                        f"retrying {spec.name} after {type(exc).__name__}",
                        severity=events.WARNING, source="core_worker",
                        task_id=task_id.hex())
            self._resolve_deps_then_schedule(spec)
        else:
            self._fail_task(spec, exc)

    def _on_actor_worker_death(self, handle: WorkerHandle,
                               inflight: Dict[bytes, TaskSpec]) -> None:
        with self._lock:
            info = self.actors.get(handle.actor_id)
        if info is None:
            return
        if info.record.state == ACTOR_DEAD:
            for task_id, spec in inflight.items():
                self._fail_task(spec, ActorDiedError(
                    info.record.death_cause or "actor died"))
            return
        restartable = info.record.num_restarts < info.spec.max_restarts \
            or info.spec.max_restarts == -1
        if restartable:
            info.record.num_restarts += 1
            self.gcs.set_actor_state(info.record.actor_id, ACTOR_RESTARTING)
            limit = ("inf" if info.spec.max_restarts == -1
                     else info.spec.max_restarts)
            events.emit(
                "ACTOR_RESTARTING",
                f"actor {info.record.actor_id.hex()[:12]} restart "
                f"{info.record.num_restarts}/{limit}",
                severity=events.WARNING, source="core_worker",
                actor_id=info.record.actor_id.hex())
            # GCS-driven restart (gcs_actor_manager.h:214 RestartActor):
            # re-run the creation task; tasks in flight at the crash retry only
            # under max_task_retries, queued ones wait for ALIVE.
            with self._lock:
                retry = sorted(inflight.values(), key=lambda s: s.seq)
                for spec in retry:
                    rec = self.tasks.get(spec.task_id)
                    if rec and rec.retries_left > 0:
                        rec.retries_left -= 1
                        info.pending.appendleft(spec)
                    else:
                        self._fail_task(spec, ActorDiedError(
                            "actor died while running task (no retries left)"
                        ))
                info.handle = None
            self._request_pool.submit(self._start_actor, info)
        else:
            self.gcs.set_actor_state(
                info.record.actor_id, ACTOR_DEAD, "worker process died"
            )
            self._release_actor_pg(info)
            for task_id, spec in inflight.items():
                self._fail_task(spec, ActorDiedError("actor worker died"))
            self._fail_actor_queue(info, ActorDiedError("actor worker died"))

    def _release_actor_pg(self, info: _ActorInfo) -> None:
        if info.spec.placement is not None and self.pg_manager is not None:
            self.pg_manager.release_key(info.spec.actor_id)

    # ------------------------------------------------------------- job plane
    def ledger_for(self, job_id: Optional[bytes]):
        """Get-or-create the ledger for ``job_id`` (None = the root job).
        A swept (dead) job raises: no new work may charge against it."""
        from .job_plane import JobLedger

        jid = job_id or self.job_id.binary()
        with self._lock:
            if jid in self._swept_jobs:
                raise RmtError(f"job {jid.hex()[:8]} is dead (swept)")
            led = self._job_ledgers.get(jid)
            if led is None:
                led = self._job_ledgers[jid] = JobLedger(jid)
                mdefs.jobs_active().set(float(len(self._job_ledgers)))
            return led

    def set_job_quota(self, job_id: bytes, quota: Optional[dict]) -> None:
        """Install (or replace) a job's admission quota. Applies to new
        admissions only — already-held bytes/slots are never clawed back."""
        from .job_plane import JobQuota

        self.ledger_for(job_id).quota = JobQuota.from_dict(quota)

    def register_client_job(self, job_id: bytes, info: Optional[dict] = None,
                            quota: Optional[dict] = None) -> None:
        """A driver (thin client / job_submission subprocess) joined:
        GCS job row + fresh ledger. Re-registering a swept job id fails."""
        self.gcs.register_job(job_id, info or {})
        led = self.ledger_for(job_id)
        if quota:
            from .job_plane import JobQuota

            led.quota = JobQuota.from_dict(quota)

    def job_usage(self, job_id: Optional[bytes] = None) -> dict:
        """Per-job (or all-jobs) usage snapshot for state/CLI surfaces."""
        with self._lock:
            ledgers = ({job_id: self._job_ledgers[job_id]}
                       if job_id is not None
                       and job_id in self._job_ledgers
                       else dict(self._job_ledgers))
        out = {}
        for jid, led in ledgers.items():
            u = led.usage()
            u["directory_rows"] = self.gcs.count_job_rows(jid)
            out[jid.hex()] = u
        return out

    def _admit_job_bytes(self, job_id: Optional[bytes], oid: bytes,
                         nbytes: int, device: bool = False) -> None:
        """Hard byte-quota admission for a put / device pin. Raises
        QuotaExceededError at the call edge; charges the job's ledger on
        success (released again by free_objects)."""
        if job_id is None:
            return  # untagged put: the root job, unlimited
        led = self.ledger_for(job_id)
        try:
            if device:
                led.admit_device(oid, nbytes)
            else:
                led.admit_object(oid, nbytes)
        except QuotaExceededError:
            self._m_quota_rej.inc(tags={
                "resource": "device_bytes" if device else "object_bytes"})
            raise

    def _note_job_demotion(self, oid: bytes) -> None:
        """Device→host demotion: migrate the bytes from the owning job's
        device_bytes to its object_bytes accounting."""
        jid = self.gcs.object_job(oid)
        if jid is None:
            return
        led = self._job_ledgers.get(jid)  # lock-free dict read
        if led is not None:
            led.note_demoted(oid)

    def _device_victim_rank(self, oid: bytes) -> int:
        """Demotion sort key for the device tier (lower demotes first):
        a client job's pins rank at its quota priority, driver-owned
        pins rank last. Called by the store OUTSIDE its lock."""
        jid = self.gcs.object_job(oid)
        if jid is None or jid == self.job_id.binary():
            return 1 << 30
        led = self._job_ledgers.get(jid)  # lock-free dict read
        return led.quota.priority if led is not None else 1

    def _release_job_bytes(self, oids) -> None:
        """free_objects hook: uncharge freed oids from every ledger."""
        with self._lock:
            ledgers = list(self._job_ledgers.values())
        if len(ledgers) <= 1:
            return  # root job only: unlimited, nothing charged
        for led in ledgers:
            led.release_many(oids)

    def _admit_batch(self, specs: List[TaskSpec]) -> List[TaskSpec]:
        """Router-only: cpu_slots throttle + stride-fair interleave over
        one drained submit batch (see job_plane.fair_order)."""
        from .job_plane import fair_order

        ledgers: Dict[bytes, Any] = {}

        def led_of(spec):
            jid = spec.job_id or self.job_id.binary()
            led = ledgers.get(jid)
            if led is None:
                with self._lock:
                    led = self._job_ledgers.get(jid)
                if led is None:
                    # swept mid-flight: let _schedule fail the task via
                    # the root ledger (unlimited, never parks)
                    led = self._job_ledgers[self.job_id.binary()]
                ledgers[jid] = led
            return led

        admitted = []
        for spec in specs:
            led = led_of(spec)
            if spec.task_id in self._cancelled \
                    or led.try_take_slot(spec.task_id):
                admitted.append(spec)
            else:
                led.park(spec)
        return fair_order(admitted, led_of)

    def _release_job_slot(self, spec: TaskSpec,
                          finished: bool = False) -> None:
        """Terminal-path hook for the cpu_slots throttle: return the
        task's slot and queue its job's next parked spec (if any)."""
        jid = spec.job_id
        if jid is None:
            return
        led = self._job_ledgers.get(jid)  # lock-free dict read
        if led is None:
            return
        if finished:
            with led.lock:
                led.tasks_finished += 1
        nxt = led.release_slot(spec.task_id)
        if nxt is not None:
            with self._lock:
                self._submit_q.append(nxt)
                nudge = not self._submit_nudged
                self._submit_nudged = True
            if nudge:
                self._wakeup()

    def _try_leaf_place_or_preempt(self, spec: TaskSpec) -> bool:
        """Leaf placement with priority preemption: when every lease pool
        is dry and the submitting job outranks a job holding leaf work,
        evict one victim and retry. A queued victim frees its credit
        synchronously; a running victim frees it via worker death, so the
        spec falls back to the shared scheduler this round."""
        if self._try_leaf_place(spec):
            return True
        if len(self._job_ledgers) > 1 and self._preempt_leaf_for(spec):
            return self._try_leaf_place(spec)
        return False

    def _preempt_leaf_for(self, spec: TaskSpec) -> bool:
        """Evict one lower-priority leaf task to make room for ``spec``.
        Returns True when a victim was preempted (its credit freed now or
        freeing via worker death). Preemption rides the existing retry
        machinery: the victim's retry budget is refunded, so preemption
        never consumes a retry the application paid for."""
        my_jid = spec.job_id or self.job_id.binary()
        led = self._job_ledgers.get(my_jid)
        my_pri = led.quota.priority if led is not None else 1
        if my_pri <= 1:
            return False  # baseline priority never preempts
        # snapshot victim priorities OUTSIDE the node locks (victim_ok
        # runs under nm._lock, which must never wait on runtime state)
        prio: Dict[bytes, int] = {}
        with self._lock:
            for tid, rec in self.tasks.items():
                jid = rec.spec.job_id or self.job_id.binary()
                if jid == my_jid:
                    continue
                vled = self._job_ledgers.get(jid)
                prio[tid] = vled.quota.priority if vled is not None else 1

        def victim_ok(tid: bytes) -> bool:
            return prio.get(tid, my_pri) < my_pri

        for nm in list(self.nodes.values()):
            res = nm.preempt_leaf(victim_ok)
            if res is None:
                continue
            kind, payload = res
            self._m_job_preempted.inc()
            if kind == "queued":
                # victim never started: free re-queue through the full
                # scheduling pass (credit already returned by the node)
                vspec = payload
                vled = self._job_ledgers.get(vspec.job_id or b"")
                if vled is not None:
                    with vled.lock:
                        vled.preempted_total += 1
                with self._lock:
                    self._pending_schedule.append(vspec)
                return True
            # running victim: refund the retry this eviction will consume,
            # then kill the worker — _on_worker_death releases the leaf
            # credit and _maybe_retry re-queues the task
            tid, handle = payload
            with self._lock:
                rec = self.tasks.get(tid)
                if rec is not None:
                    rec.retries_left += 1
                    vjid = rec.spec.job_id or self.job_id.binary()
                    vled = self._job_ledgers.get(vjid)
                    if vled is not None:
                        with vled.lock:
                            vled.preempted_total += 1
            try:
                handle.proc.terminate()
            except Exception:
                pass
            return True
        return False

    def sweep_job(self, job_id: bytes, trigger: str = "disconnect") -> bool:
        """Job-death sweep: release EVERYTHING the dead job owns — cancel
        its queued/parked/running tasks, kill its actors, drop its
        refcount rows, free its objects (device tier included, so
        rmt_device_bytes_pinned returns to the pre-job level), then
        retire its ledger. Idempotent: every step tolerates re-running,
        and a step that errors (job.sweep fault site) schedules a retry
        via the heartbeat loop without losing the steps that completed.
        Returns True when every step completed."""
        if job_id == self.job_id.binary():
            return True  # the root job dies with shutdown(), not a sweep
        from ..utils import faults

        t0 = time.monotonic()
        ok = True

        def step(fn):
            nonlocal ok
            try:
                act = faults.fire("job.sweep")
                if act is not None:
                    if act.mode == "stall":
                        act.sleep()
                    else:
                        act.raise_()
                fn()
            except Exception:
                ok = False

        with self._lock:
            # close admission first: ledger_for refuses swept jobs, so a
            # racing submit/put cannot re-charge a job being dismantled
            self._swept_jobs.add(job_id)
            led = self._job_ledgers.get(job_id)

        def mark_dead():
            # clean disconnect finishes the job; a stop request or a
            # watchdog-detected death (SIGKILL, lost notification) fails it
            state = {"disconnect": "FINISHED",
                     "stop": "STOPPED"}.get(trigger, "FAILED")
            self.gcs.set_job_state(job_id, state, f"swept ({trigger})")

        step(mark_dead)

        def cancel_tasks():
            dead = RmtError(f"job {job_id.hex()[:8]} died ({trigger})")
            with self._lock:
                specs = [rec.spec for rec in self.tasks.values()
                         if rec.spec.job_id == job_id
                         and rec.state not in ("FINISHED", "FAILED")]
                for s in specs:
                    self._cancelled.add(s.task_id)
                    self._waiting_deps.pop(s.task_id, None)
            ids = {s.task_id for s in specs}
            if led is not None:
                for s in led.drain_parked():
                    if s.task_id not in ids:
                        ids.add(s.task_id)
                        specs.append(s)
                    with self._lock:
                        self._cancelled.add(s.task_id)
            for nm in list(self.nodes.values()):
                # queued-but-undispatched: drop from the node queue and
                # settle any leaf credit the task held
                with nm._lock:
                    queued = [s for s in nm.queue if s.task_id in ids]
                    for s in queued:
                        try:
                            nm.queue.remove(s)
                        except ValueError:
                            pass
                        if s.task_id in nm.leaf_local:
                            nm.leaf_local.discard(s.task_id)
                            nm.leaf_credits += 1
                for tid in ids:
                    # agent-leased leaf: reclaim credit, and have the
                    # agent kill the pool worker running it (only the
                    # agent knows the placement)
                    if nm.finish_leaf(tid) is not None:
                        nm.cancel_leaf(tid)
                # running: kill the worker; _on_worker_death releases its
                # leases and refs, retry lands in _cancelled and fails
                with nm._lock:
                    victims = [h for h in nm.workers.values()
                               if h.actor_id is None
                               and any(t in ids for t in h.inflight)]
                for h in victims:
                    try:
                        h.proc.terminate()
                    except Exception:
                        pass
            for s in specs:
                self._fail_task(s, dead)

        step(cancel_tasks)

        def kill_actors():
            aids = []
            if led is not None:
                with led.lock:
                    aids = list(led.actors)
            for aid in aids:
                try:
                    self.kill_actor(aid, no_restart=True)
                except Exception:
                    pass

        step(kill_actors)

        def free_owned():
            # the job's objects: everything its ledger charged (puts and
            # device pins) plus every directory row tagged with the job
            # (store-resident returns) plus its tasks' return ids. The
            # sweep walks ONLY rows tagged with this job id — a 4-byte
            # prefix collision with another job can never widen it.
            owned = set(led.owned_object_ids()) if led is not None else set()
            owned.update(self.gcs.job_object_keys(job_id))
            with self._lock:
                for rec in self.tasks.values():
                    if rec.spec.job_id == job_id:
                        owned.update(rec.spec.return_ids)
            if not owned:
                return
            # the dead driver's handles ARE the outstanding refs: drop
            # the rows so free_objects sees refcount zero
            for oid in owned:
                sh = self._ref_stripe(oid)
                with sh.lock:
                    sh.refs.pop(oid, None)
            self.free_objects(list(owned))

        step(free_owned)

        if ok:
            # every step completed: retire the ledger (kept across failed
            # attempts so the retry still has the owned-object manifest)
            if led is not None:
                led.swept = True
            with self._lock:
                self._job_ledgers.pop(job_id, None)
                mdefs.jobs_active().set(float(len(self._job_ledgers)))
            self._m_job_sweeps.inc(tags={"trigger": trigger})
            mdefs.job_sweep_seconds().observe(time.monotonic() - t0)
            with self._lock:
                self._sweep_retry.pop(job_id, None)
        else:
            with self._lock:
                self._sweep_retry[job_id] = (
                    time.monotonic() + self.config.job_sweep_retry_s,
                    trigger)
        return ok

    def _pump_sweep_retries(self) -> None:
        """Heartbeat-loop hook: re-run job sweeps that hit an error
        (sweeps are idempotent, so re-running is always safe)."""
        now = time.monotonic()
        with self._lock:
            due = [(j, trig) for j, (t, trig)
                   in self._sweep_retry.items() if t <= now]
            for j, _ in due:
                del self._sweep_retry[j]
        for j, trig in due:
            self.sweep_job(j, trigger=trig)

    # ------------------------------------------------------------ heartbeats
    def _heartbeat_loop(self) -> None:
        interval = self.config.heartbeat_interval_s
        timeout = interval * self.config.num_heartbeats_timeout
        while not self._stop.is_set():
            with self._lock:
                nodes = list(self.nodes.values())
            for nm in nodes:
                if not nm.alive:
                    continue
                if hasattr(nm, "channel_send"):
                    # remote node: liveness = the agent channel accepting
                    # writes (EOF/half-open shows up here or at the
                    # router). The frame acks the last applied pong seq
                    # so the agent's reply carries only changes since
                    # (delta heartbeats — O(changes) ingress per node)
                    if nm.channel_send(nm.ping_frame()):
                        self.gcs.heartbeat(nm.node_id)
                else:
                    self.gcs.heartbeat(nm.node_id)
                    sweep = getattr(nm.store, "sweep_pins", None)
                    if sweep is not None:
                        try:
                            sweep()  # expire ensure_resident pins
                        except Exception:
                            pass
                    gc = getattr(nm.store, "sweep_unsealed", None)
                    if gc is not None:
                        try:
                            gc()  # abort creates leaked by dead fetchers
                        except Exception:
                            pass
            # reap workers that died WITHOUT ever dialing in (killed by
            # remove_node mid-spawn, import crash, OOM at startup): no
            # pipe means no EOF, so without this sweep their dedicated
            # actors hang at PENDING_CREATION forever and callers ride out
            # their full get() timeout (the node agent runs the same sweep
            # in its _reap_loop; the raylet's starting-worker timeout is
            # the reference analog, worker_pool.h:427)
            for nm in nodes:
                with nm._lock:  # nm.workers is guarded by the NODE's lock
                    unborn = [h for h in nm.workers.values()
                              if h.conn is None and not h.death_processed]
                for h in unborn:
                    if h.proc.poll() is not None:
                        self._on_worker_death(h)
            for node_id in self.gcs.check_heartbeats(timeout):
                self.remove_node(node_id)
            self._pump_sweep_retries()  # re-run job sweeps that errored
            try:
                self._refresh_gauges(nodes)
            except Exception:
                pass  # sampling must never kill the heartbeat loop
            try:
                self._health_tick()
            except Exception:
                pass  # health plane must never kill the heartbeat loop
            if self.gcs.durable:
                # directory shard snapshots ride the heartbeat cadence
                # (~10 ticks): cheap enough to repeat, fresh enough that
                # a restarted head knows what the old process held
                self._hb_ticks += 1
                if self._hb_ticks % 10 == 0:
                    try:
                        self.gcs.snapshot_directory()
                    except Exception:
                        pass  # durability is best-effort off the WAL path
            self._stop.wait(interval)

    def _refresh_gauges(self, nodes: Optional[List[NodeManager]] = None
                        ) -> None:
        """Heartbeat-period sample of cluster-level gauges (the
        reference's periodic stats collection): per-node dispatch-queue
        depth and object-store bytes, pending-dependency count,
        device-store bytes, heartbeat age."""
        if nodes is None:
            with self._lock:
                nodes = list(self.nodes.values())
        self.scheduler.publish_load()
        store_g = mdefs.object_store_bytes()
        hb_g = mdefs.worker_heartbeat_age_seconds()
        now_mono = time.monotonic()
        for nm in nodes:
            if not nm.alive:
                continue
            nid = nm.node_id.hex()[:12]
            stat = getattr(nm, "agent_stat", None)
            if stat:
                # remote node: the delta-heartbeat mirror already holds
                # the agent's store bytes — no channel round trip
                store_g.set(float(stat.get("store_used", 0)),
                            tags={"node_id": nid})
            else:
                store = getattr(nm, "store", None)
                if store is not None and hasattr(store, "usage"):
                    try:
                        used = store.usage()[0]
                        store_g.set(float(used), tags={"node_id": nid})
                    except Exception:
                        pass
            info = self.gcs.nodes.get(nm.node_id)
            if info is not None:
                hb_g.set(max(0.0, now_mono - info.last_heartbeat),
                         tags={"node_id": nid})
        with self._lock:
            pending = len(self._waiting_deps)
        mdefs.scheduler_pending_args().set(float(pending))
        mdefs.device_store_bytes().set(float(self.device_store.total_bytes()))
        dstats = self.gcs.directory_stats()
        mdefs.gcs_directory_hot_rows().set(float(dstats["hot"]))
        mdefs.gcs_directory_cold_rows().set(float(dstats["cold"]))

    def _health_tick(self) -> None:
        """Heartbeat-period health pass: snapshot the merged registry
        into the tsdb rings, then run the SLO rules over the new
        history. Both are no-ops under RMT_HEALTH=0 (the store stays
        empty, so every rule expr evaluates to no-data)."""
        from ..utils import tsdb as _tsdb

        if not _tsdb.is_enabled():
            return
        self.tsdb.sample_registry()
        self.health.evaluate()

    def _health_exemplar(self, rule) -> Optional[dict]:
        """Map a firing rule to a {task_id, trace_id} pivot: the most
        recent FAILED task's trace for failure-shaped rules, else the
        most recent traced task — 'when attributable', so None is a
        valid answer on an idle cluster."""
        want_failed = rule.name in ("task-failure-rate",
                                    "worker-exit-rate")
        best = None  # ((is_failed, ts), task_id, trace_ctx)
        with self._lock:
            for tid, rec in self.tasks.items():
                ctx = rec.spec.trace_ctx
                if not ctx:
                    continue
                ts = max(rec.ts.values()) if rec.ts else 0.0
                score = (rec.state == "FAILED", ts)
                if best is None or score > best[0]:
                    best = (score, tid, ctx)
            # history rows: (tid, name, state, num_returns, retries_left,
            # is_actor, ts_map, trace_ctx, rusage), append-ordered —
            # newest matching row wins
            for row in reversed(self.task_history):
                tid, state, ctx = row[0], row[2], row[7]
                if not ctx or (want_failed and state != "FAILED"):
                    continue
                ts = max(row[6].values()) if row[6] else 0.0
                score = (state == "FAILED", ts)
                if best is None or score > best[0]:
                    best = (score, tid, ctx)
                break
        if best is None or (want_failed and not best[0][0]):
            return None
        return {"task_id": best[1].hex(), "trace_id": best[2][0]}

    # --------------------------------------------------------- device objects
    def put_device_object(self, value: Any,
                          job_id: Optional[bytes] = None) -> bytes:
        """Pin a jax.Array in THIS process's device store (HBM-resident
        ObjectRef — SURVEY.md §7 design; see device_store.py)."""
        from .device_store import is_device_array

        if not is_device_array(value):
            raise TypeError(
                "put(..., device=True) requires a jax.Array; got "
                f"{type(value).__name__}")
        oid = ObjectID.for_put().binary()
        try:
            nbytes = int(value.nbytes)
        except Exception:  # noqa: BLE001
            nbytes = 0
        # quota BEFORE any registration: an over-quota pin must touch
        # nothing (no directory row, no future, no store state)
        self._admit_job_bytes(job_id, oid, nbytes, device=True)
        with self._lock:
            self._device_locations[oid] = "driver"
            fut = _SlimFuture()
            fut.set_result(True)
            self.futures[oid] = fut
        # directory first, then the pin: a put over budget demotes LRU
        # entries synchronously, and a demoted sibling's tier flip must
        # not race this object's own registration
        self.gcs.add_object_location(oid, self.head_node().node_id,
                                     size=nbytes, tier="hbm", job=job_id)
        self.device_store.put(oid, value)
        return oid

    def reserve_device_put(self, handle: WorkerHandle) -> bytes:
        """Worker-side device put, step 1: allocate the id and register
        the owning worker; the seal message completes it."""
        oid = ObjectID.for_put().binary()
        with self._lock:
            self._device_locations[oid] = handle
            self.futures[oid] = _SlimFuture()  # resolved by device_put_sealed
        return oid

    def seal_device_put(self, oid: bytes, handle: Optional[WorkerHandle] = None,
                        size: Optional[int] = None,
                        mesh: Optional[tuple] = None) -> None:
        if handle is not None:
            # the sealed device copy joins the object directory under
            # its hbm tier tag: locality scoring sees the bytes, the
            # transfer plane does not (get_object_locations filters
            # device tiers), and state.list_objects reports the tier
            self.gcs.add_object_location(oid, handle.node_id, size=size,
                                         tier="hbm")
            if mesh is not None:
                # one fingerprint per worker process: the ICI-route
                # decision compares it against the consumer's mesh
                handle.device_mesh = tuple(mesh)
        with self._lock:
            fut = self.futures.get(oid)
        if fut is not None and not fut.done():
            fut.set_result(True)
        self._on_dep_ready(oid)

    def _ensure_device_materialized(self, oid: bytes,
                                    timeout: float = 120.0) -> bool:
        """Make a device-resident object readable through the normal host
        object plane: the owner copies device→host into its node store on
        demand (the spill tier). Returns False if oid is not a device
        object or its owner is gone."""
        with self._lock:
            loc = self._device_locations.get(oid)
        if loc is None:
            return False
        # wait for the seal (producer may still be storing)
        with self._lock:
            seal = self.futures.get(oid)
        if seal is not None:
            seal.result(timeout=timeout)
        if loc == "driver":
            arr = self.device_store.get(oid)
            if arr is None:
                return False
            self._fire_device_materialize()
            nm = self.head_node()
            if not nm.store.contains(oid):
                try:
                    nm.store.put_serialized(oid, ser.serialize(arr))
                except ValueError:
                    pass  # concurrent reader materialized it first
                self.gcs.add_object_location(oid, nm.node_id)
            return True
        # worker-owned: one materialize request, shared by all waiters
        if not loc.alive():
            return False
        if self.gcs.get_object_locations(oid):
            return True  # already materialized earlier
        if self._device_route(loc) == "ici":
            # producer shares this consumer's mesh: the object could ride
            # a device-to-device collective instead of the host wire.
            # Cross-process collectives need a cooperative mesh runtime
            # on both sides (jax.distributed), which the in-process
            # transfer plane cannot drive yet — fall through to host
            # materialization, loudly, so the decision point is
            # exercised end-to-end today and becomes a fast path when
            # the collective lands.
            events.emit(
                "DEVICE_ICI_FALLBACK",
                f"same-mesh device object {oid.hex()[:12]} moved over "
                "the host path (no cooperative collective runtime)",
                source="runtime")
        with self._lock:
            fut = self._materialize_futs.get(oid)
            if fut is None:
                fut = _SlimFuture()
                self._materialize_futs[oid] = fut
                send_needed = True
            else:
                send_needed = False
        if send_needed:
            if not self._send(loc, {"type": "materialize_device",
                                    "object_id": oid}):
                with self._lock:
                    self._materialize_futs.pop(oid, None)
                return False
        try:
            fut.result(timeout=timeout)
        except Exception:
            return False
        return True

    def _on_device_materialized(self, handle: WorkerHandle,
                                msg: dict) -> None:
        oid = msg["object_id"]
        if msg.get("error") is None:
            self.gcs.add_object_location(oid, handle.node_id)
        with self._lock:
            fut = self._materialize_futs.pop(oid, None)
        if fut is not None and not fut.done():
            if msg.get("error") is not None:
                fut.set_exception(ser.loads(msg["error"]))
            else:
                fut.set_result(True)

    def _on_device_demoted(self, handle: WorkerHandle, msg: dict) -> None:
        """One-way notice that a worker's device tier demoted an object
        to its node shm store under budget pressure. The directory tier
        flips to shm (host-readable again) and the head stops routing
        device reads at the worker — the normal shm/transfer plane now
        owns the object."""
        oid = msg["object_id"]
        self.gcs.add_object_location(
            oid, handle.node_id, size=msg.get("size"))
        with self._lock:
            if self._device_locations.get(oid) is handle:
                del self._device_locations[oid]
            self._demoted_device.add(oid)
        self._note_job_demotion(oid)  # device quota bytes -> object bytes

    def _on_device_consumed(self, handle: WorkerHandle, msg: dict) -> None:
        """A worker took a device entry for donation (consume=True):
        no copy survives there, so drop the routing and the hbm tag.
        Later gets fall through to any host copy, else lineage."""
        oid = msg["object_id"]
        with self._lock:
            if self._device_locations.get(oid) is handle:
                del self._device_locations[oid]
            self._demoted_device.discard(oid)
        self.gcs.remove_device_location(oid, handle.node_id)

    def _drop_device_location(self, handle: WorkerHandle) -> None:
        """Owner process died: its device objects are gone; gets fall
        through to lineage recovery."""
        with self._lock:
            dead = [oid for oid, loc in self._device_locations.items()
                    if loc is handle]
            for oid in dead:
                del self._device_locations[oid]
                fut = self._materialize_futs.pop(oid, None)
                if fut is not None and not fut.done():
                    fut.set_exception(ObjectLostError(
                        oid.hex(), "device-object owner process died"))
        for oid in dead:
            # drop the directory's hbm tag for the dead process; a host
            # copy materialized earlier (tier flipped to shm) survives
            self.gcs.remove_device_location(oid, handle.node_id)

    @staticmethod
    def _fire_device_materialize() -> None:
        """Injectable fault site on every device<->host movement
        (on-demand materialization and host->device re-promotion)."""
        from ..utils import faults

        act = faults.fire("device.materialize")
        if act is not None:
            if act.mode == "stall":
                act.sleep()
            elif act.mode in ("error", "drop"):
                act.raise_()

    def _device_route(self, loc) -> str:
        """Transfer route for a device object owned by ``loc``:
        'local' (same process — zero-copy / donation), 'ici' (owner
        shares this process's mesh — device-to-device move), or 'host'
        (materialize + v2 striped wire). Decided from the mesh
        fingerprint the owner registered at seal time."""
        if loc == "driver":
            return "local"
        if not self.config.device_ici_transfer:
            return "host"
        from . import transfer as xfer

        if xfer.same_mesh(getattr(loc, "device_mesh", None),
                          xfer.mesh_fingerprint()):
            return "ici"
        return "host"

    def _demote_device_object(self, oid: bytes, arr: Any) -> bool:
        """Device→host demotion (the device store's LRU eviction
        callback): write the serialized value — bf16-downcast when
        configured — through the head node store's create/seal path and
        flip the directory tier to shm; the spill plane takes over below
        shm. Returns False (object stays device-resident) on any IO
        failure."""
        data = ser.serialize_device_demotion(
            arr, self.config.device_demote_precision)
        nm = self.head_node()
        if not nm.store.contains(oid):
            try:
                nm.store.put_serialized(oid, data)
            except ValueError:
                pass  # concurrent reader materialized it first
        self.gcs.add_object_location(oid, nm.node_id,
                                     size=data.total_size)
        with self._lock:
            self._device_locations.pop(oid, None)
            self._demoted_device.add(oid)
        # demoted bytes stop counting against the owner's device quota
        self._note_job_demotion(oid)
        return True

    def _maybe_promote_device(self, oid: bytes, value: Any):
        """Re-promotion on device read: a get() that found host bytes
        for a previously demoted device object re-pins the rehydrated
        array so the NEXT consumer is zero-copy again (LRU re-entry —
        pressure can demote it right back)."""
        with self._lock:
            if oid not in self._demoted_device:
                return value
        if not self.config.device_promote_on_read:
            return value
        from .device_store import is_device_array

        if not is_device_array(value):
            return value
        try:
            self._fire_device_materialize()
        except Exception:  # noqa: BLE001 — injected: skip the promotion
            return value
        with self._lock:
            self._demoted_device.discard(oid)
            self._device_locations[oid] = "driver"
        # the host copy stays resident (and keeps its shm tier tag —
        # flipping it to hbm would hide it from host readers); the
        # re-pinned array just makes the next local read zero-copy
        self.device_store.put(oid, value)
        return value

    def _forget_device_object(self, oid: bytes) -> None:
        """A consume=True get took the pinned buffer for donation: the
        device copy no longer exists anywhere the runtime can hand out."""
        with self._lock:
            self._device_locations.pop(oid, None)
            self._demoted_device.discard(oid)
        self.gcs.remove_device_location(oid, self.head_node().node_id)

    def move_device_object(self, oid: bytes, device) -> bool:
        """Driver-side ICI move: relocate a driver-pinned device object
        onto ``device`` with the jitted device-to-device transfer (the
        same-mesh fast path the bench headlines). Zero-copy readers keep
        working against the moved buffer. False if the object is not
        pinned in this process."""
        arr = self.device_store.get(oid)
        if arr is None:
            return False
        from . import transfer as xfer

        moved = xfer.ici_move(arr, device)
        self.device_store.put(oid, moved)
        return True

    # ------------------------------------------------------------ object api
    def put_object(self, value: Any,
                   job_id: Optional[bytes] = None) -> bytes:
        data = ser.serialize(value)
        oid = ObjectID.for_put().binary()
        # quota first: an over-quota put touches neither store nor WAL
        self._admit_job_bytes(job_id, oid, data.total_size)
        if data.total_size <= self.config.max_direct_call_object_size:
            payload = data.to_bytes()
            with self._lock:
                self.memory_store[oid] = payload
            if self._wal_enabled and len(payload) <= self._wal_max:
                # sealed the moment put() returns: WAL before the caller
                # can observe the id (head-restart durability)
                self.gcs.wal_put_sealed(oid, payload)
        else:
            # release deferred dead objects BEFORE allocating: resident
            # corpses slow the store allocator (free-list walks, eviction
            # pressure) — measured 5x on the 16MB bulk-put path. Only
            # the STORE branch pays this; inline puts never touch the
            # allocator (the pump loop flushes stragglers for them)
            self._flush_deferred_frees()
            nm = self.head_node()
            nm.store.put_serialized(oid, data)
            self.gcs.add_object_location(oid, nm.node_id,
                                         size=data.total_size, job=job_id)
        with self._lock:
            fut = _SlimFuture()
            fut.set_result(True)
            self.futures[oid] = fut
        return oid

    # ------------------------------------------------------------- promises
    def create_promise(self) -> bytes:
        """Pre-allocate an object id whose value an EXTERNAL executor
        delivers later (the cross-language task plane: C++ executors
        return results for ids minted before dispatch). Gets on the id
        park on the unresolved future exactly like a task return; no
        lineage — a lost promise is failed by its broker, not recovered."""
        oid = ObjectID.for_put().binary()
        with self._lock:
            self.futures[oid] = _SlimFuture()
            self._promises.add(oid)
        return oid

    def resolve_promise(self, oid: bytes, value: Any = None,
                        error: Optional[Exception] = None) -> None:
        """Deliver (or fail) a promise created by :meth:`create_promise`."""
        with self._lock:
            if oid not in self._promises:
                return  # promise freed (caller gone): drop the late result
        if error is None:
            data = ser.serialize(value)
            if data.total_size <= self.config.max_direct_call_object_size:
                payload = data.to_bytes()
                with self._lock:
                    self.memory_store[oid] = payload
                if self._wal_enabled and len(payload) <= self._wal_max:
                    # WAL before the future resolves (see put_object)
                    self.gcs.wal_put_sealed(oid, payload)
            else:
                self._flush_deferred_frees()  # see put_object
                nm = self.head_node()
                nm.store.put_serialized(oid, data)
                self.gcs.add_object_location(oid, nm.node_id,
                                             size=data.total_size)
        with self._lock:
            fut = self.futures.get(oid)
            if fut is None:
                fut = self.futures[oid] = _SlimFuture()
        if fut.done():
            return  # double resolve: first delivery wins
        if error is None:
            fut.set_result(True)
        else:
            fut.set_exception(error)

    def put_serialized_arg(self, data: ser.SerializedObject) -> bytes:
        """Promote an oversized call argument to a store object (the
        plasma-promotion path of serialization.py:411 in the reference)."""
        self._flush_deferred_frees()  # see put_object
        oid = ObjectID.for_put().binary()
        nm = self.head_node()
        nm.store.put_serialized(oid, data)
        self.gcs.add_object_location(oid, nm.node_id,
                                     size=data.total_size)
        with self._lock:
            fut = _SlimFuture()
            fut.set_result(True)
            self.futures[oid] = fut
        return oid

    def cancel_task(self, oid: bytes, force: bool = False) -> None:
        self.cancel(oid, force)

    def get_objects(self, oids: List[bytes],
                    timeout: Optional[float] = None,
                    consume: bool = False) -> List[Any]:
        deadline = None if timeout is None else time.monotonic() + timeout
        out: Dict[bytes, Any] = {}
        for oid in dict.fromkeys(oids):
            out[oid] = self._get_one(oid, deadline, consume=consume)
        results = []
        for oid in oids:
            v = out[oid]
            if isinstance(v, Exception):
                raise v
            results.append(v)
        return results

    def _get_one(self, oid: bytes, deadline: Optional[float],
                 consume: bool = False):
        # driver-pinned device object: zero-copy return of the live
        # array. consume=True is the last-reader donation path — the
        # store drops its pin and the directory forgets the device copy
        # so the caller can donate the buffer into its pjit computation
        # (a later get of the ref is an object-lost error, by contract).
        if consume:
            arr = self.device_store.take(oid)
            if arr is not None:
                self._forget_device_object(oid)
                return arr
        arr = self.device_store.get(oid)
        if arr is not None:
            return arr
        for attempt in range(3):
            with self._lock:
                fut = self.futures.get(oid)
            if fut is not None:
                remaining = None if deadline is None else max(
                    0.0, deadline - time.monotonic())
                try:
                    fut.result(timeout=remaining)
                # _CFTimeoutError is NOT the builtin TimeoutError until
                # Python 3.11 — catch both so 3.10 converts too
                except (TimeoutError, _CFTimeoutError):
                    raise GetTimeoutError(
                        f"get() timed out waiting for {oid.hex()}"
                    )
                except Exception as e:
                    return e
            with self._lock:
                data = self.memory_store.get(oid)
            if data is not None:
                return ser.loads(data)
            value, found = self._read_from_stores(oid)
            if found:
                return self._maybe_promote_device(oid, value)
            # device-resident elsewhere: materialize device→host, re-read
            if self._ensure_device_materialized(oid):
                value, found = self._read_from_stores(oid)
                if found:
                    return value
            # Not in memory, not in any store: lost. Try lineage recovery
            # (ObjectRecoveryManager, object_recovery_manager.h:41).
            try:
                self._recover_object(oid)
            except ObjectLostError as e:
                return e
        return ObjectLostError(oid.hex(), "recovery retries exhausted")

    def _read_from_stores(self, oid: bytes) -> Tuple[Any, bool]:
        from .remote_node import RemoteNodeManager

        locs = self.gcs.get_object_locations(oid)
        # "local" = readable through a direct shm mapping: head-local
        # nodes AND same-host agents (their segment is just another named
        # mapping — reading it is zero-copy, no localization needed)
        local = [l for l in locs
                 if not isinstance(self.nodes.get(l), RemoteNodeManager)
                 or self._same_host_store(self.nodes[l]) is not None]
        remote = [l for l in locs if l not in set(local)]
        # truly-remote-only objects: localize into the head store over the
        # p2p plane first — a driver get used to buffer the WHOLE object
        # in head RAM (b"".join of pulled chunks); fetching into the store
        # keeps it O(chunk), zero-copy on read, spill-managed, and cached
        # for the next get
        for node_id in remote if not local else ():
            nm = self.nodes.get(node_id)
            if nm is None or not nm.alive:
                continue
            addr = getattr(nm, "transfer_addr", None)
            if addr is None:
                continue
            from .transfer import fetch_object

            head = self.head_node()
            err = fetch_object(
                addr[0], addr[1], self._authkey, oid, head.store,
                self.config.object_manager_chunk_size,
                pool=self._xfer_conn_pool,
                stripe_threshold=self.config.transfer_stripe_threshold,
                stripe_count=self.config.transfer_stripe_count,
                alt_sources=lambda: self._holder_addrs(oid),
                retry=self._fetch_policy(),
                verify_checksum=self.config.transfer_verify_checksum,
                stripe_deadline=self.config.transfer_stripe_deadline_s,
                codecs=wire_codec.client_codecs(self.config))
            if err is None:
                self.gcs.add_object_location(oid, head.node_id)
                local = [head.node_id]
                break
            self._prune_stale_location(oid, node_id, err)
        for node_id in local + remote:
            nm = self.nodes.get(node_id)
            if nm is None or not nm.alive:
                continue
            cli = self._store_client_for(node_id)
            view = cli.get(oid)
            if view is None and cli is not getattr(nm, "store", None):
                # a same-host mapping of an agent's store cannot see
                # objects SPILLED inside that agent — the channel proxy
                # can (its read serves the spill file)
                proxy = getattr(nm, "store", None)
                if proxy is not None:
                    view = proxy.get(oid)
                    cli = proxy
            if view is None:
                continue
            # the store refcount taken by get() is held until the last
            # zero-copy view of the value dies (plasma buffer semantics)
            value = ser.deserialize(
                view, on_release=lambda c=cli, o=oid: c.release(o)
            )
            return value, True
        return None, False

    def _recover_object(self, oid: bytes) -> None:
        with self._lock:
            task_id = self.lineage.get(oid)
            rec = self.tasks.get(task_id) if task_id else None
        if rec is None:
            raise ObjectLostError(oid.hex(), "no lineage recorded")
        if rec.spec.is_actor_task:
            # re-running an actor method against mutated actor state is
            # not reconstruction (the reference likewise only rebuilds
            # task lineage; actor results need max_task_retries)
            raise ObjectLostError(
                oid.hex(), "actor task result is not reconstructable")
        spec = rec.spec
        with self._lock:
            # reset return futures so dependents re-wait
            for roid in spec.return_ids:
                fut = self.futures.get(roid)
                if fut is None or fut.done():
                    self.futures[roid] = _SlimFuture()
            rec.state = "RESUBMITTED"
            # re-acquire the arg pins the first completion released: the
            # re-execution (and the completion sweep that follows it)
            # must see the args — and its own result — as referenced
            if rec.args_released:
                rec.args_released = False
                for aoid in self._ref_deps(spec):
                    self._incref(aoid)
        self._resolve_deps_then_schedule(spec)
        for roid in spec.return_ids:
            with self._lock:
                fut = self.futures[roid]
            fut.result(timeout=self.config.worker_lease_timeout_s * 4)

    def wait(self, oids: List[bytes], num_returns: int,
             timeout: Optional[float], fetch_local: bool = True):
        """Event-driven wait: park on the objects' completion futures
        (FIRST_COMPLETED) instead of polling — the 1 ms busy-poll burned a
        core-share and added latency at scale (the reference's WaitManager
        is likewise callback-driven, wait_manager.h). Handles a mix of
        _SlimFuture (every completion broadcasts the shared condition) and
        stdlib Future (placement-group readiness) by parking on the shared
        condition with a short cap whenever a stdlib future is present."""

        def futures_wait(futs, timeout):
            """Returns (done, not_done); empty done ONLY after the full
            timeout elapsed (callers treat that as a timeout)."""
            futs = set(futs)
            end = None if timeout is None else time.monotonic() + timeout
            while True:
                done = {f for f in futs if f.done()}
                if done:
                    return done, futs - done
                left = None if end is None else end - time.monotonic()
                if left is not None and left <= 0:
                    return done, futs
                # stdlib futures (PG readiness) don't signal the shared
                # condition — cap the park so they are re-polled
                if any(not isinstance(f, _SlimFuture) for f in futs):
                    left = 0.02 if left is None else min(left, 0.02)
                with _SlimFuture._cond:
                    _SlimFuture._cond.wait_for(
                        lambda: any(f.done() for f in futs), left)

        deadline = None if timeout is None else time.monotonic() + timeout
        ready: List[bytes] = []
        pending: List[Tuple[bytes, Optional[Future]]] = []
        with self._lock:
            for oid in oids:
                fut = self.futures.get(oid)
                if (oid in self.memory_store
                        or (fut is not None and fut.done())
                        or (fut is None
                            and self.gcs.get_object_locations(oid))):
                    ready.append(oid)
                else:
                    pending.append((oid, fut))
        while len(ready) < num_returns and pending:
            remaining = None if deadline is None else max(
                0.0, deadline - time.monotonic())
            futs = {f for _, f in pending if f is not None}
            untracked = len(futs) < len(pending)
            if futs:
                # untracked ids (no owner future) surface only via GCS
                # location updates the futures can't signal — cap the park
                # so they are re-polled even while futures stay pending
                park = remaining
                if untracked:
                    park = 0.05 if remaining is None else min(remaining,
                                                              0.05)
                done, _ = futures_wait(futs, timeout=park)
                if not done and not untracked:
                    break  # timed out
            else:
                if remaining == 0.0:
                    break
                time.sleep(min(0.05, remaining or 0.05))
            still = []
            for oid, fut in pending:
                if (fut is not None and fut.done()) or (
                        fut is None and self.gcs.get_object_locations(oid)):
                    ready.append(oid)
                else:
                    still.append((oid, fut))
            pending = still
            if deadline is not None and time.monotonic() >= deadline:
                break
        return (ready[:num_returns] + ready[num_returns:],
                [oid for oid, _ in pending])

    def future_for(self, ref: ObjectRef) -> Future:
        with self._lock:
            fut = self.futures.get(ref.binary())
            if fut is None:
                fut = _SlimFuture()
                if ref.binary() in self.memory_store or \
                        self.gcs.get_object_locations(ref.binary()):
                    fut.set_result(True)
                self.futures[ref.binary()] = fut
            return fut

    # ------------------------------------------- decentralized ownership
    def _on_owned_put(self, handle: WorkerHandle, msg: dict) -> None:
        """Register a worker-owned put (the worker minted the id and
        wrote its node store itself — creator-owns,
        reference_count.h:39). The head records the location and the
        ownership attribution; the value is freed only by the owner's
        release (guarded against live driver pins)."""
        oid = msg["object_id"]
        self.gcs.add_object_location(oid, handle.node_id,
                                     size=msg.get("size"))
        with self._lock:
            if msg.get("own", True):
                self._worker_owned.setdefault(
                    handle.worker_id.binary(), set()).add(oid)
            fut = self.futures.get(oid)
            if fut is None:
                self.futures[oid] = fut = _SlimFuture()
        if not fut.done():
            fut.set_result(True)
        self._on_dep_ready(oid)

    def _apply_worker_ref_tables(self, handle: WorkerHandle,
                                 borrows, releases, owned_drops) -> None:
        """The borrowed-ref table riding a done reply
        (reference_count.h:139-156): ``borrows`` are refs the worker
        still holds past the task — each takes a head-side pin
        attributed to the worker, outliving the task-duration arg pin;
        ``releases`` are zero-count transitions worker-side — borrow
        pins drop, and NEVER-ESCAPED owned puts (no other process can
        hold the id) free outright; ``owned_drops`` are escaped owned
        ids whose owner dropped its last ref — attribution only, the
        value stays for whoever the id escaped to (bare driver refs are
        invisible to refcounting by design)."""
        wid = handle.worker_id.binary()
        freed: List[bytes] = []
        with self._lock:
            wb = self._worker_borrows.setdefault(wid, set())
            wo = self._worker_owned.get(wid, set())
            # releases BEFORE borrows: one reply can carry both a
            # release and a re-borrow of the same oid (dropped then
            # re-acquired between two completions) — borrow-first would
            # skip the increment ("already borrowed") and the release
            # would then drop the pin while the worker still holds it
            # (wb/wo stay under _lock; the counts take one ref stripe
            # at a time — leaf locks, never two at once)
            for oid in releases or ():
                if oid in wb:
                    wb.discard(oid)
                    self._decref_defer(oid)
                elif oid in wo:
                    wo.discard(oid)
                    if not self._ref_held(oid):
                        # never escaped + owner dropped it + no other
                        # pin: the owned value can go
                        freed.append(oid)
            for oid in owned_drops or ():
                wo.discard(oid)
            for oid in borrows or ():
                if oid not in wb:
                    wb.add(oid)
                    self._incref(oid)
        if freed:
            self.free_objects(freed)

    def _release_worker_refs(self, handle: WorkerHandle) -> None:
        """Worker died: its borrow pins release (the borrower is gone);
        its owned puts keep their values (a driver may hold bare refs —
        owner-death object loss stays out of scope) but lose
        attribution."""
        wid = handle.worker_id.binary()
        with self._lock:
            borrows = self._worker_borrows.pop(wid, None)
            self._worker_owned.pop(wid, None)
            if borrows:
                for oid in borrows:
                    self._decref_defer(oid)

    # ----------------------------------------------------- reference counting
    def _ref_stripe(self, oid: bytes) -> _RefShard:
        return self._ref_shards[hash(oid) % self._ref_shard_n]

    def _ref_stripes_for(self, oids) -> List[_RefShard]:
        """Distinct stripes for a batch of oids, in ascending index
        order — the ONLY sanctioned multi-stripe hold (see __init__)."""
        idxs = sorted({hash(oid) % self._ref_shard_n for oid in oids})
        return [self._ref_shards[i] for i in idxs]

    def _incref(self, oid: bytes) -> None:
        sh = self._ref_stripe(oid)
        with sh.lock:
            sh.refs[oid] += 1

    def _decref(self, oid: bytes) -> bool:
        """Drop one count; True on the zero transition (entry removed,
        NOT deferred — the caller frees synchronously)."""
        sh = self._ref_stripe(oid)
        with sh.lock:
            sh.refs[oid] -= 1
            if sh.refs[oid] > 0:
                return False
            del sh.refs[oid]
            return True

    def _decref_defer(self, oid: bytes) -> int:
        """Drop one count; on the zero transition move the oid into its
        stripe's deferred-free buffer. Returns that buffer's new length
        (0 when the count stayed positive)."""
        sh = self._ref_stripe(oid)
        with sh.lock:
            sh.refs[oid] -= 1
            if sh.refs[oid] > 0:
                return 0
            del sh.refs[oid]
            sh.frees.append(oid)
            return len(sh.frees)

    def _ref_held(self, oid: bytes) -> bool:
        sh = self._ref_stripe(oid)
        with sh.lock:
            return oid in sh.refs

    @property
    def local_refs(self) -> Dict[bytes, int]:
        """Merged snapshot of every stripe's counts (tests/state API —
        NOT the hot path; internal code reads per-stripe)."""
        merged: Dict[bytes, int] = {}
        for sh in self._ref_shards:
            with sh.lock:
                merged.update(sh.refs)
        return merged

    @property
    def _deferred_frees(self) -> List[bytes]:
        """Merged snapshot of every stripe's free buffer (tests only)."""
        out: List[bytes] = []
        for sh in self._ref_shards:
            with sh.lock:
                out.extend(sh.frees)
        return out

    def add_local_ref(self, oid: bytes) -> None:
        self._incref(oid)

    def remove_local_ref(self, oid: bytes) -> None:
        # zero-ref frees batch through per-stripe deferred buffers the
        # ROUTER pump drains: a driver dropping a list of refs (every
        # `del refs` after a bulk get) fires thousands of __del__s
        # back-to-back on the application thread, and the free pass
        # (store deletes + task-record prune cascades) was ~60% of that
        # thread's time in the task hot path. Here we only decrement and
        # buffer; crossing the per-stripe batch threshold nudges the
        # router, which frees between dispatch rounds
        # (_flush_deferred_frees in _pump).
        n = self._decref_defer(oid)
        if n == 0:
            return
        # wake immediately for a DEVICE object (its HBM stays pinned
        # until the flush — latency there is device memory held
        # hostage) and at the per-stripe batch threshold; host-object
        # frees keep the lazy window and drain on the router's next
        # natural wakeup. The _device_locations probe is a lock-free
        # dict read; a stale answer only costs one spurious or
        # slightly-late wakeup.
        if oid in self._device_locations or n >= 16:
            self._wakeup()

    def _take_deferred_frees(self) -> List[bytes]:
        """Drain every stripe's deferral buffer, SKIPPING any oid that
        picked up a live reference since its count hit zero (e.g. a
        cached ref handed out again, a borrowed bare-id re-pinned at
        submission) — freeing those would drop a value a live handle
        still expects. The synchronous pre-batching free could never see
        this because it ran at the zero transition itself. One stripe
        lock at a time; the unlocked emptiness peek is racy but safe
        (a straggler drains on the next flush)."""
        batch: List[bytes] = []
        for sh in self._ref_shards:
            if not sh.frees:
                continue
            with sh.lock:
                batch.extend(oid for oid in sh.frees
                             if oid not in sh.refs)
                sh.frees = []
        return batch

    def _flush_deferred_frees(self) -> None:
        batch = self._take_deferred_frees()
        if batch:
            self.free_objects(batch)

    def _try_prune_record_locked(self, task_id: bytes) -> None:  # rmtcheck: holds=_lock
        """With self._lock held: prune a terminal task's record, futures,
        and lineage edges once nothing can need them again — no live
        handle on any return, no settled-future waiter, and no RETAINED
        downstream record that could demand transitive reconstruction
        (lineage pinning, reference_count.h). Pruning a record releases
        its lineage pins on its OWN args, which can cascade upstream.
        Without this GC the head retains O(all tasks ever) records
        (many_actors.json records head peak memory for this reason)."""
        stack = [task_id]
        while stack:
            tid = stack.pop()
            rec = self.tasks.get(tid)
            if (rec is None or not rec.gc_returns
                    or rec.state not in ("FINISHED", "FAILED")
                    or not rec.args_released):
                continue
            rets = rec.spec.return_ids
            # the returns' stripe locks span the handle check AND the
            # pops: an app-thread add_local_ref (a cached ref handed out
            # again) must not land between "no handle lives" and the
            # future/value drop. Acquired in ascending index order —
            # this path is serialized by _lock, and single-stripe
            # holders never wait on a second lock, so no cycle.
            stripes = self._ref_stripes_for(rets)
            for sh in stripes:
                sh.lock.acquire()
            try:
                if any(r in self._ref_stripe(r).refs for r in rets):
                    continue  # a handle (or a task's arg pin) lives
                if any(self._lineage_dependents.get(r, 0) > 0
                       for r in rets):
                    continue  # a retained downstream record remains
                if any(r in self.futures and not self.futures[r].done()
                       for r in rets):
                    continue  # an unresolved future may have waiters
                for r in rets:
                    self.futures.pop(r, None)
                    self.lineage.pop(r, None)
                    self.memory_store.pop(r, None)
                # raw tuple: this runs once per completed task, and
                # building a keyed dict (plus .hex()) here showed in the
                # completion hot path — the state API renders rows
                # lazily on read
                self.task_history.append(
                    (tid, rec.spec.name, rec.state, rec.spec.num_returns,
                     rec.retries_left, rec.spec.is_actor_task, rec.ts,
                     rec.spec.trace_ctx, rec.rusage))
                del self.tasks[tid]
                for a in self._ref_deps(rec.spec):
                    n = self._lineage_dependents.get(a, 0) - 1
                    if n > 0:
                        self._lineage_dependents[a] = n
                    else:
                        self._lineage_dependents.pop(a, None)
                        # the arg's producer may have been waiting on
                        # us. The arg's stripe may not be held here, so
                        # this is a bare dict read: racy, and only a
                        # cascade OPPORTUNITY is at stake — a pin that
                        # lands concurrently re-checks at the top of the
                        # next iteration under the stripes' locks.
                        ptid = self.lineage.get(a)
                        if ptid is not None \
                                and a not in self._ref_stripe(a).refs:
                            stack.append(ptid)
            finally:
                for sh in stripes:
                    sh.lock.release()

    def free_object(self, oid: bytes) -> None:
        self.free_objects((oid,))

    def free_objects(self, oids) -> None:
        """Drop objects' values everywhere (ray.internal.free analog),
        then try to prune the producing tasks' metadata (see
        _try_prune_record_locked). Batched: completion bursts free many
        zero-ref returns at once, and per-object lock acquisition was a
        measurable slice of the task hot path."""
        if not oids:
            return
        device_local: List[bytes] = []
        device_remote: List[tuple] = []
        with self._lock:
            for oid in oids:
                loc = self._device_locations.pop(oid, None)
                self._demoted_device.discard(oid)
                self.memory_store.pop(oid, None)  # value is dead either way
                task_id = self.lineage.get(oid)
                if task_id is not None:
                    self._try_prune_record_locked(task_id)
                elif oid in self._promises:
                    # freed promise: the caller is gone, so purge even a
                    # PENDING future — a late external resolution must
                    # find nothing and drop its result (resolve_promise
                    # checks _promises), not store an ownerless object
                    self._promises.discard(oid)
                    self.futures.pop(oid, None)
                else:
                    # a put object: no lineage, just the settled future
                    fut = self.futures.get(oid)
                    if fut is not None and fut.done():
                        self.futures.pop(oid, None)
                if loc == "driver":
                    device_local.append(oid)
                elif loc is not None:
                    device_remote.append((loc, oid))
        for oid in device_local:
            self.device_store.delete(oid)
        for loc, oid in device_remote:
            self._send(loc, {"type": "free_device", "object_id": oid})
        # one batched directory pop for the whole burst; inline-return
        # oids (no store copy anywhere) cost nothing here
        for oid, locs in self.gcs.take_objects_locations(oids).items():
            for node_id in locs:
                nm = self.nodes.get(node_id)
                if nm and nm.alive:
                    nm.store.delete(oid)
        if self._wal_enabled:
            # freed oids leave the sealed WAL too, or a restart would
            # resurrect values every live handle already dropped
            self.gcs.wal_del_sealed(oids)
        # job plane: uncharge freed bytes from their owners' quotas
        self._release_job_bytes(oids)

    # ------------------------------------------------------ worker requests
    def _serve_worker_request(self, handle: WorkerHandle, msg: dict) -> None:
        req_id = msg.get("req_id")
        reply: dict = {"type": "reply", "req_id": req_id, "error": None}
        try:
            mtype = msg["type"]
            if mtype == "submit_task":
                reply["return_ids"] = self.submit_task(
                    msg["payload"], adopt_returns=False)
            elif mtype == "submit_actor_task":
                reply["return_ids"] = self.submit_actor_task(
                    msg["payload"], adopt_returns=False)
            elif mtype == "create_actor":
                reply["actor_id"] = self.create_actor(msg["payload"])
            elif mtype == "get_objects":
                reply["values"] = self._serve_get(
                    handle, msg["oids"], inline=msg.get("inline", False))
            elif mtype == "make_room":
                # a worker's direct shm put hit a full store: spill on its
                # node so the retry can allocate (the raylet-spills-for-
                # plasma-creates path, create_request_queue.h:32)
                self._make_room(handle.node_id, int(msg["bytes"]))
            elif mtype == "put_inline":
                oid = ObjectID.for_put().binary()
                with self._lock:
                    self.memory_store[oid] = msg["data"]
                    fut = _SlimFuture()
                    fut.set_result(True)
                    self.futures[oid] = fut
                    if msg.get("own"):
                        # the worker owns this put like a store put: the
                        # owner-release protocol frees/drops it uniformly
                        self._worker_owned.setdefault(
                            handle.worker_id.binary(), set()).add(oid)
                if self._wal_enabled \
                        and len(msg["data"]) <= self._wal_max:
                    # WAL after _lock released, before the reply hands
                    # the id out (see put_object)
                    self.gcs.wal_put_sealed(oid, msg["data"])
                reply["object_id"] = oid
            elif mtype == "device_put":
                reply["object_id"] = self.reserve_device_put(handle)
            elif mtype == "device_put_sealed":
                self.seal_device_put(msg["object_id"], handle,
                                     size=msg.get("size"),
                                     mesh=msg.get("mesh"))
            elif mtype == "wait":
                ready, not_ready = self.wait(
                    msg["oids"], msg["num_returns"], msg["timeout"]
                )
                reply["ready"] = ready
                reply["not_ready"] = not_ready
            elif mtype == "kill_actor":
                self.kill_actor(msg["actor_id"], msg["no_restart"])
            elif mtype == "cancel_task":
                self.cancel(msg["object_id"], msg["force"])
            elif mtype == "actor_info":
                with self._lock:
                    info = self.actors.get(msg["actor_id"])
                reply["exists"] = info is not None
            elif mtype == "create_pg":
                from .placement_group import _manager

                pg = _manager(self).create(
                    msg["bundles"], msg["strategy"], msg.get("name", ""))
                reply["pg_id"] = pg.id
            elif mtype == "pg_state":
                from .placement_group import _manager

                reply["state"] = _manager(self).state(msg["pg_id"])
            elif mtype == "wait_pg":
                from .placement_group import _manager

                reply["created"] = _manager(self).wait_created(
                    msg["pg_id"], msg["timeout"])
            elif mtype == "remove_pg":
                from .placement_group import _manager

                _manager(self).remove(msg["pg_id"])
            elif mtype == "get_named_actor":
                rec = self.gcs.get_named_actor(msg["name"])
                if rec is None:
                    raise ValueError(f"no actor named {msg['name']!r}")
                reply["actor_id"] = rec.actor_id.binary()
            else:
                raise ValueError(f"unknown worker request {mtype}")
        except Exception as e:  # noqa: BLE001
            try:
                reply = {"type": "reply", "req_id": req_id,
                         "error": ser.dumps(e)}
            except Exception:
                reply = {"type": "reply", "req_id": req_id,
                         "error": ser.dumps(RuntimeError(str(e)))}
        if not self._send(handle, reply):
            self._on_worker_death(handle)

    def _serve_get(self, handle: WorkerHandle, oids: List[bytes],
                   inline: bool = False):
        """Make each object available to the requesting worker: inline bytes
        for memory-store values, or ensure presence in the worker's node store
        (transfer / spill-restore / lineage recovery). With ``inline`` the
        envelope bytes are sent back in the reply even for store objects —
        the worker's last-resort path when its direct shm reads keep losing
        the race against the store's spill tier."""
        values: Dict[bytes, tuple] = {}
        need_ensure: List[bytes] = []
        node_id = handle.node_id
        nm = self.nodes[node_id]
        for oid in dict.fromkeys(oids):
            with self._lock:
                fut = self.futures.get(oid)
            if fut is not None and not fut.done():
                fut.result(timeout=3600)
            with self._lock:
                data = self.memory_store.get(oid)
            if data is not None:
                values[oid] = ("v", data)
                continue
            if inline:
                # inline serve needs NO copy on the worker's (possibly full)
                # node: read the bytes from whatever live node has them
                data = self._inline_bytes_anywhere(oid, prefer=node_id)
                if data is None:
                    self._ensure_device_materialized(oid)
                    data = self._inline_bytes_anywhere(oid, prefer=node_id)
                if data is None:
                    self._recover_object(oid)
                    with self._lock:
                        data = self.memory_store.get(oid)
                    if data is None:
                        data = self._inline_bytes_anywhere(oid,
                                                           prefer=node_id)
                if data is None:
                    raise ObjectLostError(
                        oid.hex(), "could not materialize on worker's node")
                values[oid] = ("v", data)
                continue
            if not nm.store.contains(oid):
                try:
                    self._ensure_device_materialized(oid)
                    locs = [l for l in self.gcs.get_object_locations(oid)
                            if l != node_id and self.nodes.get(l)
                            and self.nodes[l].alive]
                    if locs:
                        self._transfer_from(oid, locs, node_id)
                    elif not nm.store.contains(oid):
                        self._recover_object(oid)
                        # recovery may produce an inline value
                        with self._lock:
                            data = self.memory_store.get(oid)
                        if data is not None:
                            values[oid] = ("v", data)
                            continue
                        if not nm.store.contains(oid):
                            locs = [l for l in
                                    self.gcs.get_object_locations(oid)
                                    if self.nodes.get(l)
                                    and self.nodes[l].alive]
                            if not locs:
                                raise ObjectLostError(oid.hex())
                            self._transfer_from(oid, locs, node_id)
                except (ObjectStoreFullError, ObjectLostError):
                    # the worker's node cannot take a copy right now (store
                    # full past the wait budget): serve the bytes inline
                    # from wherever they are instead of failing the get
                    data = self._inline_bytes_anywhere(oid, prefer=node_id)
                    if data is None:
                        raise
                    values[oid] = ("v", data)
                    continue
            need_ensure.append(oid)
        # answering "local" is a promise the worker's DIRECT shm read will
        # hit: restore-from-spill and pin briefly (the worker's store client
        # is shm-only and cannot see the spill tier). Ensures are BATCHED
        # per node — for a remote node each would otherwise be its own
        # blocking agent round-trip, and a multi-object get against a
        # degraded agent could park this request-pool thread for minutes.
        if need_ensure:
            ensured = self._ensure_resident_batch(nm, need_ensure)
            for oid in need_ensure:
                if ensured.get(oid, True):
                    values[oid] = ("local", b"")
                    continue
                # the node's store is too full to restore (capacity held by
                # executing tasks): serve the bytes inline as a last resort
                # before declaring the object lost
                data = self._inline_bytes_anywhere(oid, prefer=node_id)
                if data is None:
                    raise ObjectLostError(
                        oid.hex(), "could not materialize on worker's node")
                values[oid] = ("v", data)
        return [values[oid] for oid in oids]

    def _ensure_resident_batch(self, nm, oids: List[bytes]) -> Dict[bytes, bool]:
        """Restore-and-pin a set of objects on one node's store; one channel
        round-trip for remote nodes (ensure_resident_many), a plain loop for
        the local store."""
        many = getattr(nm.store, "ensure_resident_many", None)
        if many is not None:
            try:
                return many(oids)
            except Exception:  # noqa: BLE001 — degrade to per-oid inline
                return {oid: False for oid in oids}
        ensure = getattr(nm.store, "ensure_resident", None)
        out = {}
        for oid in oids:
            if ensure is None:
                out[oid] = True
                continue
            try:
                out[oid] = ensure(oid)
            except ObjectStoreFullError:
                out[oid] = False  # transiently full: caller serves inline
        return out

    def _inline_bytes_anywhere(self, oid: bytes,
                               prefer: NodeID) -> Optional[bytes]:
        """Envelope bytes from ANY live node holding the object, trying
        ``prefer`` first — no transfer into (and no allocation on) the
        requesting worker's node."""
        order = [prefer] + [l for l in self.gcs.get_object_locations(oid)
                            if l != prefer]
        for node_id in order:
            nm = self.nodes.get(node_id)
            if nm is None or not nm.alive:
                continue
            data = self._inline_bytes_from_store(nm, oid)
            if data is not None:
                return data
        return None

    def _make_room(self, node_id: NodeID, nbytes: int) -> None:
        """Spill a node's store down so ``nbytes`` can allocate (local
        stores spill directly; remote proxies do one agent round trip)."""
        # deferred zero-ref frees may be pinning exactly the space the
        # caller needs (up to 128 objects of any size): release them
        # before resorting to spilling live objects
        self._flush_deferred_frees()
        nm = self.nodes.get(node_id)
        if nm is None:
            return
        make_room = getattr(nm.store, "make_room", None)
        if make_room is not None and not make_room(nbytes):
            events.emit(
                "STORE_FULL",
                f"could not spill {nbytes} bytes on {node_id.hex()[:8]}",
                severity=events.WARNING, source="object_store")

    def _inline_bytes_from_store(self, nm, oid: bytes) -> Optional[bytes]:
        """Envelope bytes from a node's store without forcing shm residency
        (NodeObjectStore.read serves spilled objects from the spill file;
        the remote proxy's get pulls over the channel, which the agent also
        serves residency-free)."""
        reader = getattr(nm.store, "read", None) or nm.store.get
        view = reader(oid)
        if view is None:
            return None
        data = bytes(view)
        if isinstance(view, memoryview):
            nm.store.release(oid)
        return data

    # ---------------------------------------------------------------- cancel
    def cancel(self, oid: bytes, force: bool = False) -> None:
        """Best-effort cancel of a queued (not yet dispatched) task
        (CoreWorker::CancelTask analog; running tasks are only killed with
        force=True, which terminates the worker)."""
        with self._lock:
            task_id = self.lineage.get(oid)
            if task_id is None:
                return
            self._cancelled.add(task_id)
            rec = self.tasks.get(task_id)
        for nm in self.nodes.values():
            with nm._lock:
                for spec in list(nm.queue):
                    if spec.task_id == task_id:
                        nm.queue.remove(spec)
                        self._fail_task(spec, TaskError(
                            spec.name, None, "cancelled"))
                        return
        if force and rec is not None:
            for nm in self.nodes.values():
                for h in list(nm.workers.values()):
                    if task_id in h.inflight:
                        h.proc.terminate()
                        return

    # -------------------------------------------------------------- shutdown
    def _atexit_shutdown(self) -> None:
        try:
            if not self._stop.is_set():
                self.shutdown()
        except Exception:
            pass

    def shutdown(self) -> None:
        self._stop.set()
        try:
            self.gcs.set_job_state(self.job_id.binary(), "FINISHED")
        except Exception:  # noqa: BLE001
            pass
        if self.gcs.durable:
            try:
                self.gcs.snapshot_directory()  # final directory snapshot
            except Exception:  # noqa: BLE001
                pass
        try:
            # detach this cluster's LogStore so later emits in this
            # process buffer for the NEXT cluster instead of landing in
            # a dead store
            from ..utils import structlog as _structlog

            _structlog.attach_store(None)
        except Exception:  # noqa: BLE001
            pass
        try:
            # same for the ProfileStore; the continuous sampler stops
            # with the cluster (a later init restarts it)
            from ..utils import profiler as _profiler

            _profiler.stop_sampler()
            _profiler.attach_store(None)
        except Exception:  # noqa: BLE001
            pass
        try:
            # a config-installed fault plane is scoped to THIS cluster:
            # drop it and its env exports so a later init (or any child
            # spawned after) doesn't inherit the chaos
            from ..utils import faults

            faults.deconfigure()
        except Exception:  # noqa: BLE001
            pass
        self._sender_pool.stop()
        self._wakeup()
        with self._send_cond:
            channels = list(self._send_channels.values())
            self._send_channels.clear()
        for chan in channels:  # retire per-conn sender threads
            with chan.cond:
                chan.dead = True
                chan.cond.notify_all()
        if self._memory_monitor is not None:
            self._memory_monitor.stop()
        if self._node_listener is not None:
            try:
                self._node_listener.close()
            except OSError:
                pass
        try:
            self._listener.close()
        except OSError:
            pass
        self._accept_thread.join(timeout=1.0)
        try:
            os.unlink(self._socket_path)
        except OSError:
            pass
        self._router.join(timeout=2.0)
        self._hb.join(timeout=2.0)
        self._request_pool.shutdown(wait=False, cancel_futures=True)
        self._transfer_pool.shutdown(wait=False, cancel_futures=True)
        # fail every unresolved object future: a pool thread parked in
        # fut.result() with no timeout (a worker's blocking get) would
        # otherwise never wake — and concurrent.futures' atexit hook joins
        # every worker thread ever created, so one sleeper wedges
        # interpreter exit after the last test finishes. Runs AFTER the
        # router/pools stop and LOOPS: a woken pool thread can still
        # insert one more future before it observes _stop (dep callbacks
        # are _stop-guarded, so nothing resubmits work).
        for _ in range(20):
            with self._lock:
                pending_futs = [f for f in self.futures.values()
                                if not f.done()]
            if not pending_futs:
                break
            for f in pending_futs:
                try:
                    f.set_exception(RuntimeError("runtime shut down"))
                except Exception:  # noqa: BLE001
                    pass
            _SlimFuture.broadcast()
            time.sleep(0.05)
        try:
            self._xfer_conn_pool.close()
        except Exception:
            pass
        for srv in self._xfer_servers.values():
            try:
                srv.close()
            except Exception:
                pass
        for nm in self.nodes.values():
            try:
                nm.shutdown(unlink_store=True)
            except Exception:
                pass
        from . import zygote as _zygote

        _zygote.shutdown_global()
        for cli in self._store_clients.values():
            if isinstance(cli, StoreClient):
                try:
                    cli.close()
                except Exception:
                    pass
        for proc in self._agent_procs:
            try:
                proc.wait(timeout=3.0)
            except Exception:
                try:
                    proc.terminate()
                except Exception:
                    pass
        # a SIGKILLed agent (chaos, preemption) cannot unlink its shm
        # store; reclaim any same-host segment whose owning pid is gone
        try:
            from ..native import reap_stale_stores

            reap_stale_stores("rmtA_")
        except Exception:
            pass
        with self._lock:
            self.memory_store.clear()
        try:
            self.gcs.storage.close()
        except Exception:
            pass
        try:
            os.close(self._wakeup_r)
            os.close(self._wakeup_w)
        except OSError:
            pass
        _worker_context.set_runtime(None)
