"""``protocol-additivity`` — transfer wire protocol v2 may only evolve
by ADDING keys.

The checker extracts every request/reply header key that
``core/transfer.py`` actually sends or reads, and compares the observed
sets against the generated registry ``analysis/protocol_schema.py``:

  * a schema key that no longer appears in the code is a REMOVAL or
    RENAME -> violation (old peers still send/expect it across a rolling
    upgrade — the v2 negotiation in PR 6/7 only works because unknown
    keys are ignored and known keys never change meaning);
  * an observed key missing from the schema is an ADDITION: by default
    it auto-registers (protocol_schema.py is regenerated, the diff is
    recorded in ``options["schema_diff"]`` for the CLI to print); in
    ``frozen`` mode (tier-1 CI) it is a violation, forcing the schema
    diff into the same commit as the protocol change.

Key extraction (core/transfer.py only):

  * dict literals containing ``"proto"`` are request headers; dict
    literals containing ``"size"``/``"error"``/``"deferred"`` are reply
    headers — their string keys are observed;
  * subscript writes/reads and ``.get("k")`` on variables named
    ``req``/``first_req`` (request side) or ``reply``/``hdr`` (reply
    side) are observed.

A third side covers the observability piggyback frames (the profiling
plane rides them): in ``core/worker.py`` and ``core/node_agent.py``,
dict literals whose ``"type"`` is ``"profile"`` (the worker's flush
frame) or ``"pong"`` (the agent's keepalive reply) plus subscript
writes/``get`` reads on variables named ``frame``/``pong`` observe the
``FRAME_KEYS`` set — same additive-only contract: the head ignores
unknown frame keys, so adding one is safe across a rolling upgrade and
removing one strands data old peers still send.
"""

from __future__ import annotations

import ast
import os
from typing import List, Set, Tuple

from .engine import Project, Violation, dict_literal_keys, const_str, \
    register

_TRANSFER_SUFFIX = "core/transfer.py"
_SCHEMA_SUFFIX = "analysis/protocol_schema.py"
_REQUEST_VARS = {"req", "first_req", "request"}
_REPLY_VARS = {"reply", "hdr", "header", "resp"}
_REPLY_MARKERS = {"size", "error", "deferred"}
# observability piggyback frames: worker flush frame + agent pong
_FRAME_SUFFIXES = ("core/worker.py", "core/node_agent.py")
_FRAME_VARS = {"frame", "pong"}
_FRAME_TYPES = {"profile", "pong"}


def observed_keys(project: Project) -> Tuple[Set[str], Set[str]]:
    """(request_keys, reply_keys) actually used by core/transfer.py."""
    req: Set[str] = set()
    rep: Set[str] = set()
    sf = project.get(_TRANSFER_SUFFIX)
    if sf is None or sf.tree is None:
        return req, rep
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Dict):
            keys = set(dict_literal_keys(node))
            if "proto" in keys:
                req |= keys
            elif keys & _REPLY_MARKERS:
                rep |= keys
        elif isinstance(node, ast.Subscript) and \
                isinstance(node.value, ast.Name):
            key = const_str(node.slice)
            if key is None:
                continue
            if node.value.id in _REQUEST_VARS:
                req.add(key)
            elif node.value.id in _REPLY_VARS:
                rep.add(key)
        elif isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr == "get" and node.args and \
                isinstance(node.func.value, ast.Name):
            key = const_str(node.args[0])
            if key is None:
                continue
            if node.func.value.id in _REQUEST_VARS:
                req.add(key)
            elif node.func.value.id in _REPLY_VARS:
                rep.add(key)
    return req, rep


def observed_frame_keys(project: Project) -> Set[str]:
    """Frame keys actually sent by core/worker.py + core/node_agent.py."""
    frame: Set[str] = set()
    for suffix in _FRAME_SUFFIXES:
        sf = project.get(suffix)
        if sf is None or sf.tree is None:
            continue
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Dict):
                keys = set(dict_literal_keys(node))
                if "type" not in keys:
                    continue
                for k, v in zip(node.keys, node.values):
                    if const_str(k) == "type" and \
                            const_str(v) in _FRAME_TYPES:
                        frame |= keys
                        break
            elif isinstance(node, ast.Subscript) and \
                    isinstance(node.value, ast.Name) and \
                    node.value.id in _FRAME_VARS:
                key = const_str(node.slice)
                if key is not None:
                    frame.add(key)
            elif isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "get" and node.args and \
                    isinstance(node.func.value, ast.Name) and \
                    node.func.value.id in _FRAME_VARS:
                key = const_str(node.args[0])
                if key is not None:
                    frame.add(key)
    return frame


def schema_keys(project: Project
                ) -> Tuple[Set[str], Set[str], Set[str], str]:
    """(request_keys, reply_keys, frame_keys, path) from
    protocol_schema.py."""
    sf = project.get(_SCHEMA_SUFFIX)
    req: Set[str] = set()
    rep: Set[str] = set()
    frame: Set[str] = set()
    if sf is None or sf.tree is None:
        return req, rep, frame, ""
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                isinstance(node.value, (ast.Tuple, ast.List)):
            vals = {s for s in (const_str(e) for e in node.value.elts)
                    if s is not None}
            if node.targets[0].id == "REQUEST_KEYS":
                req = vals
            elif node.targets[0].id == "REPLY_KEYS":
                rep = vals
            elif node.targets[0].id == "FRAME_KEYS":
                frame = vals
    return req, rep, frame, sf.path


_HEADER = '''"""Generated wire-protocol v2 key registry — do not hand-edit key sets.

``rmt check`` (rule ``protocol-additivity``) regenerates this file when
core/transfer.py starts sending a NEW request/reply key (additive
evolution, the diff is printed), and FAILS when a key listed here stops
appearing in the code: removing or renaming a wire key breaks rolling
upgrades where old peers still send/expect it. In ``--frozen`` mode
(CI / tests/test_static_analysis.py) additions fail too, so the schema
diff lands in the same commit as the protocol change.
"""
'''


def _regenerate(path: str, req: Set[str], rep: Set[str],
                frame: Set[str]) -> None:
    def block(name: str, comment: str, keys: Set[str]) -> str:
        lines = [f"# {comment}", f"{name} = ("]
        lines += [f"    \"{k}\"," for k in sorted(keys)]
        lines.append(")")
        return "\n".join(lines)

    text = (_HEADER + "\n"
            + block("REQUEST_KEYS",
                    "v2 fetch request: client -> server header dict",
                    req)
            + "\n\n"
            + block("REPLY_KEYS",
                    "v2 fetch reply: server -> client header dict", rep)
            + "\n\n"
            + block("FRAME_KEYS",
                    "observability piggyback frames: worker flush frame "
                    "+ agent pong", frame)
            + "\n")
    with open(path, "w", encoding="utf-8") as f:
        f.write(text)


@register("protocol-additivity")
def check_protocol_additivity(project: Project, options: dict
                              ) -> List[Violation]:
    out: List[Violation] = []
    obs_req, obs_rep = observed_keys(project)
    obs_frame = observed_frame_keys(project)
    sch_req, sch_rep, sch_frame, schema_path = schema_keys(project)
    if not schema_path:
        out.append(Violation(
            "protocol-additivity", _SCHEMA_SUFFIX, 1,
            "analysis/protocol_schema.py missing or unparseable"))
        return out
    if not obs_req and not obs_rep and not obs_frame:
        # sender files absent (e.g. fixture-only project): nothing to do
        return out
    schema_rel = os.path.relpath(schema_path, project.repo_root)

    # a side only votes when its sender file(s) are present and emit
    # keys — a fixture project without transfer.py must not see its
    # whole REQUEST_KEYS registry as "removed"
    sides: List[Tuple[str, Set[str], Set[str], str, str]] = []
    if obs_req or obs_rep:
        transfer_rel = project.get(_TRANSFER_SUFFIX).rel
        sides.append(("request", sch_req, obs_req, transfer_rel,
                      "transfer.py"))
        sides.append(("reply", sch_rep, obs_rep, transfer_rel,
                      "transfer.py"))
    if obs_frame:
        frame_sf = next((project.get(s) for s in _FRAME_SUFFIXES
                         if project.get(s) is not None), None)
        frame_rel = frame_sf.rel if frame_sf else _FRAME_SUFFIXES[0]
        sides.append(("frame", sch_frame, obs_frame, frame_rel,
                      "worker.py/node_agent.py"))

    for side, sch, obs, sender_rel, sender in sides:
        for key in sorted(sch - obs):
            out.append(Violation(
                "protocol-additivity", sender_rel, 1,
                f"wire {side} key {key!r} is registered in "
                f"protocol_schema.py but no longer sent/read by "
                f"{sender} — removing or renaming a v2 key breaks "
                f"rolling upgrades (additive-only protocol)"))
        added = sorted(obs - sch)
        if not added:
            continue
        if options.get("frozen"):
            for key in added:
                out.append(Violation(
                    "protocol-additivity", schema_rel, 1,
                    f"new wire {side} key {key!r} is not registered in "
                    f"protocol_schema.py — run `rmt check` to "
                    f"auto-register it and commit the schema diff"))
        else:
            options.setdefault("schema_diff", []).extend(
                f"+ {side} key {key!r}" for key in added)

    if not options.get("frozen") and \
            any(obs - sch for _, sch, obs, _, _ in sides):
        _regenerate(schema_path, sch_req | obs_req, sch_rep | obs_rep,
                    sch_frame | obs_frame)
    return out
