# rmtcheck: disable-file=log-discipline -- main() is the CLI report
# renderer for `rmt check --perf` (same stdout surface as scripts/)
"""``rmt check --perf`` — the perf-regression gate (ROADMAP item 4).

Unlike its AST-rule siblings this checker diffs DATA: the headline JSON
that bench.py prints as its last stdout line and that every recorded
round archives in ``BENCH_r<N>.json`` (``{"n", "cmd", "rc", "tail"}``,
the headline being the tail's final line). The gate compares the round
under test (default: the newest round whose tail still parses — round 4
famously outgrew its tail window and is skipped, not failed) against a
baseline (default: the newest parseable round strictly older), field by
field with per-field tolerance bands:

- throughput-like fields (geomean, GB/s, tasks/s, MFU) regress when the
  new value drops more than the band below the old one — the bands are
  deliberately loose (25-40%) because rounds run on whatever hardware
  the session got, and the gate must flag real cliffs, not host noise;
- overhead-percent fields (tracing/logging/profile ≤5% contracts)
  regress when the new value EXCEEDS the old by more than an absolute
  slack in percentage points.

Only fields present and numeric in BOTH headlines are compared — a
round that predates a suite simply doesn't vote on it. Output is one
``field: old -> new (-N%)`` line per regression and exit 1, or a
one-line OK; ``--json`` emits the full machine-readable diff.
"""

from __future__ import annotations

import glob
import json
import os
import re
from typing import Any, Dict, List, Optional, Tuple

# (dotted field, kind, tolerance). kind "up" = higher is better, the
# tolerance is the allowed fractional drop; kind "down" = lower is
# better (overhead %), the tolerance is allowed absolute increase.
FIELD_SPECS: Tuple[Tuple[str, str, float], ...] = (
    ("vs_baseline", "up", 0.25),
    ("hw.memcpy_gbps", "up", 0.30),
    ("hw.put_vs_memcpy_ceiling", "up", 0.30),
    ("micro.single_client_tasks_sync", "up", 0.35),
    ("micro.single_client_tasks_async", "up", 0.35),
    ("micro.single_client_put_gigabytes", "up", 0.35),
    ("scale.many_tasks_per_s", "up", 0.35),
    ("scale.many_actors_per_s", "up", 0.40),
    ("scale.many_pgs_per_s", "up", 0.40),
    ("scale.broadcast_gbps", "up", 0.40),
    ("scale.cross_node_gbps", "up", 0.40),
    # decentralized-control-plane curve (ISSUE 15): per-node-count task
    # throughput and the 1->4 virtual-node scaling factor must not
    # quietly sink back toward the single-core plateau
    ("scale_curve.tasks_per_s.1", "up", 0.35),
    ("scale_curve.tasks_per_s.4", "up", 0.35),
    ("scale_curve.tasks_scaling_1_to_4", "up", 0.25),
    # pod-scale control plane (ISSUE 19): task throughput at the
    # smallest and largest SIM membership must not collapse; the
    # directory-op tail, head RSS at 256 nodes, and the row flood's
    # RSS bound get absolute slack (us / MB of creep over baseline)
    ("pod_curve.tasks_per_s_8", "up", 0.40),
    ("pod_curve.tasks_per_s_256", "up", 0.45),
    ("pod_curve.dir_p99_us_256", "down", 800.0),
    ("pod_curve.head_rss_mb_256", "down", 768.0),
    ("pod_curve.rows_rss_mb", "down", 768.0),
    ("tpu.train_tokens_per_s", "up", 0.35),
    ("tpu.train_mfu", "up", 0.35),
    # serving data plane (ISSUE 17): tail latency must not creep, the
    # paged-KV capacity win and per-chip decode rate must not erode
    ("serve.p99_ms", "down", 200.0),
    ("serve.tokens_per_s_per_chip", "up", 0.40),
    ("serve.paged_slots_ratio", "up", 0.25),
    ("serve.continuous_vs_barrier", "up", 0.30),
    # multi-tenant job plane (ISSUE 18): the quota/attribution machinery
    # must not tax the submit hot path (overhead is a percentage, so the
    # band is absolute points), sweeps must stay milliseconds-fast, and
    # the churn soak's aggregate rate must not collapse
    ("jobs.isolation_overhead_pct", "down", 10.0),
    ("jobs.churn_tasks_per_s", "up", 0.40),
    ("jobs.sweep_ms_1000", "down", 50.0),
    ("tracing.overhead_pct", "down", 4.0),
    ("logging.overhead_pct", "down", 4.0),
    ("profile.overhead_pct", "down", 4.0),
    ("health.overhead_pct", "down", 4.0),
)

_ROUND_RE = re.compile(r"BENCH_r(\d+)\.json$")


def parse_headline(path: str) -> Optional[Dict[str, Any]]:
    """The headline dict archived in one BENCH_r*.json, or None when the
    tail's last line doesn't parse (truncated tail window, crashed run)."""
    try:
        with open(path, "r", encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    tail = (doc.get("tail") or "").strip()
    if not tail:
        return None
    try:
        headline = json.loads(tail.splitlines()[-1])
    except ValueError:
        return None
    return headline if isinstance(headline, dict) else None


def discover_rounds(root: str) -> List[Tuple[int, str]]:
    """(round_number, path) for every BENCH_r*.json under root, sorted
    oldest-first."""
    out = []
    for path in glob.glob(os.path.join(root, "BENCH_r*.json")):
        m = _ROUND_RE.search(os.path.basename(path))
        if m:
            out.append((int(m.group(1)), path))
    out.sort()
    return out


def _resolve_round(selector: str, rounds: List[Tuple[int, str]]
                   ) -> Optional[str]:
    """Accepts '5', 'r05', 'BENCH_r05.json' or a path."""
    if os.path.sep in selector or os.path.isfile(selector):
        return selector
    m = re.search(r"(\d+)", selector)
    if not m:
        return None
    want = int(m.group(1))
    for n, path in rounds:
        if n == want:
            return path
    return None


def _field(headline: Dict[str, Any], dotted: str) -> Optional[float]:
    node: Any = headline
    for part in dotted.split("."):
        if not isinstance(node, dict) or part not in node:
            return None
        node = node[part]
    if isinstance(node, bool) or not isinstance(node, (int, float)):
        return None
    return float(node)


def compare(baseline: Dict[str, Any], current: Dict[str, Any]
            ) -> List[Dict[str, Any]]:
    """Field-by-field diff rows; ``regression`` marks tolerance breaks."""
    rows: List[Dict[str, Any]] = []
    for dotted, kind, tol in FIELD_SPECS:
        old = _field(baseline, dotted)
        new = _field(current, dotted)
        if old is None or new is None:
            continue
        if kind == "up":
            delta_pct = (new - old) / old * 100.0 if old else 0.0
            regression = old > 0 and new < old * (1.0 - tol)
            tolerance_pct = tol * 100.0
        else:  # "down": overhead percentage points, absolute slack
            delta_pct = new - old
            regression = new > old + tol
            tolerance_pct = tol
        rows.append({
            "field": dotted, "kind": kind,
            "old": old, "new": new,
            "delta_pct": round(delta_pct, 2),
            "tolerance_pct": tolerance_pct,
            "regression": regression,
        })
    return rows


def run_gate(root: Optional[str] = None,
             baseline: Optional[str] = None,
             current: Optional[str] = None) -> Dict[str, Any]:
    """The gate as data: {"ok", "baseline", "current", "fields",
    "skipped", "note"} — main() renders it."""
    if root is None:
        # analysis/ -> package -> repo root
        root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
    rounds = discover_rounds(root)
    parseable: List[Tuple[int, str, Dict[str, Any]]] = []
    skipped: List[str] = []
    for n, path in rounds:
        headline = parse_headline(path)
        if headline is None:
            skipped.append(os.path.basename(path))
        else:
            parseable.append((n, path, headline))

    def _pick(selector: Optional[str], default_idx: int
              ) -> Optional[Tuple[str, Dict[str, Any]]]:
        if selector is not None:
            path = _resolve_round(selector, rounds)
            if path is None:
                return None
            headline = parse_headline(path)
            if headline is None:
                return None
            return (os.path.basename(path), headline)
        if not parseable:
            return None
        n, path, headline = parseable[default_idx]
        return (os.path.basename(path), headline)

    cur = _pick(current, -1)
    if cur is None:
        return {"ok": True, "baseline": None, "current": current,
                "fields": [], "skipped": skipped,
                "note": "no parseable round under test — nothing to gate"}
    if baseline is not None:
        base = _pick(baseline, 0)
        if base is None:
            return {"ok": False, "baseline": baseline,
                    "current": cur[0], "fields": [], "skipped": skipped,
                    "note": f"baseline {baseline!r} not found or "
                            "unparseable"}
    else:
        # newest parseable round strictly older than the current one
        older = [(n, p, h) for n, p, h in parseable
                 if os.path.basename(p) != cur[0]
                 and _round_no(p) < _round_no(cur[0])]
        if older:
            n, path, headline = older[-1]
            base = (os.path.basename(path), headline)
        else:
            base = cur  # first recorded round: gate trivially passes
    fields = compare(base[1], cur[1])
    ok = not any(r["regression"] for r in fields)
    return {"ok": ok, "baseline": base[0], "current": cur[0],
            "fields": fields, "skipped": skipped, "note": None}


def _round_no(name: str) -> int:
    m = _ROUND_RE.search(os.path.basename(name))
    return int(m.group(1)) if m else -1


def main(root: Optional[str] = None, baseline: Optional[str] = None,
         current: Optional[str] = None, as_json: bool = False) -> int:
    result = run_gate(root=root, baseline=baseline, current=current)
    if as_json:
        print(json.dumps(result, indent=2))
        return 0 if result["ok"] else 1
    if result.get("note"):
        print(f"perf gate: {result['note']}")
    for name in result["skipped"]:
        print(f"perf gate: skipping {name} (headline unparseable)")
    regressions = [r for r in result["fields"] if r["regression"]]
    for r in regressions:
        sign = "" if r["delta_pct"] >= 0 else "-"
        mag = abs(r["delta_pct"])
        unit = "%" if r["kind"] == "up" else "pp"
        print(f"{r['field']}: {r['old']:g} -> {r['new']:g} "
              f"({sign}{mag:g}{unit}, tolerance "
              f"{r['tolerance_pct']:g}{unit})")
    if result["ok"]:
        if result["baseline"]:
            print(f"perf gate OK: {result['current']} vs "
                  f"{result['baseline']}, {len(result['fields'])} "
                  "fields within tolerance")
        return 0
    print(f"perf gate FAILED: {len(regressions)} field(s) regressed "
          f"past tolerance ({result['current']} vs {result['baseline']})")
    return 1
