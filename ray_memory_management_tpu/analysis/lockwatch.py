"""Opt-in runtime lock-order / blocking-call detector (``RMT_LOCK_CHECK=1``).

A ``threading.settrace``-free complement to the static checkers: static
analysis sees lexical ``with`` nesting, but lock-ORDER inversions only
exist across threads at runtime (thread A takes L1 then L2, thread B
takes L2 then L1 — each order is locally fine, together they deadlock).

Mechanism: ``install()`` monkeypatches ``threading.Lock`` /
``threading.RLock`` with a factory that wraps locks CREATED from package
code (creation frame filtered by filename; frames inside the
``threading`` module are skipped so a ``Condition()``'s internal RLock
is attributed to the real caller). Each wrapper records, per thread, the
stack of held lock SITES (``file:line`` of creation — site-keyed, so
10k per-connection locks from one constructor collapse into one graph
node). On every acquire, an edge ``held-site -> new-site`` is added to a
global order graph; ``report()`` runs Tarjan SCC over it and returns the
inversion cycles. ``time.sleep`` is also wrapped: sleeping while holding
any watched lock is recorded as a blocking-under-lock event (the runtime
twin of the static ``blocking-under-lock`` rule).

Overhead budget (soaks assert <= 5%): the hot path is one thread-local
list append plus a lock-free ``(a, b) in edges`` membership test —
the bookkeeping mutex is only taken for a NEW edge, which happens
O(distinct-pairs) times, not O(acquisitions).

Condition-variable compatibility: the wrapper ``__getattr__``-delegates
everything else (``_release_save`` / ``_acquire_restore`` /
``_is_owned``) to the inner lock, so ``Condition(wrapped_lock).wait()``
releases the INNER lock directly. The held stack deliberately keeps its
entry across the wait: the thread is parked and acquires nothing, and
the reacquire on wakeup restores the real state the stack describes.
"""

from __future__ import annotations

import contextlib
import os
import sys
import threading
import time
from typing import Dict, List, Optional, Set, Tuple

_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock
_REAL_SLEEP = time.sleep

_PKG_MARKER = "ray_memory_management_tpu"
_SELF_FILE = os.path.abspath(__file__)
# path substrings whose frames count as "ours" for lock creation; tests
# extend this via install(markers=...) to watch locks they create
_markers: Tuple[str, ...] = (_PKG_MARKER,)

# all state guarded by _mu (a REAL lock: never wrapped, never in the graph)
_mu = _REAL_LOCK()
_edges: Set[Tuple[str, str]] = set()
_edge_examples: Dict[Tuple[str, str], str] = {}   # edge -> thread name
_blocking: List[dict] = []
_locks_watched = 0
_acquisitions = 0
_installed = False

_tls = threading.local()


def _held_stack() -> List[Tuple[str, int]]:
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


def _creation_site() -> Optional[str]:
    """file:line of the package frame creating a lock, or None when the
    lock belongs to foreign code (stdlib, test harness internals)."""
    f = sys._getframe(2)
    while f is not None:
        fn = f.f_code.co_filename
        mod = f.f_globals.get("__name__", "")
        if mod == "threading" or mod.startswith("threading.") or \
                os.path.abspath(fn) == _SELF_FILE:
            f = f.f_back
            continue
        for marker in _markers:
            if marker in fn:
                rel = fn.split(marker, 1)[-1].lstrip(os.sep + "/")
                return f"{os.path.basename(marker)}/{rel}:{f.f_lineno}"
        return None
    return None


class _WatchedLock:
    """Wraps one Lock/RLock; tracks held-site order per thread."""

    __slots__ = ("_inner", "_site")

    def __init__(self, inner, site: str):
        self._inner = inner
        self._site = site

    def acquire(self, blocking: bool = True, timeout: float = -1):
        got = self._inner.acquire(blocking, timeout)
        if got:
            self._record_acquire()
        return got

    def _record_acquire(self) -> None:
        global _acquisitions
        stack = _held_stack()
        me = id(self._inner)
        for held_site, held_id in stack:
            if held_site == self._site or held_id == me:
                continue  # reentrant / same creation site: not an order
            edge = (held_site, self._site)
            if edge not in _edges:       # lock-free fast path
                with _mu:
                    if edge not in _edges:
                        _edges.add(edge)
                        _edge_examples[edge] = \
                            threading.current_thread().name
        stack.append((self._site, me))
        _acquisitions += 1               # GIL-atomic, diagnostic only

    def release(self) -> None:
        self._inner.release()
        stack = _held_stack()
        me = id(self._inner)
        for i in range(len(stack) - 1, -1, -1):
            if stack[i][1] == me:
                del stack[i]
                break

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def __getattr__(self, name):
        # Condition internals (_release_save/_acquire_restore/_is_owned)
        # and anything else go straight to the real lock
        return getattr(self._inner, name)

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"<WatchedLock {self._site} of {self._inner!r}>"


def _make_factory(real_ctor):
    def factory(*args, **kwargs):
        global _locks_watched
        inner = real_ctor(*args, **kwargs)
        site = _creation_site()
        if site is None:
            return inner
        _locks_watched += 1
        return _WatchedLock(inner, site)
    return factory


def _watched_sleep(seconds):
    stack = getattr(_tls, "stack", None)
    if stack:
        with _mu:
            _blocking.append({
                "call": "time.sleep",
                "seconds": seconds,
                "held": [s for s, _ in stack],
                "thread": threading.current_thread().name,
            })
    return _REAL_SLEEP(seconds)


def install(markers=None) -> None:
    """Patch threading.Lock/RLock + time.sleep. Idempotent. Must run
    BEFORE the runtime creates its locks (the package __init__ calls
    maybe_install_from_env() for exactly this reason). ``markers``:
    extra path substrings whose frames count as package code (tests use
    this to watch locks they create themselves)."""
    global _installed, _markers
    if markers:
        _markers = (_PKG_MARKER,) + tuple(markers)
    if _installed:
        return
    threading.Lock = _make_factory(_REAL_LOCK)
    threading.RLock = _make_factory(_REAL_RLOCK)
    time.sleep = _watched_sleep
    _installed = True


def uninstall() -> None:
    global _installed, _markers
    threading.Lock = _REAL_LOCK
    threading.RLock = _REAL_RLOCK
    time.sleep = _REAL_SLEEP
    _markers = (_PKG_MARKER,)
    _installed = False


def reset() -> None:
    global _locks_watched, _acquisitions
    with _mu:
        _edges.clear()
        _edge_examples.clear()
        del _blocking[:]
    _locks_watched = 0
    _acquisitions = 0


def installed() -> bool:
    return _installed


def _scc_cycles(edges: Set[Tuple[str, str]]) -> List[List[str]]:
    """Tarjan SCC; returns components of size > 1 plus self-loops —
    i.e. the lock-order-inversion cycles."""
    adj: Dict[str, List[str]] = {}
    nodes: Set[str] = set()
    for a, b in edges:
        adj.setdefault(a, []).append(b)
        nodes.add(a)
        nodes.add(b)
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    onstack: Set[str] = set()
    stack: List[str] = []
    out: List[List[str]] = []
    counter = [0]

    def strongconnect(v: str) -> None:
        # iterative Tarjan (soak graphs are small but recursion limits
        # are not ours to burn)
        work = [(v, iter(adj.get(v, ())))]
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        onstack.add(v)
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    onstack.add(w)
                    work.append((w, iter(adj.get(w, ()))))
                    advanced = True
                    break
                elif w in onstack:
                    low[node] = min(low[node], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                comp = []
                while True:
                    w = stack.pop()
                    onstack.discard(w)
                    comp.append(w)
                    if w == node:
                        break
                if len(comp) > 1 or (node, node) in edges:
                    out.append(sorted(comp))

    for n in sorted(nodes):
        if n not in index:
            strongconnect(n)
    return out


def report() -> dict:
    """{"cycles": [[site,...]], "edges": n, "blocking_under_lock": [...],
    "locks_watched": n, "acquisitions": n}. A non-empty ``cycles`` means
    two threads take the same pair of locks in opposite orders."""
    with _mu:
        edges = set(_edges)
        blocking = list(_blocking)
    return {
        "cycles": _scc_cycles(edges),
        "edges": sorted(f"{a} -> {b}" for a, b in edges),
        "blocking_under_lock": blocking,
        "locks_watched": _locks_watched,
        "acquisitions": _acquisitions,
    }


@contextlib.contextmanager
def watching(markers=None):
    """Install + reset, yield the module (call ``report()`` inside),
    uninstall on exit. The soak-test entry point."""
    install(markers=markers)
    reset()
    try:
        yield sys.modules[__name__]
    finally:
        uninstall()


def maybe_install_from_env() -> bool:
    """Install when RMT_LOCK_CHECK=1 — called from the package __init__
    so patching precedes every lock the runtime creates."""
    if os.environ.get("RMT_LOCK_CHECK", "") == "1":
        install()
        return True
    return False
